"""Group-by counts via dictionary codes + device segment reduction.

The reference shuffles rows for ``GROUP BY`` (GroupingAnalyzers.scala:66-78).
The TPU-native design avoids a shuffle entirely: every column is already
dictionary-encoded, so a group key is a mixed-radix packing of per-column
codes and the frequency table is one ``segment_sum`` of ones — a single
device pass, with ``psum`` merging per-device count vectors across the mesh
(this IS the monoid merge of the frequency state).

For pathological key-space sizes (product of per-column cardinalities too
large to materialize as a dense count vector) we fall back to host
``np.unique`` over the packed keys, which is the sparse equivalent.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deequ_tpu.data.table import Column, ColumnarTable, DType
from deequ_tpu.ops.scan_engine import SCAN_STATS
from deequ_tpu.parallel.mesh import ROW_AXIS, current_mesh, shard_map

# dense device count vectors are used up to this key-space size
DENSE_KEYSPACE_LIMIT = 1 << 22

# below this row count the single-phase fetch (O(n) bytes) is cheaper than
# the extra device round trip the two-phase O(G) fetch path pays
SMALL_N_FETCH_LIMIT = 1 << 16

# below this row count grouping work runs entirely on HOST: a tiny input's
# device pass costs a dispatch+fetch round trip (~0.1s on the tunnel, and
# still dominated by launch latency on a local chip) for microseconds of
# host work — the latency-dominated regime of BASELINE config 1.
# Promoted to a sweepable knob in round 14: the kernel A/B probe sweeps
# DEEQU_TPU_HOST_GROUP_LIMIT to measure the crossover on its own
# hardware; this constant is the unset-knob default (tests monkeypatch
# it directly, which the helper below honors)
HOST_GROUP_LIMIT = 1 << 14


def host_group_limit() -> int:
    """The effective host-fallback row threshold: the registered
    DEEQU_TPU_HOST_GROUP_LIMIT knob when set, else the module default
    (``HOST_GROUP_LIMIT`` — still a plain module attribute so existing
    monkeypatch-based tests keep steering the un-swept default)."""
    from deequ_tpu.envcfg import env_value

    value = env_value("DEEQU_TPU_HOST_GROUP_LIMIT")
    return HOST_GROUP_LIMIT if value is None else value


def _pad_group_count(g: int) -> int:
    """Static gather size for a data-dependent group count: next power of
    two (>= 64) so jit programs are shared across nearby G and the fetched
    bytes stay within 2x of the exact O(G) bound."""
    size = 64
    while size < g:
        size <<= 1
    return size


def _record_fetch(*arrays) -> None:
    # one logical device->host materialization (the arrays come back in
    # one round trip at each call site)
    SCAN_STATS.record_fetch(sum(int(a.size) * a.itemsize for a in arrays))


@jax.jit
def _unique_inverse_kernel(v, m):
    """Module-level jitted body (a nested closure would retrace per call)."""
    # primary key: validity (valid rows first), then NaN-ness (all NaNs
    # group together), then the value
    is_nan = v != v
    perm = jnp.lexsort((v, is_nan, ~m))
    sv = v[perm]
    sm = m[perm]
    snan = is_nan[perm]
    neq = (sv[1:] != sv[:-1]) & ~(snan[1:] & snan[:-1])
    neq = jnp.concatenate([jnp.array([True]), neq])
    starts = neq & sm  # a new distinct value, among valid rows only
    ids = jnp.cumsum(starts.astype(jnp.int64))
    codes_sorted = jnp.where(sm, ids, 0)
    inv = jnp.zeros_like(ids).at[perm].set(codes_sorted)
    return sv, starts, inv, ids[-1]  # ids[-1] == number of distinct values


@partial(jax.jit, static_argnames=("size",))
def _gather_at_starts_kernel(sv, starts, size):
    positions = jnp.nonzero(starts, size=size, fill_value=0)[0]
    return sv[positions]


def _device_unique_inverse(
    values: np.ndarray, mask: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Sort-based unique on DEVICE (the shuffle-sort of SURVEY §2.14.2):
    one lexsort puts valid values in order, adjacent-compare marks group
    starts, a cumsum assigns dense ids, and a scatter maps them back to row
    order. NaN values (possible when a caller builds columns with explicit
    masks) collapse into ONE distinct group, matching np.unique's
    equal_nan semantics. Returns (uniques, codes) with codes 0 = null,
    1..K = distinct.

    Fetch discipline: the row codes (O(n)) must come to host — they feed
    the host-side key packing — but the distinct values are gathered at
    group starts ON DEVICE so only O(U) values are fetched (plus one
    scalar round trip for U), not the full sorted column. Small inputs
    keep the single-phase fetch (the extra round trip would dominate)."""
    n = len(values)
    if n == 0:
        return np.empty(0, dtype=values.dtype), np.zeros(0, dtype=np.int64)
    if n <= host_group_limit() and values.dtype != np.float64:
        # latency-dominated regime: a tiny input's device sort costs one
        # dispatch+fetch round trip (~0.1s on the tunnel) for microseconds
        # of work — run the identical unique/inverse on host. FRACTIONAL
        # columns stay on the device path at EVERY size: the axon
        # backend's f64 emulation decodes values a few ulps off the
        # host's bit-exact ones, so a size-dependent path choice would
        # make the same value produce two different group keys across
        # batch sizes (review catch) — consistency beats latency there.
        vals = values[mask]
        uniques = np.unique(vals)
        codes = np.zeros(n, dtype=np.int64)
        if len(uniques):
            codes[mask] = np.searchsorted(uniques, vals) + 1
        return uniques, codes
    SCAN_STATS.device_sort_passes += 1
    if values.dtype != np.float64:
        # integer/bool columns have no NaN; the kernel's v != v is all-False
        values = np.asarray(values)
    sv_dev, starts_dev, inv_dev, nu_dev = _unique_inverse_kernel(values, mask)

    def single_phase():
        sv, starts, inv = (
            np.asarray(x) for x in (sv_dev, starts_dev, inv_dev)
        )
        _record_fetch(sv, starts, inv)
        return sv[starts], inv

    if n <= SMALL_N_FETCH_LIMIT:
        return single_phase()
    num_uniques = int(nu_dev)
    SCAN_STATS.record_fetch(8)
    size = _pad_group_count(num_uniques)
    if size >= n:
        # nearly-all-distinct column: the padded gather fetches more
        # than the sorted values themselves
        return single_phase()
    uniques = np.asarray(_gather_at_starts_kernel(sv_dev, starts_dev, size))
    inv = np.asarray(inv_dev)
    _record_fetch(uniques, inv)
    return uniques[:num_uniques], inv


def _sorted_starts(mat, va):
    """Traced helper shared by every sparse-grouping kernel: lexsort the
    (k, n) code matrix with valid rows first, mark run starts among valid
    rows. Returns (sorted matrix, sorted validity, starts)."""
    perm = jnp.lexsort(tuple(mat) + (~va,))  # valid rows first
    smat = mat[:, perm]
    sva = va[perm]
    neq = jnp.any(smat[:, 1:] != smat[:, :-1], axis=0)
    starts = jnp.concatenate([jnp.array([True]), neq]) & sva
    return smat, sva, starts


def _run_lengths(positions, n, m):
    """Traced helper: run lengths from ascending start positions (padded
    slots hold ``n``); valid rows occupy the sorted prefix [0, m). Padded
    slots produce count 0."""
    nxt = jnp.minimum(
        jnp.concatenate(
            [positions[1:], jnp.full((1,), n, dtype=positions.dtype)]
        ),
        m,
    )
    return jnp.maximum(nxt - jnp.minimum(positions, m), 0)


@jax.jit
def _matrix_rle_kernel(mat, va):
    smat, sva, starts = _sorted_starts(mat, va)
    # scalars ride back in ONE fetch: [num_groups, num_valid]
    scalars = jnp.stack(
        [jnp.sum(starts.astype(jnp.int64)), jnp.sum(sva.astype(jnp.int64))]
    )
    return smat, sva, starts, scalars


@partial(jax.jit, static_argnames=("size",))
def _rle_gather_kernel(smat, starts, m, size):
    """Gather group representatives + run lengths for the first ``size``
    group starts, entirely on device. Padded slots (beyond the true group
    count) gather index 0 and produce count 0 — the host filters them."""
    n = smat.shape[1]
    positions = jnp.nonzero(starts, size=size, fill_value=n)[0]
    counts = _run_lengths(positions, n, m)
    reps = smat[:, jnp.minimum(positions, n - 1)]
    return reps, counts


def _device_matrix_rle(
    code_matrix: np.ndarray, valid: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Run-length-encode the distinct rows of a (k, n) code matrix via one
    device lexsort + adjacent-compare (the sparse/high-cardinality group-by;
    replaces a host np.unique(axis=0) which is a full host sort). Returns
    (groups (k, G), counts (G,)) for valid rows.

    Device-bounded fetch: the sorted (k, n) matrix never leaves the device.
    One scalar round trip reads the group count G, then a second kernel
    gathers the (k, G) representatives + (G,) run lengths on device, so
    fetched bytes are O(k*G) — not O(k*n) — matching the reference's
    shuffle group-by output size (GroupingAnalyzers.scala:66-78). Small
    inputs keep the single-phase fetch."""
    k, n = code_matrix.shape
    if n == 0:
        return code_matrix[:, :0], np.zeros(0, dtype=np.int64)
    if n <= host_group_limit():
        # latency-dominated regime (see _device_unique_inverse): the same
        # lexsort + adjacent-compare on host, identical results, zero
        # device round trips
        perm = np.lexsort(tuple(code_matrix) + (~valid,))
        smat = code_matrix[:, perm]
        sva = valid[perm]
        neq = np.any(smat[:, 1:] != smat[:, :-1], axis=0)
        starts = np.concatenate([[True], neq]) & sva
        m = int(sva.sum())
        positions = np.nonzero(starts)[0]
        groups = smat[:, positions]
        counts = np.diff(np.append(positions, m)).astype(np.int64)
        return groups, counts
    SCAN_STATS.device_sort_passes += 1

    smat_dev, sva_dev, starts_dev, scalars_dev = _matrix_rle_kernel(
        code_matrix, valid
    )

    def single_phase(m=None):
        smat, starts = np.asarray(smat_dev), np.asarray(starts_dev)
        if m is None:
            sva = np.asarray(sva_dev)
            _record_fetch(smat, sva, starts)
            m = int(sva.sum())  # valid rows occupy the sorted prefix
        else:
            _record_fetch(smat, starts)
        positions = np.nonzero(starts)[0]
        groups = smat[:, positions]
        counts = np.diff(np.append(positions, m)).astype(np.int64)
        return groups, counts

    if n <= SMALL_N_FETCH_LIMIT:
        return single_phase()

    num_groups, m = (int(x) for x in np.asarray(scalars_dev))
    SCAN_STATS.record_fetch(16)
    size = _pad_group_count(num_groups)
    if size >= n:
        # nearly-all-distinct data: the pow2-padded gather would fetch
        # MORE than the plain sorted matrix (up to 2n slots); m is
        # already known from the scalar fetch
        return single_phase(m)
    reps, counts = (
        np.asarray(x)
        for x in _rle_gather_kernel(smat_dev, starts_dev, m, size)
    )
    _record_fetch(reps, counts)
    keep = counts > 0
    return reps[:, keep], counts[keep].astype(np.int64)


def column_key_codes(col: Column) -> Tuple[np.ndarray, List]:
    """Per-row integer codes (0 = null, 1..K = distinct values) + the
    decoded distinct values in code order. Numeric columns build codes via
    a device sort (see _device_unique_inverse); strings are already
    dictionary-encoded at ingest."""
    if col.dtype == DType.STRING:
        codes = col.codes.astype(np.int64) + 1
        return codes, list(col.dictionary)
    if col.dtype == DType.BOOLEAN:
        # 2-value domain: no sort needed at all
        uniques = np.unique(col.values[col.mask])
        # deequ-lint: ignore[host-fetch] -- uniques is host np.unique output over host column values
        lut = {v: i + 1 for i, v in enumerate(uniques.tolist())}
        codes = np.where(
            col.mask, np.where(col.values, lut.get(True, 0), lut.get(False, 0)), 0
        ).astype(np.int64)
        return codes, [bool(v) for v in uniques]
    uniques, codes = _device_unique_inverse(col.values, col.mask)
    if col.dtype == DType.INTEGRAL:
        values = [int(v) for v in uniques]
    else:
        values = [float(v) for v in uniques]
    return codes, values


from functools import lru_cache


def _count_slots(slot, num_segments: int, variant: str):
    """Traced: counts over ``num_segments + 1`` slots under the routed
    kernel tier (ops/histogram_device.py). The scatter variant keeps the
    historical ``segment_sum``-of-ones formulation bit-for-bit; the
    one-hot/pallas variants replace the scatter-add with the blocked
    matmul / Mosaic grid kernel, exact by the tier's integer-count
    contract."""
    if variant == "scatter":
        return jax.ops.segment_sum(
            jnp.ones_like(slot, dtype=jnp.int64), slot,
            num_segments=num_segments + 1,
        )
    from deequ_tpu.ops.histogram_device import bincount_variant

    return bincount_variant(
        variant, slot, num_segments + 1, jnp, dtype=jnp.int64
    )


def _shard_map_kwargs(variant: str) -> dict:
    """``pallas_call`` has no shard_map replication rule in this jax
    (NotImplementedError at trace time), so the pallas variant disables
    the replication check — sound here because every grouping kernel
    psums its counts to an explicitly replicated output anyway."""
    return {"check_rep": False} if variant == "pallas" else {}


@lru_cache(maxsize=64)
def _bincount_fn(num_segments: int, mesh, variant: str = "scatter"):
    """Jitted (and mesh-wrapped) bincount kernel, cached so repeated runs
    with the same cardinality/mesh/kernel-variant reuse the traced
    program instead of retracing per call (the variant is part of the
    cache key — a one-hot program must never serve a scatter dispatch
    or vice versa)."""

    def count(k):
        slot = jnp.where(k < 0, num_segments, k)
        counts = _count_slots(slot, num_segments, variant)
        if mesh is not None:
            counts = jax.lax.psum(counts, ROW_AXIS)
        return counts

    if mesh is not None:
        return jax.jit(
            shard_map(
                count, mesh=mesh, in_specs=P(ROW_AXIS), out_specs=P(),
                **_shard_map_kwargs(variant),
            )
        )
    return jax.jit(count)


@lru_cache(maxsize=64)
def _topk_fn(
    num_segments: int, kk: int, mesh, merge_null_into: int = -1,
    variant: str = "scatter",
):
    """Jitted dense-count + device top-k kernel (cached like _bincount_fn,
    kernel variant in the cache key). ``merge_null_into`` as in
    _topk_from_counts_fn."""

    def kernel(c):
        slot = jnp.where(c < 0, num_segments, c)
        counts = _count_slots(slot, num_segments, variant)
        if mesh is not None:
            counts = jax.lax.psum(counts, ROW_AXIS)
        counts = counts[:num_segments]
        if merge_null_into >= 0:
            counts = counts.at[merge_null_into].add(counts[0])
            counts = counts.at[0].set(0)
        num_groups = (counts > 0).sum()
        top_counts, top_idx = jax.lax.top_k(counts, kk)
        return num_groups, top_counts, top_idx

    if mesh is not None:
        return jax.jit(
            shard_map(
                kernel, mesh=mesh, in_specs=P(ROW_AXIS), out_specs=P(),
                **_shard_map_kwargs(variant),
            )
        )
    return jax.jit(kernel)


# -- device-resident grouping (persisted tables) ----------------------------
#
# When the table is persist()ed, a string column's codes already live in
# HBM inside the packed chunks; the grouping kernels then read them there
# instead of re-shipping O(rows) host bytes per analysis run. Only the tiny
# counts-derived results (top-k bins, scalar stats) ever leave the device.


@lru_cache(maxsize=64)
def _resident_bincount_fn(
    num_segments: int, n_chunks: int, row: int, include_null: bool, mesh,
    variant: str = "scatter",
):
    def kernel(*args):  # codes_0, rv_0, codes_1, rv_1, ...
        counts = jnp.zeros(num_segments + 1, dtype=jnp.int64)
        for i in range(n_chunks):
            c = args[2 * i][row].astype(jnp.int64)
            rv = args[2 * i + 1]
            on = rv if include_null else rv & (c >= 0)
            slot = jnp.where(on, c + 1, num_segments)
            counts = counts + _count_slots(slot, num_segments, variant)
        if mesh is not None:
            counts = jax.lax.psum(counts, ROW_AXIS)
        return counts[:num_segments]

    if mesh is not None:
        in_specs = (P(None, ROW_AXIS), P(ROW_AXIS)) * n_chunks
        return jax.jit(
            shard_map(
                kernel, mesh=mesh, in_specs=in_specs, out_specs=P(),
                **_shard_map_kwargs(variant),
            )
        )
    return jax.jit(kernel)


def _resident_string_bincount(table, column: str, include_null: bool, mesh):
    """Counts per code slot (slot 0 = null when include_null) straight from
    the persisted chunks, or None when the table/column is not resident.
    Returns a DEVICE array of length cardinality+1."""
    cache = getattr(table, "_device_cache", None)
    if cache is None or not cache.device_chunks:
        return None
    if not cache.matches(mesh, [column]):
        return None
    packer = cache.packer
    if column not in packer.string_names:
        return None
    row = packer.string_names.index(column)
    card = len(packer.col_dict[column])
    from deequ_tpu.ops.device_policy import resolve_hist_variant

    variant = resolve_hist_variant((card + 2,), rows=table.num_rows)
    # one bincount pass per resident chunk, all inside one dispatch
    SCAN_STATS.record_hist_dispatch(variant, len(cache.device_chunks))
    fn = _resident_bincount_fn(
        card + 1, len(cache.device_chunks), row, include_null, mesh,
        variant,
    )
    args = []
    for chunk in cache.device_chunks:
        args.append(chunk[5])  # codes buffer
        args.append(chunk[6])  # row_valid
    return fn(*args)


@lru_cache(maxsize=64)
def _topk_from_counts_fn(kk: int, merge_null_into: int = -1):
    """Top-k + group count from a dense counts vector. When
    ``merge_null_into`` >= 0, slot 0 (the null group) folds into that slot
    BEFORE ranking: the Histogram metric stringifies groups (null ->
    "NullValue"), so a literal "NullValue" string and actual nulls are ONE
    bin — merging after truncation would undercount whenever one of the
    pair straddles the k boundary."""

    def kernel(counts):
        if merge_null_into >= 0:
            counts = counts.at[merge_null_into].add(counts[0])
            counts = counts.at[0].set(0)
        num_groups = (counts > 0).sum()
        top_counts, top_idx = jax.lax.top_k(counts, kk)
        return num_groups, top_counts, top_idx

    return jax.jit(kernel)


@jax.jit
def _rle_stats_kernel(mat, va):
    """Sparse group-by count-distribution aggregates entirely on device:
    lexsort + run starts as in _matrix_rle_kernel, then run lengths via a
    positions-diff over a full-length (static-shape) sorted position
    vector — no data-dependent shapes, so num_groups, singletons, and the
    entropy numerator sum(c*log c) come back as FOUR SCALARS regardless of
    how many distinct groups the data has."""
    _smat, sva, starts = _sorted_starts(mat, va)
    n = mat.shape[1]
    m = jnp.sum(sva)  # valid rows occupy the sorted prefix
    pos = jnp.sort(jnp.where(starts, jnp.arange(n, dtype=jnp.int64), n))
    counts = _run_lengths(pos, n, m)
    num_groups = jnp.sum(starts)
    singletons = jnp.sum(counts == 1)
    c = counts.astype(jnp.float64)
    clogc = jnp.sum(jnp.where(counts > 0, c, 0.0) * jnp.log(jnp.where(counts > 0, c, 1.0)))
    return m, num_groups, singletons, clogc


@jax.jit
def _stats_from_counts(counts):
    total = counts.sum()
    groups = (counts > 0).sum()
    singles = (counts == 1).sum()
    p = counts / jnp.maximum(total, 1)
    ent = -jnp.where(counts > 0, p * jnp.log(jnp.where(p > 0, p, 1.0)), 0.0).sum()
    return total, groups, singles, ent


def _device_bincount(keys: np.ndarray, num_segments: int, mesh) -> np.ndarray:
    """Count key occurrences on device; psum across the mesh if present.

    ``keys`` may contain -1 for rows to ignore (filtered / padding); those
    land in an extra trailing slot that is dropped.
    """
    n = len(keys)
    if n <= host_group_limit():
        # latency-dominated regime: host bincount (totals are identical —
        # the mesh merge only re-sums the same rows)
        slots = np.where(keys >= 0, keys, num_segments)
        counts = np.bincount(slots, minlength=num_segments + 1)
        return counts[:num_segments].astype(np.int64)
    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    padded = max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)
    if padded != n:
        keys = np.concatenate([keys, np.full(padded - n, -1, dtype=np.int64)])

    # histogram kernel tier (round 14): scatter vs one-hot matmul vs
    # pallas, resolved per dispatch from keyspace width / rows / platform
    from deequ_tpu.ops.device_policy import resolve_hist_variant

    variant = resolve_hist_variant((num_segments + 1,), rows=n)
    SCAN_STATS.record_hist_dispatch(variant)
    counts = np.asarray(_bincount_fn(num_segments, mesh, variant)(keys))
    _record_fetch(counts)
    return counts[:num_segments]


def _typed_values(col_dtype: DType, values: List) -> np.ndarray:
    """Distinct values (code order) -> a typed numpy array the columnar
    frequency state can factorize with vectorized np.unique."""
    if col_dtype == DType.STRING:
        # deequ-lint: ignore[host-fetch] -- `values` is a host python list (dictionary order), never a device array
        return np.asarray(values, dtype=np.str_) if values else np.empty(
            0, dtype=np.str_
        )
    if col_dtype == DType.BOOLEAN:
        # deequ-lint: ignore[host-fetch] -- `values` is a host python list (dictionary order), never a device array
        return np.asarray(values, dtype=np.bool_)
    if col_dtype == DType.INTEGRAL:
        # deequ-lint: ignore[host-fetch] -- `values` is a host python list (dictionary order), never a device array
        return np.asarray(values, dtype=np.int64)
    # deequ-lint: ignore[host-fetch] -- `values` is a host python list (dictionary order), never a device array
    return np.asarray(values, dtype=np.float64)


@dataclass
class _GroupPrep:
    """One grouping set's PREPARED key material — the planning/packing
    half of ``group_counts_state``/``group_count_stats``, split out
    (round 19) so the fused multi-pass dispatch and the per-set paths
    share one derivation and can never drift. ``keys`` (dense only) is
    the mixed-radix packed int64 vector with -1 marking excluded rows —
    exactly what ``_device_bincount`` consumes, offsettable for
    fusion."""

    columns: Tuple[str, ...]
    code_arrays: List[np.ndarray]
    value_arrays: Optional[List[np.ndarray]]
    radices: List[int]
    any_non_null: Optional[np.ndarray]
    num_rows: int
    keyspace: int
    dense: bool
    keys: Optional[np.ndarray]


def _prepare_grouping(
    table: ColumnarTable,
    columns: Sequence[str],
    require_any_non_null: bool = True,
    with_values: bool = True,
) -> _GroupPrep:
    """Derive one grouping set's codes/radices/packed keys.
    ``with_values=False`` skips the typed distinct-value arrays (the
    count-stats path never decodes group values)."""
    code_arrays = []
    value_arrays: Optional[List[np.ndarray]] = [] if with_values else None
    radices = []
    for name in columns:
        col = table[name]
        codes, values = column_key_codes(col)
        if with_values:
            # memoize the typed distinct-value array per column: for
            # string columns this converts the whole dictionary
            # (O(cardinality)); repeated runs (incremental monitoring)
            # reuse it
            typed = getattr(col, "_typed_distinct", None)
            if typed is None or len(typed) != len(values):
                typed = _typed_values(col.dtype, values)
                col._typed_distinct = typed
            value_arrays.append(typed)
        code_arrays.append(codes)
        radices.append(len(values) + 1)

    if require_any_non_null and len(columns) > 0:
        any_non_null = np.zeros(table.num_rows, dtype=bool)
        for codes in code_arrays:
            any_non_null |= codes > 0
        num_rows = int(any_non_null.sum())
    else:
        any_non_null = None
        num_rows = table.num_rows

    # Python-int product: mixed-radix packing into int64 silently wraps when
    # the key space exceeds 2^63, so overflow must be checked BEFORE packing
    keyspace = 1
    for radix in radices:
        keyspace *= radix

    dense = keyspace <= DENSE_KEYSPACE_LIMIT
    keys = None
    if dense:
        keys = np.zeros(table.num_rows, dtype=np.int64)
        for codes, radix in zip(code_arrays, radices):
            keys = keys * radix + codes
        if any_non_null is not None:
            keys = np.where(any_non_null, keys, -1)
    return _GroupPrep(
        tuple(columns), code_arrays, value_arrays, radices, any_non_null,
        num_rows, keyspace, dense, keys,
    )


def _dense_digits(
    prep: _GroupPrep, counts: np.ndarray
) -> Tuple[List[np.ndarray], np.ndarray]:
    """Dense counts vector -> (per-column digit codes of the present
    groups, their counts) via vectorized mixed-radix decode."""
    present = np.nonzero(counts)[0]
    group_counts_vec = counts[present].astype(np.int64)
    digit_cols = []
    rest = present
    for radix in reversed(prep.radices):
        digit_cols.append(rest % radix)
        rest = rest // radix
    digit_cols.reverse()
    return digit_cols, group_counts_vec


def _freq_state_from_digits(
    columns: Sequence[str],
    digit_cols: List[np.ndarray],
    group_counts_vec: np.ndarray,
    value_arrays: List[np.ndarray],
    num_rows: int,
    canonicalize: bool,
):
    """Digit codes + counts -> columnar ``FrequenciesAndNumRows`` (the
    finalize half shared by the dense, sparse, and fused paths)."""
    from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows

    key_values = []
    key_nulls = []
    for digits, values in zip(digit_cols, value_arrays):
        nulls = digits == 0
        if len(values):
            key_values.append(values[np.maximum(digits - 1, 0)])
        else:
            key_values.append(np.zeros(len(digits), dtype=values.dtype))
        key_nulls.append(nulls)
    if canonicalize:
        # lazy import: spill depends on analyzers.grouping which imports
        # this module; at call time everything is loaded
        from deequ_tpu.spill.order import is_strictly_ascending, merge_add_sorted

        if not is_strictly_ascending(key_values, key_nulls):
            kv, kn, group_counts_vec = merge_add_sorted(
                [(tuple(key_values), tuple(key_nulls), group_counts_vec)]
            )
            key_values, key_nulls = list(kv), list(kn)
    return FrequenciesAndNumRows(
        tuple(columns), tuple(key_values), tuple(key_nulls),
        group_counts_vec, num_rows,
    )


def group_counts_state(
    table: ColumnarTable,
    columns: Sequence[str],
    mesh=None,
    require_any_non_null: bool = True,
    canonicalize: bool = False,
):
    """Compute the frequency table for a set of grouping columns as a
    COLUMNAR ``FrequenciesAndNumRows`` (reference
    GroupingAnalyzers.scala:53-79): counts come off the device and group
    keys decode via vectorized gathers into the per-column distinct-value
    arrays — no per-group python loop, so 100M-distinct groupings stay in
    array ops end to end.

    ``canonicalize=True`` emits the state as a SORTED delta in canonical
    key order (first column most significant, nulls first, values
    ascending, NaN last — the order ``FrequenciesAndNumRows.sum``
    produces): the out-of-core spill engine (deequ_tpu/spill) folds these
    per-chunk sorted deltas straight into budget-bounded runs without
    re-sorting. Numeric columns come out of the device paths already in
    that order (codes are value-ascending ranks); string columns carry
    ingest-dictionary codes in arbitrary dictionary order, so the emitted
    delta is VERIFIED (O(G) adjacent-row compare) and host sort+dedup'd
    only when the order actually fails.
    """
    if mesh is None:
        mesh = current_mesh()
    SCAN_STATS.grouping_passes += 1
    SCAN_STATS.rows_scanned += table.num_rows

    prep = _prepare_grouping(
        table, columns, require_any_non_null, with_values=True
    )

    if prep.dense:
        counts = _device_bincount(prep.keys, prep.keyspace, mesh)
        digit_cols, group_counts_vec = _dense_digits(prep, counts)
    else:
        # sparse path for huge key spaces: device lexsort + run-length
        # encoding over the code matrix — no packing (no overflow regardless
        # of cardinality product), no host sort
        matrix = np.stack(prep.code_arrays, axis=0)
        valid = (
            prep.any_non_null
            if prep.any_non_null is not None
            else np.ones(table.num_rows, dtype=bool)
        )
        groups_mat, group_counts_vec = _device_matrix_rle(matrix, valid)
        digit_cols = [groups_mat[i] for i in range(groups_mat.shape[0])]
        if canonicalize and len(digit_cols) > 1:
            # the RLE kernels lexsort last-column-major; re-order the O(G)
            # digit codes first-column-major (digits ARE canonical ranks:
            # 0 = null, then value-ascending np.unique codes)
            order = np.lexsort(tuple(reversed(digit_cols)))
            digit_cols = [d[order] for d in digit_cols]
            group_counts_vec = group_counts_vec[order]

    return _freq_state_from_digits(
        columns, digit_cols, group_counts_vec, prep.value_arrays,
        prep.num_rows, canonicalize,
    )


def group_counts(
    table: ColumnarTable,
    columns: Sequence[str],
    mesh=None,
    require_any_non_null: bool = True,
) -> Tuple[Dict[tuple, int], int]:
    """Dict-shaped compatibility wrapper around ``group_counts_state``:
    maps each tuple of group values (None = null) to its count."""
    state = group_counts_state(table, columns, mesh, require_any_non_null)
    return state.as_dict(), state.num_rows


@dataclass(frozen=True)
class TopKCounts:
    """Device-computed histogram summary: total rows, distinct-group count,
    and only the top-k (group value, count) pairs decoded to host — the
    analogue of the reference computing top-maxDetailBins in the engine
    (Histogram.scala:97-103) instead of collecting every group."""

    num_rows: int
    num_groups: int
    top: Tuple[Tuple[object, int], ...]  # (value-or-None, count), count desc


def group_top_k(
    table: ColumnarTable,
    column: str,
    k: int,
    mesh=None,
) -> TopKCounts:
    """Top-k most frequent values of ONE column, counts computed and ranked
    on device; only k codes+counts are fetched and only those k distinct
    values are decoded. Nulls form their own group (value None). Ties at
    the k-boundary break by first-seen code order (the reference's top() is
    similarly tie-unstable)."""
    if mesh is None:
        mesh = current_mesh()
    SCAN_STATS.grouping_passes += 1
    SCAN_STATS.rows_scanned += table.num_rows

    col = table[column]
    nv_code = -1
    if col.dtype == DType.STRING:
        # the Histogram metric stringifies nulls to "NullValue": if that
        # literal also appears in the data, the two slots are ONE bin and
        # must merge on device BEFORE top-k truncation
        hits = np.nonzero(col.dictionary == "NullValue")[0]
        if len(hits):
            nv_code = int(hits[0]) + 1
        # persisted table: counts + top-k entirely from HBM-resident codes
        resident = _resident_string_bincount(table, column, True, mesh)
        if resident is not None:
            kk = min(k, len(col.dictionary) + 1)
            num_groups, top_counts, top_idx = (
                np.asarray(x)
                for x in _topk_from_counts_fn(kk, nv_code)(resident)
            )
            top = []
            for idx, cnt in zip(top_idx.tolist(), top_counts.tolist()):
                if cnt <= 0:
                    continue
                top.append(
                    (None if idx == 0 else col.dictionary[idx - 1], int(cnt))
                )
            return TopKCounts(table.num_rows, int(num_groups), tuple(top))
        codes = col.codes.astype(np.int64) + 1
        decode = lambda idx: col.dictionary[idx - 1]  # noqa: E731
        card = len(col.dictionary)
    elif col.dtype == DType.BOOLEAN:
        codes, values = column_key_codes(col)
        decode = lambda idx: values[idx - 1]  # noqa: E731
        card = len(values)
    else:
        uniques, codes = _device_unique_inverse(col.values, col.mask)
        cast = int if col.dtype == DType.INTEGRAL else float
        decode = lambda idx: cast(uniques[idx - 1])  # noqa: E731
        card = len(uniques)

    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    n = len(codes)
    num_segments = card + 1  # slot 0 = null group
    kk = min(k, num_segments)

    if n <= host_group_limit():
        # latency-dominated regime: counts + top-k on host (identical
        # ordering: argsort(-counts) stable == top_k's rank order up to
        # count ties, which are unstable on both sides by contract)
        slots = np.where(codes >= 0, codes, num_segments)
        counts = np.bincount(slots, minlength=num_segments + 1)[
            :num_segments
        ].astype(np.int64)
        if nv_code >= 0:
            counts[nv_code] += counts[0]
            counts[0] = 0
        num_groups = int((counts > 0).sum())
        order = np.argsort(-counts, kind="stable")[:kk]
        top_idx, top_counts = order, counts[order]
    else:
        padded = max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)
        if padded != n:
            codes = np.concatenate(
                [codes, np.full(padded - n, -1, dtype=np.int64)]
            )
        from deequ_tpu.ops.device_policy import resolve_hist_variant

        variant = resolve_hist_variant((num_segments + 1,), rows=n)
        SCAN_STATS.record_hist_dispatch(variant)
        num_groups, top_counts, top_idx = (
            np.asarray(x)
            for x in _topk_fn(num_segments, kk, mesh, nv_code, variant)(codes)
        )
        _record_fetch(num_groups, top_counts, top_idx)

    top = []
    for idx, cnt in zip(top_idx.tolist(), top_counts.tolist()):
        if cnt <= 0:
            continue
        top.append((None if idx == 0 else decode(idx), int(cnt)))
    return TopKCounts(table.num_rows, int(num_groups), tuple(top))


def _count_stats_from_counts(counts: np.ndarray, num_rows: int) -> "CountStats":
    """Host counts vector -> CountStats (shared by the dense path and the
    small-input host path so the entropy/singleton definitions cannot
    drift apart)."""
    num_groups = int(len(counts))
    singletons = int((counts == 1).sum())
    if num_rows > 0 and num_groups > 0:
        p = counts.astype(np.float64) / num_rows
        entropy = float(-(p * np.log(p)).sum())
    else:
        entropy = float("nan")
    return CountStats(num_rows, num_groups, singletons, entropy)


@dataclass(frozen=True)
class CountStats:
    """Scalar aggregates of the group-count distribution — everything the
    count-only grouping analyzers (Uniqueness, UniqueValueRatio,
    Distinctness, CountDistinct, Entropy) need, WITHOUT materializing the
    frequency table on host. For high-cardinality groupings (#groups ~ n)
    this skips the O(n) group decode + dict build entirely."""

    num_rows: int
    num_groups: int
    singletons: int
    entropy: float


def group_count_stats(
    table: ColumnarTable,
    columns: Sequence[str],
    mesh=None,
    require_any_non_null: bool = True,
) -> CountStats:
    """Count-distribution aggregates for a grouping, group values never
    leaving the device (sparse path) / never decoded (dense path)."""
    if mesh is None:
        mesh = current_mesh()
    SCAN_STATS.grouping_passes += 1
    SCAN_STATS.rows_scanned += table.num_rows

    # single resident string column: all four aggregates from HBM-resident
    # codes — only 4 scalars leave the device
    if len(columns) == 1 and table[columns[0]].dtype == DType.STRING:
        resident = _resident_string_bincount(
            table, columns[0], not require_any_non_null, mesh
        )
        if resident is not None:
            total, groups, singles, ent = (
                np.asarray(x) for x in _stats_from_counts(resident)
            )
            total = int(total)
            return CountStats(
                total,
                int(groups),
                int(singles),
                float(ent) if total > 0 and int(groups) > 0 else float("nan"),
            )

    prep = _prepare_grouping(
        table, columns, require_any_non_null, with_values=False
    )
    num_rows = prep.num_rows

    if prep.dense:
        counts = _device_bincount(prep.keys, prep.keyspace, mesh)
        return _count_stats_from_counts(counts[counts > 0], num_rows)

    # sparse path: every aggregate reduces ON DEVICE — only four scalars
    # are fetched, regardless of group count (the former implementation
    # fetched two n-length boolean vectors)
    matrix = np.stack(prep.code_arrays, axis=0)
    valid = (
        prep.any_non_null
        if prep.any_non_null is not None
        else np.ones(table.num_rows, dtype=bool)
    )
    if table.num_rows <= host_group_limit():
        # latency-dominated regime: _device_matrix_rle takes its host
        # path below this size — derive the stats from its counts
        _groups, counts = _device_matrix_rle(matrix, valid)
        return _count_stats_from_counts(counts, num_rows)
    SCAN_STATS.device_sort_passes += 1
    m, num_groups, singletons, clogc = (
        float(x) for x in _rle_stats_kernel(matrix, valid)
    )
    SCAN_STATS.record_fetch(4 * 8)
    num_groups = int(num_groups)
    if num_rows > 0 and num_groups > 0:
        # entropy = -sum (c/N) log(c/N) = log N - (sum c*log c)/N, N = m
        entropy = float(np.log(m) - clogc / m)
    else:
        entropy = float("nan")
    return CountStats(num_rows, num_groups, singletons, entropy)


# -- cross-pass grouping fusion (round 19, the whole-run plan optimizer) ----


@dataclass(frozen=True)
class GroupRequest:
    """One grouping pass the plan optimizer may fuse: its sorted column
    set and which finalize shape the caller needs — ``"freq"`` (the full
    columnar ``FrequenciesAndNumRows``) or ``"stats"`` (count-distribution
    scalars only, the ``group_count_stats`` fast path)."""

    columns: Tuple[str, ...]
    mode: str = "freq"
    canonicalize: bool = False


def _resident_stats_eligible(table, columns, mesh) -> bool:
    """True when a stats-mode set would take ``group_count_stats``'s
    resident-string fast path (all four aggregates from HBM-resident
    codes, four scalars fetched) — cheaper than any fusion, and its
    device-side entropy reduction is not bit-guaranteed against the host
    finalize, so the optimizer must leave such sets on the per-set
    path."""
    if len(columns) != 1 or table[columns[0]].dtype != DType.STRING:
        return False
    cache = getattr(table, "_device_cache", None)
    if cache is None or not cache.device_chunks:
        return False
    if not cache.matches(mesh, [columns[0]]):
        return False
    return columns[0] in cache.packer.string_names


def _maybe_lint_fused(
    keyspaces: Tuple[int, ...], n: int, mesh, variant: str
) -> None:
    """Static lint of the fused multi-pass bincount program under the
    ambient DEEQU_TPU_PLAN_LINT mode — the ``plan-fusion-refetch`` rule
    armed against the exact jitted program the dispatch will run (one
    concatenated counts output, no host callbacks). Memoized under the
    fusion signature so fused and unfused variants of the same sets lint
    separately, and repeated fused dispatches add zero traces."""
    from deequ_tpu.lint.plan_lint import (
        enforce_plan_lint,
        lint_plan_cached,
        plan_lint_mode,
    )

    mode = plan_lint_mode(None)
    if mode == "off":
        return
    from deequ_tpu.ops.scan_plan import plan_fused_grouping

    total = sum(keyspaces)
    plan_ir = plan_fused_grouping(keyspaces, rows=n, hist_variant=variant)
    fn = _bincount_fn(total, mesh, variant)
    avals = (jax.ShapeDtypeStruct((int(n),), np.int64),)
    mesh_sig = (
        None
        if mesh is None
        else tuple(int(d.id) for d in np.ravel(mesh.devices))
    )
    memo_key = ("fused_group", keyspaces, int(n), variant, mesh_sig)
    findings, traced = lint_plan_cached(plan_ir, fn, avals, memo_key)
    if traced:
        SCAN_STATS.plan_lint_traces += 1
    if findings:
        SCAN_STATS.plan_lints.extend(f.as_dict() for f in findings)
    enforce_plan_lint(findings, mode)


def fused_group_counts(
    table: ColumnarTable,
    requests: Sequence[GroupRequest],
    mesh=None,
) -> Dict[int, object]:
    """Cross-pass grouping FUSION: execute several dense grouping passes
    in ONE device dispatch (round 19, the tentpole observable — K
    grouping passes, one ``record_hist_dispatch``, one fetch).

    Each dense-eligible request's packed keys are offset by the
    cumulative keyspace of the requests fused before it and concatenated
    into one key vector; a single ``_device_bincount`` over the summed
    keyspace then counts every sub-pass at once, and the counts vector
    slices back per request. Integer bincounts are exact under any
    kernel variant or concatenation order, so each slice is bit-identical
    to the counts the per-set dispatch would have produced — the fusion
    legality rule (docs/planner.md).

    Returns ``{request_index: state}`` for the requests computed here
    (``FrequenciesAndNumRows`` for freq mode, ``CountStats`` for stats
    mode), with per-request ``grouping_passes``/``rows_scanned``
    accounting identical to the per-set path. A request ABSENT from the
    result falls back to the ordinary per-set path: sparse keyspaces,
    resident-string stats sets, sets whose preparation failed (the
    per-set path re-raises into the analyzer's failure metric), and sets
    whose fused group faulted twice.

    Fault ladder: a typed device fault (or an armed plan-lint rejection)
    during the FUSED dispatch demotes that group — recorded as a
    ``fusion_demote`` degradation — and each member re-plans UNFUSED
    from its own prepared keys, exactly the re-plan-per-attempt contract
    the scan ladder keeps."""
    from deequ_tpu.exceptions import DeviceException, PlanLintError

    if mesh is None:
        mesh = current_mesh()

    preps: Dict[int, _GroupPrep] = {}
    for i, req in enumerate(requests):
        if req.mode == "stats" and _resident_stats_eligible(
            table, req.columns, mesh
        ):
            continue
        try:
            prep = _prepare_grouping(
                table, list(req.columns), True,
                with_values=req.mode == "freq",
            )
        # deequ-lint: ignore[bare-except] -- a failed preparation falls back to the per-set path, which re-raises into the analyzer's typed failure metric
        except Exception:  # noqa: BLE001
            continue
        if prep.dense:
            preps[i] = prep

    # greedy keyspace packing: fuse runs of dense sets whose SUMMED
    # counts vector still fits the dense limit (the fused dispatch
    # materializes one vector of the total width)
    groups: List[List[int]] = []
    cur: List[int] = []
    cur_space = 0
    for i in sorted(preps):
        k = preps[i].keyspace
        if cur and cur_space + k > DENSE_KEYSPACE_LIMIT:
            groups.append(cur)
            cur, cur_space = [], 0
        cur.append(i)
        cur_space += k
    if cur:
        groups.append(cur)

    results: Dict[int, object] = {}
    for group in groups:
        group_counts: Optional[List[np.ndarray]] = None
        if len(group) >= 2:
            keyspaces = tuple(preps[i].keyspace for i in group)
            total = sum(keyspaces)
            offsets = np.cumsum((0,) + keyspaces[:-1])
            combined = np.concatenate([
                np.where(
                    preps[i].keys >= 0,
                    preps[i].keys + np.int64(off),
                    np.int64(-1),
                )
                for i, off in zip(group, offsets)
            ])
            try:
                if len(combined) > host_group_limit():
                    from deequ_tpu.ops.device_policy import (
                        resolve_hist_variant,
                    )

                    variant = resolve_hist_variant(
                        (total + 1,), rows=len(combined)
                    )
                    _maybe_lint_fused(
                        keyspaces, len(combined), mesh, variant
                    )
                all_counts = _device_bincount(combined, total, mesh)
                group_counts = [
                    all_counts[off:off + k]
                    for off, k in zip(offsets, keyspaces)
                ]
                SCAN_STATS.record_fused_group_pass(len(group))
            except (DeviceException, PlanLintError) as e:
                # the demotion rung: re-plan each member UNFUSED below
                SCAN_STATS.record_degradation(
                    "fusion_demote", passes=len(group),
                    keyspace=int(total), reason=str(e),
                )
                group_counts = None
        for j, i in enumerate(group):
            req, prep = requests[i], preps[i]
            try:
                counts = (
                    group_counts[j]
                    if group_counts is not None
                    else _device_bincount(prep.keys, prep.keyspace, mesh)
                )
                if req.mode == "stats":
                    state = _count_stats_from_counts(
                        counts[counts > 0], prep.num_rows
                    )
                else:
                    digit_cols, vec = _dense_digits(prep, counts)
                    state = _freq_state_from_digits(
                        req.columns, digit_cols, vec, prep.value_arrays,
                        prep.num_rows, req.canonicalize,
                    )
            # deequ-lint: ignore[bare-except] -- an unfused retry that still fails falls back to the per-set path for its typed failure metric
            except Exception:  # noqa: BLE001
                continue
            # per-request census parity with the per-set path
            SCAN_STATS.grouping_passes += 1
            SCAN_STATS.rows_scanned += table.num_rows
            results[i] = state
    return results
