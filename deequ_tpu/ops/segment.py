"""Group-by counts via dictionary codes + device segment reduction.

The reference shuffles rows for ``GROUP BY`` (GroupingAnalyzers.scala:66-78).
The TPU-native design avoids a shuffle entirely: every column is already
dictionary-encoded, so a group key is a mixed-radix packing of per-column
codes and the frequency table is one ``segment_sum`` of ones — a single
device pass, with ``psum`` merging per-device count vectors across the mesh
(this IS the monoid merge of the frequency state).

For pathological key-space sizes (product of per-column cardinalities too
large to materialize as a dense count vector) we fall back to host
``np.unique`` over the packed keys, which is the sparse equivalent.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deequ_tpu.data.table import Column, ColumnarTable, DType
from deequ_tpu.ops.scan_engine import SCAN_STATS
from deequ_tpu.parallel.mesh import ROW_AXIS, current_mesh

# dense device count vectors are used up to this key-space size
DENSE_KEYSPACE_LIMIT = 1 << 22


def column_key_codes(col: Column) -> Tuple[np.ndarray, List]:
    """Per-row integer codes (0 = null, 1..K = distinct values) + the
    decoded distinct values in code order."""
    if col.dtype == DType.STRING:
        codes = col.codes.astype(np.int64) + 1
        return codes, list(col.dictionary)
    valid = col.values[col.mask]
    uniques, inv = np.unique(valid, return_inverse=True)
    codes = np.zeros(len(col), dtype=np.int64)
    codes[col.mask] = inv + 1
    if col.dtype == DType.BOOLEAN:
        values = [bool(v) for v in uniques]
    elif col.dtype == DType.INTEGRAL:
        values = [int(v) for v in uniques]
    else:
        values = [float(v) for v in uniques]
    return codes, values


def _device_bincount(keys: np.ndarray, num_segments: int, mesh) -> np.ndarray:
    """Count key occurrences on device; psum across the mesh if present.

    ``keys`` may contain -1 for rows to ignore (filtered / padding); those
    land in an extra trailing slot that is dropped.
    """
    n = len(keys)
    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    padded = max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)
    if padded != n:
        keys = np.concatenate([keys, np.full(padded - n, -1, dtype=np.int64)])

    def count(k):
        slot = jnp.where(k < 0, num_segments, k)
        counts = jax.ops.segment_sum(
            jnp.ones_like(slot, dtype=jnp.int64), slot, num_segments=num_segments + 1
        )
        if mesh is not None:
            counts = jax.lax.psum(counts, ROW_AXIS)
        return counts

    if mesh is not None:
        fn = jax.jit(
            jax.shard_map(count, mesh=mesh, in_specs=P(ROW_AXIS), out_specs=P())
        )
    else:
        fn = jax.jit(count)
    counts = np.asarray(fn(keys))
    return counts[:num_segments]


def group_counts(
    table: ColumnarTable,
    columns: Sequence[str],
    mesh=None,
    require_any_non_null: bool = True,
) -> Tuple[Dict[tuple, int], int]:
    """Compute the frequency table for a set of grouping columns.

    Returns ``(frequencies, num_rows)`` where frequencies maps a tuple of
    group values (None = null) to its count and num_rows is the number of
    rows with at least one non-null grouping column (reference
    GroupingAnalyzers.scala:53-79).
    """
    if mesh is None:
        mesh = current_mesh()
    SCAN_STATS.grouping_passes += 1
    SCAN_STATS.rows_scanned += table.num_rows

    code_arrays = []
    value_lists = []
    for name in columns:
        codes, values = column_key_codes(table[name])
        code_arrays.append(codes)
        value_lists.append(values)

    radices = [len(v) + 1 for v in value_lists]

    if require_any_non_null and len(columns) > 0:
        any_non_null = np.zeros(table.num_rows, dtype=bool)
        for codes in code_arrays:
            any_non_null |= codes > 0
        num_rows = int(any_non_null.sum())
    else:
        any_non_null = None
        num_rows = table.num_rows

    # Python-int product: mixed-radix packing into int64 silently wraps when
    # the key space exceeds 2^63, so overflow must be checked BEFORE packing
    keyspace = 1
    for radix in radices:
        keyspace *= radix

    frequencies: Dict[tuple, int] = {}
    if keyspace <= DENSE_KEYSPACE_LIMIT:
        keys = np.zeros(table.num_rows, dtype=np.int64)
        for codes, radix in zip(code_arrays, radices):
            keys = keys * radix + codes
        if any_non_null is not None:
            keys = np.where(any_non_null, keys, -1)
        counts = _device_bincount(keys, keyspace, mesh)
        present = np.nonzero(counts)[0]
        present_counts = counts[present]
        for key, cnt in zip(present.tolist(), present_counts.tolist()):
            digits = []
            rest = key
            for radix in reversed(radices):
                digits.append(rest % radix)
                rest //= radix
            digits.reverse()
            group = tuple(
                None if d == 0 else value_lists[i][d - 1]
                for i, d in enumerate(digits)
            )
            frequencies[group] = int(cnt)
    else:
        # sparse path for huge key spaces: unique over the code matrix rows —
        # no packing, so no overflow regardless of cardinality product
        matrix = np.stack(code_arrays, axis=1)
        if any_non_null is not None:
            matrix = matrix[any_non_null]
        uniques, counts = np.unique(matrix, axis=0, return_counts=True)
        for row, cnt in zip(uniques.tolist(), counts.tolist()):
            group = tuple(
                None if d == 0 else value_lists[i][d - 1]
                for i, d in enumerate(row)
            )
            frequencies[group] = int(cnt)
    return frequencies, num_rows
