"""Group-by counts via dictionary codes + device segment reduction.

The reference shuffles rows for ``GROUP BY`` (GroupingAnalyzers.scala:66-78).
The TPU-native design avoids a shuffle entirely: every column is already
dictionary-encoded, so a group key is a mixed-radix packing of per-column
codes and the frequency table is one ``segment_sum`` of ones — a single
device pass, with ``psum`` merging per-device count vectors across the mesh
(this IS the monoid merge of the frequency state).

For pathological key-space sizes (product of per-column cardinalities too
large to materialize as a dense count vector) we fall back to host
``np.unique`` over the packed keys, which is the sparse equivalent.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deequ_tpu.data.table import Column, ColumnarTable, DType
from deequ_tpu.ops.scan_engine import SCAN_STATS
from deequ_tpu.parallel.mesh import ROW_AXIS, current_mesh

# dense device count vectors are used up to this key-space size
DENSE_KEYSPACE_LIMIT = 1 << 22


@jax.jit
def _unique_inverse_kernel(v, m):
    """Module-level jitted body (a nested closure would retrace per call)."""
    # primary key: validity (valid rows first), then NaN-ness (all NaNs
    # group together), then the value
    is_nan = v != v
    perm = jnp.lexsort((v, is_nan, ~m))
    sv = v[perm]
    sm = m[perm]
    snan = is_nan[perm]
    neq = (sv[1:] != sv[:-1]) & ~(snan[1:] & snan[:-1])
    neq = jnp.concatenate([jnp.array([True]), neq])
    starts = neq & sm  # a new distinct value, among valid rows only
    ids = jnp.cumsum(starts.astype(jnp.int64))
    codes_sorted = jnp.where(sm, ids, 0)
    inv = jnp.zeros_like(ids).at[perm].set(codes_sorted)
    return sv, starts, inv


def _device_unique_inverse(
    values: np.ndarray, mask: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Sort-based unique on DEVICE (the shuffle-sort of SURVEY §2.14.2):
    one lexsort puts valid values in order, adjacent-compare marks group
    starts, a cumsum assigns dense ids, and a scatter maps them back to row
    order. Host work is only the O(n) fetch + boolean compress — no host
    sort. NaN values (possible when a caller builds columns with explicit
    masks) collapse into ONE distinct group, matching np.unique's
    equal_nan semantics. Returns (uniques, codes) with codes 0 = null,
    1..K = distinct."""
    n = len(values)
    if n == 0:
        return np.empty(0, dtype=values.dtype), np.zeros(0, dtype=np.int64)
    SCAN_STATS.device_sort_passes += 1
    if values.dtype != np.float64:
        # integer/bool columns have no NaN; the kernel's v != v is all-False
        values = np.asarray(values)
    sv, starts, inv = (
        np.asarray(x) for x in _unique_inverse_kernel(values, mask)
    )
    return sv[starts], inv


@jax.jit
def _matrix_rle_kernel(mat, va):
    perm = jnp.lexsort(tuple(mat) + (~va,))  # valid rows first
    smat = mat[:, perm]
    sva = va[perm]
    neq = jnp.any(smat[:, 1:] != smat[:, :-1], axis=0)
    starts = jnp.concatenate([jnp.array([True]), neq]) & sva
    return smat, sva, starts


def _device_matrix_rle(
    code_matrix: np.ndarray, valid: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Run-length-encode the distinct rows of a (k, n) code matrix via one
    device lexsort + adjacent-compare (the sparse/high-cardinality group-by;
    replaces a host np.unique(axis=0) which is a full host sort). Returns
    (groups (k, G), counts (G,)) for valid rows."""
    k, n = code_matrix.shape
    if n == 0:
        return code_matrix[:, :0], np.zeros(0, dtype=np.int64)
    SCAN_STATS.device_sort_passes += 1

    smat, sva, starts = (
        np.asarray(x) for x in _matrix_rle_kernel(code_matrix, valid)
    )
    m = int(sva.sum())  # valid rows occupy the sorted prefix
    positions = np.nonzero(starts)[0]
    groups = smat[:, positions]
    counts = np.diff(np.append(positions, m)).astype(np.int64)
    return groups, counts


def column_key_codes(col: Column) -> Tuple[np.ndarray, List]:
    """Per-row integer codes (0 = null, 1..K = distinct values) + the
    decoded distinct values in code order. Numeric columns build codes via
    a device sort (see _device_unique_inverse); strings are already
    dictionary-encoded at ingest."""
    if col.dtype == DType.STRING:
        codes = col.codes.astype(np.int64) + 1
        return codes, list(col.dictionary)
    if col.dtype == DType.BOOLEAN:
        # 2-value domain: no sort needed at all
        uniques = np.unique(col.values[col.mask])
        lut = {v: i + 1 for i, v in enumerate(uniques.tolist())}
        codes = np.where(
            col.mask, np.where(col.values, lut.get(True, 0), lut.get(False, 0)), 0
        ).astype(np.int64)
        return codes, [bool(v) for v in uniques]
    uniques, codes = _device_unique_inverse(col.values, col.mask)
    if col.dtype == DType.INTEGRAL:
        values = [int(v) for v in uniques]
    else:
        values = [float(v) for v in uniques]
    return codes, values


def _device_bincount(keys: np.ndarray, num_segments: int, mesh) -> np.ndarray:
    """Count key occurrences on device; psum across the mesh if present.

    ``keys`` may contain -1 for rows to ignore (filtered / padding); those
    land in an extra trailing slot that is dropped.
    """
    n = len(keys)
    n_dev = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    padded = max(n_dev, ((n + n_dev - 1) // n_dev) * n_dev)
    if padded != n:
        keys = np.concatenate([keys, np.full(padded - n, -1, dtype=np.int64)])

    def count(k):
        slot = jnp.where(k < 0, num_segments, k)
        counts = jax.ops.segment_sum(
            jnp.ones_like(slot, dtype=jnp.int64), slot, num_segments=num_segments + 1
        )
        if mesh is not None:
            counts = jax.lax.psum(counts, ROW_AXIS)
        return counts

    if mesh is not None:
        fn = jax.jit(
            jax.shard_map(count, mesh=mesh, in_specs=P(ROW_AXIS), out_specs=P())
        )
    else:
        fn = jax.jit(count)
    counts = np.asarray(fn(keys))
    return counts[:num_segments]


def group_counts(
    table: ColumnarTable,
    columns: Sequence[str],
    mesh=None,
    require_any_non_null: bool = True,
) -> Tuple[Dict[tuple, int], int]:
    """Compute the frequency table for a set of grouping columns.

    Returns ``(frequencies, num_rows)`` where frequencies maps a tuple of
    group values (None = null) to its count and num_rows is the number of
    rows with at least one non-null grouping column (reference
    GroupingAnalyzers.scala:53-79).
    """
    if mesh is None:
        mesh = current_mesh()
    SCAN_STATS.grouping_passes += 1
    SCAN_STATS.rows_scanned += table.num_rows

    code_arrays = []
    value_lists = []
    for name in columns:
        codes, values = column_key_codes(table[name])
        code_arrays.append(codes)
        value_lists.append(values)

    radices = [len(v) + 1 for v in value_lists]

    if require_any_non_null and len(columns) > 0:
        any_non_null = np.zeros(table.num_rows, dtype=bool)
        for codes in code_arrays:
            any_non_null |= codes > 0
        num_rows = int(any_non_null.sum())
    else:
        any_non_null = None
        num_rows = table.num_rows

    # Python-int product: mixed-radix packing into int64 silently wraps when
    # the key space exceeds 2^63, so overflow must be checked BEFORE packing
    keyspace = 1
    for radix in radices:
        keyspace *= radix

    frequencies: Dict[tuple, int] = {}
    if keyspace <= DENSE_KEYSPACE_LIMIT:
        keys = np.zeros(table.num_rows, dtype=np.int64)
        for codes, radix in zip(code_arrays, radices):
            keys = keys * radix + codes
        if any_non_null is not None:
            keys = np.where(any_non_null, keys, -1)
        counts = _device_bincount(keys, keyspace, mesh)
        present = np.nonzero(counts)[0]
        present_counts = counts[present]
        for key, cnt in zip(present.tolist(), present_counts.tolist()):
            digits = []
            rest = key
            for radix in reversed(radices):
                digits.append(rest % radix)
                rest //= radix
            digits.reverse()
            group = tuple(
                None if d == 0 else value_lists[i][d - 1]
                for i, d in enumerate(digits)
            )
            frequencies[group] = int(cnt)
    else:
        # sparse path for huge key spaces: device lexsort + run-length
        # encoding over the code matrix — no packing (no overflow regardless
        # of cardinality product), no host sort
        matrix = np.stack(code_arrays, axis=0)
        valid = (
            any_non_null
            if any_non_null is not None
            else np.ones(table.num_rows, dtype=bool)
        )
        groups_mat, counts = _device_matrix_rle(matrix, valid)
        for col_idx in range(groups_mat.shape[1]):
            row = groups_mat[:, col_idx].tolist()
            group = tuple(
                None if d == 0 else value_lists[i][d - 1]
                for i, d in enumerate(row)
            )
            frequencies[group] = int(counts[col_idx])
    return frequencies, num_rows


@dataclass(frozen=True)
class CountStats:
    """Scalar aggregates of the group-count distribution — everything the
    count-only grouping analyzers (Uniqueness, UniqueValueRatio,
    Distinctness, CountDistinct, Entropy) need, WITHOUT materializing the
    frequency table on host. For high-cardinality groupings (#groups ~ n)
    this skips the O(n) group decode + dict build entirely."""

    num_rows: int
    num_groups: int
    singletons: int
    entropy: float


def group_count_stats(
    table: ColumnarTable,
    columns: Sequence[str],
    mesh=None,
    require_any_non_null: bool = True,
) -> CountStats:
    """Count-distribution aggregates for a grouping, group values never
    leaving the device (sparse path) / never decoded (dense path)."""
    if mesh is None:
        mesh = current_mesh()
    SCAN_STATS.grouping_passes += 1
    SCAN_STATS.rows_scanned += table.num_rows

    code_arrays = []
    radices = []
    for name in columns:
        codes, values = column_key_codes(table[name])
        code_arrays.append(codes)
        radices.append(len(values) + 1)

    if require_any_non_null and len(columns) > 0:
        any_non_null = np.zeros(table.num_rows, dtype=bool)
        for codes in code_arrays:
            any_non_null |= codes > 0
        num_rows = int(any_non_null.sum())
    else:
        any_non_null = None
        num_rows = table.num_rows

    keyspace = 1
    for radix in radices:
        keyspace *= radix

    if keyspace <= DENSE_KEYSPACE_LIMIT:
        keys = np.zeros(table.num_rows, dtype=np.int64)
        for codes, radix in zip(code_arrays, radices):
            keys = keys * radix + codes
        if any_non_null is not None:
            keys = np.where(any_non_null, keys, -1)
        counts = _device_bincount(keys, keyspace, mesh)
        counts = counts[counts > 0]
    else:
        matrix = np.stack(code_arrays, axis=0)
        valid = (
            any_non_null
            if any_non_null is not None
            else np.ones(table.num_rows, dtype=bool)
        )
        SCAN_STATS.device_sort_passes += 1
        _smat, sva, starts = _matrix_rle_kernel(matrix, valid)
        # fetch ONLY the boolean vectors — the sorted group matrix stays on
        # device (it is only needed when materializing the full table)
        sva = np.asarray(sva)
        starts = np.asarray(starts)
        m = int(sva.sum())
        positions = np.nonzero(starts)[0]
        counts = np.diff(np.append(positions, m)).astype(np.int64)

    num_groups = int(len(counts))
    singletons = int((counts == 1).sum())
    if num_rows > 0 and num_groups > 0:
        p = counts.astype(np.float64) / num_rows
        entropy = float(-(p * np.log(p)).sum())
    else:
        entropy = float("nan")
    return CountStats(num_rows, num_groups, singletons, entropy)
