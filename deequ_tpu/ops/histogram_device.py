"""Histogram / segment-fold kernel tier: scatter, one-hot-MXU, Pallas.

Every remaining compute risk in the engine has the same shape — XLA's
TPU ``scatter`` lowering: the selection kernel's three bincount passes
(``ops/select_device.py``), the grouping path's scatter-add bincounts
and segment reductions (``ops/segment.py``), and the HLL register fold
before round 5 fixed it. The fix-idiom is already proven in this repo:
``ops/hll.py`` replaced a scatter-max register fold (~20 ns/row on the
bench chip) with a blocked one-hot bf16 MXU matmul for ~10x. This
module generalizes that idiom into a routed KERNEL TIER every
histogram-shaped reduction shares:

- ``"scatter"`` — the XLA lowering the engine has always run
  (``zeros.at[seg].add(w)``): the baseline every other variant is
  hard-asserted bit-exact against;
- ``"onehot"`` — the factored blocked one-hot matmul: a segment id
  splits into (hi, lo) digits of a B-wide radix, and the counts matrix
  is ``one_hot(hi)^T @ one_hot(lo)`` accumulated over row blocks. On
  the MXU the planes ride bf16 (products are exactly 0/1); on CPU they
  ride f32 (bf16 is software-emulated there — measured 8x SLOWER than
  scatter, while the f32 sgemm form wins 5-8x on narrow keyspaces).
  Per-block accumulation is f32 (block <= 2^18 rows, so every count
  fits f32's 2^24 integer range exactly) folded into an integer
  accumulator per block — counts are EXACT at any total row count;
- ``"pallas"`` — a Mosaic kernel for keyspaces too wide for the
  one-hot planes to fit: grid over (segment blocks x row blocks), each
  step reduces a compare-against-iota tile into its output block (a
  VPU formulation — no scatter, no sorted structure). GUARDED: round 4
  measured this environment's tunnel compiler SIGABRTing on
  grid-accumulation Pallas kernels (see ops/hll.py), so the variant
  never resolves by default — it is reachable only through the
  DEEQU_TPU_HIST_VARIANT force knob and runs interpret-mode on CPU
  backends (the correctness harness tier-1 exercises).

Routing is a PLAN decision, not a call-site decision: the planner
(``ops/scan_plan.py``) resolves a ``hist_variant`` per scan attempt via
``ops/device_policy.resolve_hist_variant`` (keyspace width / row count
/ platform / force knob) and binds it around the traced update via
:func:`active_hist_variant`; host-driven kernels (``ops/segment.py``)
resolve per dispatch through the same policy fn. ``bincount`` reads the
ambient variant so traced code never threads variant arguments — the
traced-program caches stay correct because every consumer keys its
program on the resolved variant. The static twin of the routing is the
``plan-hist-scatter`` lint rule (deequ_tpu/lint/plan_lint.py): a plan
claiming a matmul/pallas hist variant must trace to a jaxpr with ZERO
``scatter-add`` primitives.

Exactness contract (docs/kernels.md): all three variants produce
IDENTICAL integer histograms — one-hot products are 0/1 in either
plane dtype, per-block f32 accumulation is exact below 2^24, and the
cross-block fold is integer addition. The ``kernelv`` tier-1 suite
pins parity against ``np.bincount`` across dtypes, widths, block
boundaries, and null slots.
"""

from __future__ import annotations

import contextvars
from contextlib import contextmanager
from typing import Optional

import numpy as np

#: the variants a histogram dispatch can resolve to (order = preference
#: order for documentation; resolution lives in device_policy)
HIST_VARIANTS = ("scatter", "onehot", "pallas")

#: one-hot radix width: 128 matches both the MXU/VPU lane count and the
#: CPU sgemm sweet spot measured in round 14 (B > 128 only widens the
#: matmul without narrowing the hi plane)
_ONEHOT_LANES = 128

#: row-block sizing: planes are (block, A) + (block, B) elements; the
#: budget caps their footprint (~128MB f32 at 2^25 elements) so a
#: vmapped consumer (the batched selection kernel) stays inside HBM,
#: while the floor keeps each matmul big enough to amortize dispatch
_ONEHOT_PLANE_BUDGET = 1 << 25
_ONEHOT_MAX_BLOCK = 1 << 18
_ONEHOT_MIN_BLOCK = 1 << 12

# -- active-variant seam ------------------------------------------------------

#: ambient variant for traced histogram calls. A ContextVar (not a bare
#: module global): serve workers trace programs from their own threads,
#: and a variant bound for one attempt must never leak into another
#: thread's trace.
_ACTIVE_VARIANT: contextvars.ContextVar = contextvars.ContextVar(
    "deequ_tpu_hist_variant", default="scatter"
)


def current_hist_variant() -> str:
    """The variant ambient histogram calls resolve to ("scatter" unless
    a planner bound one — see :func:`active_hist_variant`)."""
    return _ACTIVE_VARIANT.get()


@contextmanager
def active_hist_variant(variant: str):
    """Bind the ambient histogram variant for the duration of a traced
    update call (the planner wraps resolved select updates with this, so
    the binding is live exactly while THAT op's portion of the program
    traces — never at dispatch time, where it would be dead weight)."""
    if variant not in HIST_VARIANTS:
        raise ValueError(
            f"hist variant must be one of {HIST_VARIANTS}, got {variant!r}"
        )
    token = _ACTIVE_VARIANT.set(variant)
    try:
        yield
    finally:
        _ACTIVE_VARIANT.reset(token)


def pallas_available() -> bool:
    """True when jax ships the Pallas frontend this process can trace
    (CPU backends run it interpret-mode). Deliberately NOT a statement
    about the tunnel compiler accepting the lowered kernel — that is
    exactly the round-4 SIGABRT risk the policy never auto-routes into."""
    try:
        from jax.experimental import pallas  # noqa: F401
    # deequ-lint: ignore[bare-except] -- availability probe: absence of the pallas frontend IS the answer
    except Exception:  # noqa: BLE001 — jax built without pallas
        return False
    return True


# -- kernels ------------------------------------------------------------------


def _onehot_geometry(num_segments: int):
    """(A, B, block): hi/lo radix split + row block for one keyspace."""
    B = min(_ONEHOT_LANES, max(8, int(num_segments)))
    A = (int(num_segments) + B - 1) // B
    block = max(
        _ONEHOT_MIN_BLOCK,
        min(_ONEHOT_MAX_BLOCK, _ONEHOT_PLANE_BUDGET // (A + B)),
    )
    return A, B, block


def _plane_dtype(xp):
    """One-hot plane dtype: bf16 rides the MXU on accelerators; CPU
    backends keep f32 (bf16 is software-emulated there — measured ~8x
    slower than the f32 sgemm it replaces). Products are exactly 0/1
    either way, so the choice is pure speed, never accuracy."""
    import jax

    if jax.default_backend() == "cpu":
        return xp.float32
    return xp.bfloat16


def bincount_onehot(seg, num_segments: int, xp, weights=None, dtype=None):
    """Bincount (or integer-weighted segment sum) as a blocked factored
    one-hot matmul — the ops/hll.py MXU idiom generalized.

    ``seg`` is an (n,) integer array; counts cover ``[0, num_segments)``
    with out-of-range ids (negative sentinels, the trailing invalid
    slot a caller did not allocate) DROPPED — exactly the scatter
    path's semantics. Exactness: per-block f32 accumulation never
    exceeds the block row count (< 2^24), and blocks fold in integer
    arithmetic; with ``weights`` the caller must keep per-segment
    per-block totals below 2^24 (the engine only ever folds ones)."""
    dtype = dtype or xp.int32
    A, B, block = _onehot_geometry(num_segments)
    n = seg.shape[0]
    plane = _plane_dtype(xp)
    import jax

    seg = seg.astype(xp.int32)
    counts = xp.zeros((A, B), dtype=dtype)
    for s in range(0, n, block):
        sb = seg[s:s + block]
        hi = sb // B  # floor division: negatives land < 0 -> zero row
        lo = sb - hi * B
        oh = jax.nn.one_hot(hi, A, dtype=plane)
        ol = jax.nn.one_hot(lo, B, dtype=plane)
        if weights is not None:
            # the weighted lo plane rides f32 regardless of backend: a
            # bf16 plane would round integer weights above 256 and break
            # the exact-counts contract (the hi plane stays 0/1, so only
            # this operand widens; the matmul promotes to f32)
            ol = ol.astype(xp.float32) * weights[
                s:s + block
            ].astype(xp.float32)[:, None]
        counts = counts + xp.matmul(
            oh.T, ol, preferred_element_type=xp.float32
        ).astype(dtype)
    return counts.reshape(-1)[:num_segments]


# pallas tile geometry: multiples of the (8, 128) f32 TPU tile so the
# same kernel shape lowers on Mosaic when the force knob ever runs it
# chip-side; interpret mode (CPU) accepts them regardless
_PALLAS_SEG_BLOCK = 512
_PALLAS_ROW_BLOCK = 1024


def bincount_pallas(
    seg,
    num_segments: int,
    xp,
    weights=None,
    dtype=None,
    interpret: Optional[bool] = None,
):
    """Bincount as a Pallas grid kernel: grid (segment blocks, row
    blocks), each step reducing a compare-against-iota tile into its
    output block — O(n * num_segments) VPU compares with NO scatter and
    no sorted structure, the formulation for keyspaces too wide for the
    one-hot planes. ``interpret`` defaults to True off-TPU (the tier-1
    correctness harness); chip-side lowering stays behind the force
    knob (round-4 tunnel-compiler SIGABRT risk, module doc)."""
    import jax
    from jax.experimental import pallas as pl

    dtype = dtype or xp.int32
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = seg.shape[0]
    seg = seg.astype(xp.int32)
    w = None if weights is None else weights.astype(xp.int32)
    nrb = max(1, (n + _PALLAS_ROW_BLOCK - 1) // _PALLAS_ROW_BLOCK)
    pad = nrb * _PALLAS_ROW_BLOCK - n
    if pad:
        # -1 matches no segment id: padding rows are dropped like any
        # other out-of-range sentinel
        seg = xp.concatenate([seg, xp.full((pad,), -1, xp.int32)])
        if w is not None:
            w = xp.concatenate([w, xp.zeros((pad,), xp.int32)])
    nsb = (num_segments + _PALLAS_SEG_BLOCK - 1) // _PALLAS_SEG_BLOCK
    seg2 = seg.reshape(nrb, _PALLAS_ROW_BLOCK)
    args = [seg2]
    in_specs = [
        pl.BlockSpec((1, _PALLAS_ROW_BLOCK), lambda j, k: (k, 0)),
    ]
    if w is not None:
        args.append(w.reshape(nrb, _PALLAS_ROW_BLOCK))
        in_specs.append(
            pl.BlockSpec((1, _PALLAS_ROW_BLOCK), lambda j, k: (k, 0))
        )

    def kernel(seg_ref, *rest):
        w_ref, out_ref = (
            (rest[0], rest[1]) if len(rest) == 2 else (None, rest[0])
        )
        k = pl.program_id(1)

        @pl.when(k == 0)
        def _():
            out_ref[...] = xp.zeros_like(out_ref)

        s = seg_ref[...]  # (1, row_block)
        base = pl.program_id(0) * _PALLAS_SEG_BLOCK
        # TPU iota must be >= 2D (pallas guide); (seg_block, 1) then
        # broadcast against the (1, row_block) ids
        ids = base + jax.lax.broadcasted_iota(
            xp.int32, (_PALLAS_SEG_BLOCK, 1), 0
        )
        match = (s == ids).astype(xp.int32)  # (seg_block, row_block)
        if w_ref is not None:
            match = match * w_ref[...]
        # pin the accumulator dtype: jnp.sum promotes i32 to the default
        # int (i64 under x64), which the i32 out ref would reject
        out_ref[...] += xp.sum(
            match, axis=1, keepdims=True, dtype=xp.int32
        ).T

    out = pl.pallas_call(
        kernel,
        grid=(nsb, nrb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, _PALLAS_SEG_BLOCK), lambda j, k: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((nsb, _PALLAS_SEG_BLOCK), xp.int32),
        interpret=interpret,
    )(*args)
    return out.reshape(-1)[:num_segments].astype(dtype)


def bincount_scatter(seg, num_segments: int, xp, weights=None, dtype=None):
    """The XLA scatter-add lowering (baseline variant). The tier
    contract is explicit: ids outside [0, num_segments) are DROPPED,
    never wrapped — jax normalizes negative ``.at`` indices numpy-style
    before any out-of-bounds mode applies, so negatives are pre-mapped
    to an out-of-range sentinel that ``mode="drop"`` then discards
    (engine callers all pre-map invalid rows to an allocated trailing
    slot anyway; the sentinel only defends the contract). The
    unweighted form adds a scalar 1 rather than an all-ones operand
    (measured ~2x faster on CPU — the historical select-kernel
    formulation)."""
    dtype = dtype or xp.int32
    zeros = xp.zeros((num_segments,), dtype=dtype)
    safe = xp.where(seg < 0, num_segments, seg)
    if weights is None:
        return zeros.at[safe].add(1, mode="drop")
    return zeros.at[safe].add(weights.astype(dtype), mode="drop")


_KERNELS = {
    "scatter": bincount_scatter,
    "onehot": bincount_onehot,
    "pallas": bincount_pallas,
}


def bincount_variant(
    variant: str, seg, num_segments: int, xp, weights=None, dtype=None
):
    """Histogram under an EXPLICIT variant — the host-driven kernels
    (ops/segment.py) resolve per dispatch via device_policy and key
    their jit caches on the resolved variant, so the ambient-binding
    seam (which exists for PLAN-routed traced code) would be dead
    weight there."""
    if variant not in HIST_VARIANTS:
        raise ValueError(
            f"hist variant must be one of {HIST_VARIANTS}, got {variant!r}"
        )
    return _KERNELS[variant](
        seg, num_segments, xp, weights=weights, dtype=dtype
    )


def bincount(seg, num_segments: int, xp, weights=None, dtype=None):
    """Histogram of integer segment ids under the AMBIENT variant
    (:func:`current_hist_variant`; "scatter" unless a planner bound one).
    All variants share one contract: counts over ``[0, num_segments)``,
    out-of-range ids dropped, exact integer results. Host numpy callers
    always take ``np.bincount`` — the variants are device formulations
    and the host path is already the latency-regime answer."""
    if xp is np:
        slots = np.where(
            (seg >= 0) & (seg < num_segments), seg, num_segments
        )
        if weights is None:
            counts = np.bincount(slots, minlength=num_segments + 1)
        else:
            # np.bincount's weighted form accumulates float64 — exact
            # for the small integer weights this tier admits; cast back
            counts = np.bincount(
                slots, weights=weights, minlength=num_segments + 1
            )
        return counts[:num_segments].astype(dtype or np.int64)
    return _KERNELS[current_hist_variant()](
        seg, num_segments, xp, weights=weights, dtype=dtype
    )
