"""Per-dictionary lookup-table memo.

String columns are dictionary-encoded; device string ops work by building a
host LUT over the dictionary (hashes, regex hits, lengths, type classes)
and gathering it by code on device. Those LUTs are built at TRACE time, so
any retrace (string programs are not globally cacheable — the LUT itself is
baked into the trace) used to redo O(cardinality) host work per run: for a
1M-entry dictionary that dominated wall time. The memo keys on the
dictionary array's identity (guarded by a weakref so a recycled id cannot
alias) plus a kind string naming the derivation.
"""

from __future__ import annotations

import weakref
from typing import Callable, Dict, Tuple

import numpy as np

_MAX_ENTRIES = 64
# (id(dictionary), kind) -> (weakref to dictionary, lut); insertion order
# doubles as LRU recency
_MEMO: Dict[Tuple[int, str], Tuple[weakref.ref, np.ndarray]] = {}
# same keying for device-resident LUTs (padded, transferred once)
_DEVICE_MEMO: Dict[Tuple[int, str, object], Tuple[weakref.ref, object]] = {}


def _next_pow2(n: int) -> int:
    return 1 << max(int(n - 1).bit_length(), 0) if n > 1 else 1


def pad_pow2(arr: np.ndarray) -> np.ndarray:
    """Pad a LUT to the next power-of-two length (stable shape buckets so
    jitted programs re-compile only when cardinality crosses a power of
    two, not on every dictionary size)."""
    n = max(len(arr), 1)
    target = _next_pow2(n)
    if len(arr) == target:
        return arr
    out = np.zeros(target, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _mesh_value_key(mesh):
    """Meshes are keyed by VALUE (shape + axis names + device list), never
    by object identity: default_mesh() builds a fresh (equal) Mesh per run,
    and an id() key would both miss every run and risk aliasing a GC'd
    mesh's recycled id. With no mesh, the key carries the default-device
    override: the CPU-fallback path (scan_engine) runs under
    ``jax.default_device(cpu)``, and a memoized array COMMITTED to the
    accelerator must not be handed to a scan that is fleeing it."""
    if mesh is None:
        import jax

        default = getattr(jax.config, "jax_default_device", None)
        return None if default is None else ("default_device", str(default))
    return (mesh.devices.shape, tuple(mesh.axis_names), tuple(mesh.devices.flat))


def dictionary_lut_device(
    dictionary: np.ndarray,
    kind: str,
    builder: Callable[[np.ndarray], np.ndarray],
    mesh=None,
):
    """Device-resident, pow2-padded LUT, memoized per (dictionary identity,
    kind, mesh value): the array transfers to the device ONCE and is then
    passed to jitted scans as an argument — never baked into the trace as a
    megabyte constant, so string programs stay reusable and re-runs ship
    no dictionary bytes."""
    import jax

    key = (id(dictionary), kind, _mesh_value_key(mesh))
    entry = _DEVICE_MEMO.pop(key, None)
    if entry is not None and entry[0]() is dictionary:
        _DEVICE_MEMO[key] = entry
        return entry[1]
    host = pad_pow2(dictionary_lut(dictionary, kind, builder))
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        dev = jax.device_put(host, NamedSharding(mesh, PartitionSpec()))
    else:
        dev = jax.device_put(host)
    try:
        ref = weakref.ref(dictionary)
    except TypeError:
        return dev
    _DEVICE_MEMO[key] = (ref, dev)
    while len(_DEVICE_MEMO) > _MAX_ENTRIES:
        _DEVICE_MEMO.pop(next(iter(_DEVICE_MEMO)))
    return dev


def dictionary_lut(
    dictionary: np.ndarray,
    kind: str,
    builder: Callable[[np.ndarray], np.ndarray],
) -> np.ndarray:
    """Return builder(dictionary), memoized per (dictionary identity, kind)."""
    key = (id(dictionary), kind)
    entry = _MEMO.pop(key, None)
    if entry is not None and entry[0]() is dictionary:
        _MEMO[key] = entry  # re-insert: most recently used
        return entry[1]
    lut = builder(dictionary)
    try:
        ref = weakref.ref(dictionary)
    except TypeError:  # plain lists in tests; no identity guard possible
        return lut
    _MEMO[key] = (ref, lut)
    while len(_MEMO) > _MAX_ENTRIES:
        _MEMO.pop(next(iter(_MEMO)))
    return lut
