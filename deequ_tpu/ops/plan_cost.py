"""The plan COST MODEL — one predicted-cost function for a scan plan.

Before round 19 the knowledge of "what makes a plan expensive" was
scattered as unrelated constants: the one-hot histogram crossover caps
(ops/device_policy — now the ``DEEQU_TPU_HIST_CPU_CAP`` /
``DEEQU_TPU_HIST_ACCEL_CAP`` knobs), the host-vs-device grouping
threshold (``DEEQU_TPU_HOST_GROUP_LIMIT``, ops/segment), and the
serving coalescer's batch shaping (``DEEQU_TPU_SERVE_MAX_BATCH``). This
module unifies them behind :class:`PlanCostModel`: a deliberately small
closed-form predictor in abstract COST UNITS (~host-equivalent work;
only ordering and ratios are meaningful, never wall seconds).

Two consumers:

- the serving ADMISSION tier (serve/admission.py): ``retry_after_s`` is
  derived from the queue's summed predicted cost over the observed
  cost-drain rate — a queue of 3 heavy profiling suites now schedules a
  later retry than 3 trivial completeness checks at the same depth —
  and the brownout ladder reads queued-cost pressure alongside queue
  depth;
- the test/bench surface: cost-model MONOTONICITY (a wider or deeper
  plan never predicts cheaper) is a tier-1 contract, because admission
  decisions keyed on a non-monotone predictor would invert under load.

The model's inputs are :class:`PlanFeatures`; the output
:class:`PlanCost` splits transfer / compute / fetch and counts device
dispatches (each dispatch carries a fixed launch overhead — the same
latency term the round-14 crossover sweep measured).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

#: fixed per-dispatch launch overhead, in cost units (the ~0.1s tunnel
#: round trip of BASELINE config 1, scaled into the abstract unit)
DISPATCH_OVERHEAD = 4096.0


@dataclass(frozen=True)
class PlanFeatures:
    """What the predictor sees of a plan. Every field is a size or a
    count; the model is nondecreasing in each of them."""

    #: rows the plan scans (per tenant)
    rows: int
    #: plain fused scan ops (monoid folds: completeness, mean, ...)
    scan_ops: int = 0
    #: device-sort ops (KLL/quantile on the sort path): O(n log n)
    sort_ops: int = 0
    #: selection-kernel ops (the histogram selection path): O(n) passes
    select_ops: int = 0
    #: histogram / one-hot segment-fold widths, one per hist dispatch
    hist_widths: Tuple[int, ...] = ()
    #: dense grouping keyspaces, one per grouping pass
    group_keyspaces: Tuple[int, ...] = ()
    #: tenant-axis width (a packed serving batch scales per-tenant work)
    tenants: int = 1
    #: columns riding the encoded (code-plane + LUT decode) ingest
    encoded_columns: int = 0


@dataclass(frozen=True)
class PlanCost:
    """Predicted cost split (abstract units — ordering is the API)."""

    transfer: float
    compute: float
    fetch: float
    dispatches: int

    @property
    def total(self) -> float:
        return (
            self.transfer + self.compute + self.fetch
            + DISPATCH_OVERHEAD * self.dispatches
        )


class PlanCostModel:
    """The predictor. Reads the envcfg knobs it unifies at PREDICT time
    (not construction), so a knob flipped between suites reprices the
    next admission — the registry_snapshot shows the same values the
    model used."""

    def __init__(self, platform: Optional[str] = None):
        self._platform = platform

    def _resolve_platform(self) -> str:
        if self._platform is not None:
            return self._platform
        try:
            import jax

            return jax.default_backend()
        # deequ-lint: ignore[bare-except] -- no resolvable backend: the model prices as CPU rather than refusing to price at all
        except Exception:  # noqa: BLE001
            return "cpu"

    def predict(self, f: PlanFeatures) -> PlanCost:
        """Nondecreasing in every :class:`PlanFeatures` field — the
        monotonicity contract (tier-1 ``plan`` tests): every term below
        is a nonnegative, nondecreasing function of its inputs, and
        features only ever ADD terms."""
        from deequ_tpu.ops.device_policy import hist_accel_cap, hist_cpu_cap
        from deequ_tpu.ops.segment import host_group_limit

        rows = max(int(f.rows), 0)
        tenants = max(int(f.tenants), 1)
        platform = self._resolve_platform()
        cap = hist_cpu_cap() if platform == "cpu" else hist_accel_cap()
        host_limit = host_group_limit()

        # transfer: pack + put of the value/mask planes; an encoded
        # column adds its code plane + LUT
        transfer = float(rows) * (4.0 + 2.0 * max(f.encoded_columns, 0))

        # compute: one linear pass per fused scan/select op; device
        # sorts pay the n log n factor
        log_rows = math.log2(rows + 2)
        compute = float(rows) * (
            max(f.scan_ops, 0)
            + 2.0 * max(f.select_ops, 0)
            + 4.0 * max(f.sort_ops, 0) * log_rows
        )

        # fetch: the fused pass's ONE state-vector fetch
        fetch = 64.0 * (max(f.scan_ops, 0) + max(f.select_ops, 0)
                        + max(f.sort_ops, 0))
        dispatches = 1 if (f.scan_ops or f.select_ops or f.sort_ops) else 0

        # histogram dispatches: past the variant crossover cap the
        # one-hot kernel's plane count stops amortizing (the knob the
        # round-14 sweep priced) — model it as a 4x step, still
        # nondecreasing in width
        for w in f.hist_widths:
            w = max(int(w), 0)
            dispatches += 1
            compute += float(rows) + (float(w) if w <= cap else 4.0 * w)
            fetch += float(w)

        # grouping passes: at or below the host-group limit the counts
        # fold on host (no dispatch); above it, one device bincount +
        # one O(keyspace) counts fetch per pass
        for k in f.group_keyspaces:
            k = max(int(k), 0)
            compute += float(rows) + float(k)
            if rows > host_limit:
                dispatches += 1
                fetch += float(k)

        # the tenant axis multiplies per-tenant work, not dispatches —
        # that IS the coalescer's economy, which is why admission wants
        # cost, not depth: K cheap tenants amortize, K heavy ones don't
        return PlanCost(
            transfer=transfer * tenants,
            compute=compute * tenants,
            fetch=fetch * tenants,
            dispatches=dispatches,
        )

    def estimate_suite(
        self, analyzers: Sequence, rows: int, tenants: int = 1
    ) -> PlanCost:
        """Price one tenant suite from its analyzer list — the
        admission-time entry (serve/service.py calls this per submit).
        Grouping keyspaces are unknown before the scan, so each grouping
        pass prices at its worst admissible case, ``min(rows + 1, dense
        limit)`` — monotone in rows and never an underestimate that
        would let a heavy suite skip the brownout ladder."""
        from deequ_tpu.analyzers.runner import _is_grouping_shared
        from deequ_tpu.ops.segment import DENSE_KEYSPACE_LIMIT

        scan = sort = select = 0
        widths = []
        keyspaces = []
        encoded = 0
        for a in analyzers:
            name = type(a).__name__
            if _is_grouping_shared(a):
                keyspaces.append(min(int(rows) + 1, DENSE_KEYSPACE_LIMIT))
            elif name in ("Histogram",):
                widths.append(min(int(rows) + 1, 1 << 12))
            elif "Quantile" in name or "KLL" in name:
                from deequ_tpu.ops.scan_plan import select_kernel_enabled

                try:
                    kernel = select_kernel_enabled(None)
                # deequ-lint: ignore[bare-except] -- a malformed env knob prices the sort path (the dearer estimate); the engine still raises typed at its own resolve
                except Exception:  # noqa: BLE001
                    kernel = False
                if kernel:
                    select += 1
                else:
                    sort += 1
            else:
                scan += 1
        return self.predict(PlanFeatures(
            rows=int(rows),
            scan_ops=scan,
            sort_ops=sort,
            select_ops=select,
            hist_widths=tuple(widths),
            group_keyspaces=tuple(keyspaces),
            tenants=tenants,
            encoded_columns=encoded,
        ))


#: the process-default model (admission + benches read through this)
PLAN_COST_MODEL = PlanCostModel()
