"""Pallas TPU kernels for sketch hot loops.

The engine's reductions fuse well under plain XLA, but the HLL register
fold is a scatter-max — XLA lowers ``segment_max`` to a serial scatter on
TPU. This kernel reformulates it as a dense VPU compare-select over
(registers, 8, 128) tiles streamed through VMEM, accumulating the register
file across sequential grid steps (init on step 0 via ``pl.when``).

TPU constraints honored (and discovered the hard way on the tunnel
compiler): int32 blocks must tile to (8, 128); bool ``jnp.where`` selects
recurse in this Mosaic version, so selection is arithmetic; everything is
64-bit-free.

STATUS: correct under interpret mode (tested); native TPU lowering is
blocked by this environment's remote compile helper, which SIGABRTs on any
grid-accumulation kernel (minimal repro: a 2-step grid maximum over (8,128)
int32 tiles with pl.when init). The engine therefore keeps XLA segment_max
as the TPU production path and uses this kernel only where Pallas compiles.
Enable with DEEQU_TPU_PALLAS=1.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

# rows processed per grid step: one (8, 128) int32 tile
TILE_ROWS = 8 * 128


def pallas_enabled() -> bool:
    return os.environ.get("DEEQU_TPU_PALLAS", "0") == "1"


def _fold_kernel(idx_ref, rank_ref, out_ref, *, num_registers: int):
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    idx = idx_ref[:]    # (8, 128) i32
    rank = rank_ref[:]  # (8, 128) i32
    regs = jax.lax.broadcasted_iota(
        jnp.int32, (num_registers, 8, 128), 0
    )
    # arithmetic select (bool jnp.where recurses in this Mosaic lowering)
    contrib = (idx[None, :, :] == regs).astype(jnp.int32) * rank[None, :, :]
    block_max = jnp.max(contrib, axis=(1, 2))  # (R,)
    update = jnp.broadcast_to(block_max[None, :], out_ref.shape)
    out_ref[:] = jnp.maximum(out_ref[:], update)


@functools.partial(jax.jit, static_argnames=("num_registers", "interpret"))
def hll_fold(idx, rank, num_registers: int = 512, interpret: bool = False):
    """Fold (idx, rank) pairs into an HLL register file: out[r] = max rank
    over rows with idx == r (invalid rows must carry rank 0).
    ``num_registers`` must be a multiple of 128 (HLL p >= 7)."""
    from jax.experimental import pallas as pl

    assert num_registers % 128 == 0, "num_registers must be a lane multiple"
    n = idx.shape[0]
    pad = (-n) % TILE_ROWS
    idx2 = jnp.pad(idx.astype(jnp.int32), (0, pad)).reshape(-1, 128)
    rank2 = jnp.pad(rank.astype(jnp.int32), (0, pad)).reshape(-1, 128)
    grid = (idx2.shape[0] // 8,)

    out = pl.pallas_call(
        functools.partial(_fold_kernel, num_registers=num_registers),
        out_shape=jax.ShapeDtypeStruct((8, num_registers), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8, 128), lambda i: (i, 0)),
            pl.BlockSpec((8, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, num_registers), lambda i: (0, 0)),
        interpret=interpret,
    )(idx2, rank2)
    return out[0]
