"""Device-fault policy for the scan engine — boundary classification,
compute watchdog, fault-injection seam, and backend health.

The reference inherits fault tolerance from Spark (a lost task re-executes
from lineage, so deequ never sees the fault); native-compilation engines
that trade that recovery model for speed get nothing (Flare,
arXiv:1703.08219). This module is the engine-side half of ours:

- :func:`device_call` wraps every blocking device call at one of the
  four boundaries (``transfer`` / ``trace`` / ``execute`` / ``fetch``),
  converting raw jaxlib errors into the typed taxonomy
  (``exceptions.py``) and — when a wall-clock ``deadline`` is set —
  running the call on a watchdog worker thread so a HUNG device becomes
  a typed ``DeviceHangException`` instead of a frozen run. With the
  on-device partial fold the ``fetch`` boundary (the scan's ONE
  device->host round trip) is where async execute faults surface, so
  the watchdog and the fault classification both stay armed there;
- :func:`install_scan_fault_hook` is the deterministic injection seam the
  resilience tests drive (``resilience/faults.py:FaultInjectingScanHook``);
- :class:`DeviceHealth` counts classified faults so a backend that
  REPEATEDLY faults routes subsequent scans straight to the CPU fallback
  instead of re-failing first every time;
- :class:`MeshHealth` is the same idea at MESH-MEMBER granularity: faults
  attributable to one chip (``DeviceException.device_ids``) cost that
  chip, not the backend — quarantined chips are excluded from future
  meshes up front, with half-open probes readmitting them periodically;
- :func:`resolve_hist_variant` is the histogram KERNEL-TIER policy
  (round 14, ops/histogram_device.py): which bincount/segment-fold
  formulation (scatter / one-hot matmul / pallas) a dispatch should
  run, decided from keyspace width, row count, and platform — the same
  driver the fault ladder already trusts for backend choices decides
  kernel shape too.

The degradation policies themselves (chunk bisection, degraded-mesh
re-sharding, CPU re-jit) live in ``ops/scan_engine.py:run_scan`` — this
module only decides *what* failed and *whether* the backend (or the
chip) is still trusted.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

from deequ_tpu.exceptions import (
    DeviceException,
    DeviceHangException,
    classify_device_error,
)
from deequ_tpu.obs.recorder import current_recorder

# -- fault-injection seam ----------------------------------------------------

# The installed hook is called as hook(boundary, ctx) immediately before
# the wrapped device call runs (INSIDE the watchdog, so injected hangs are
# converted like real ones). ctx carries {"scan_id", "attempt",
# "chunk_index", "fallback"} — see FaultInjectingScanHook.
_SCAN_FAULT_HOOK: Optional[Callable[[str, Dict[str, Any]], None]] = None


def install_scan_fault_hook(hook) -> Optional[Callable]:
    """Install (or, with None, remove) the scan-engine fault hook.
    Returns the previously installed hook so tests can restore it."""
    global _SCAN_FAULT_HOOK
    previous = _SCAN_FAULT_HOOK
    _SCAN_FAULT_HOOK = hook
    return previous


def current_scan_fault_hook():
    return _SCAN_FAULT_HOOK


# -- histogram kernel-variant policy -----------------------------------------

#: widest keyspace the one-hot matmul accepts on a CPU backend: the f32
#: sgemm form wins 5-8x over XLA's CPU scatter up to here (round-14
#: sweep, BENCHMARKS.md) and LOSES beyond — the crossover is sharp
#: because the matmul's work is O(n * num_segments) while scatter's is
#: O(n)
HIST_ONEHOT_CPU_MAX_SEGMENTS = 32

#: widest keyspace the one-hot matmul accepts on an accelerator: the
#: factored (hi, lo) planes are n x (A + B) bf16 with A*B >= segments,
#: so 2^17 keeps A, B <= 1024 — covering the selection kernel's 2^16
#: pass-1 histogram and its default-k pass-2/3 width ((k+2)*256+1,
#: k=256) while bounding MXU work at ~128 MACs/row/lane
HIST_ONEHOT_MXU_MAX_SEGMENTS = 1 << 17

#: below this row count the dispatch itself dominates any kernel-shape
#: delta (the BASELINE config-1 latency regime) — the resolver keeps
#: the scatter baseline rather than trading noise
HIST_MIN_ROWS = 1 << 14


def hist_cpu_cap() -> int:
    """The CPU one-hot crossover cap: ``DEEQU_TPU_HIST_CPU_CAP`` when
    set, else the module constant (which tests may monkeypatch — the
    ``host_group_limit()`` idiom from ops/segment.py). Also a plan-cost
    model input (ops/plan_cost.py)."""
    from deequ_tpu.envcfg import env_value

    configured = env_value("DEEQU_TPU_HIST_CPU_CAP")
    return HIST_ONEHOT_CPU_MAX_SEGMENTS if configured is None else configured


def hist_accel_cap() -> int:
    """The accelerator one-hot crossover cap: ``DEEQU_TPU_HIST_ACCEL_CAP``
    when set, else the module constant."""
    from deequ_tpu.envcfg import env_value

    configured = env_value("DEEQU_TPU_HIST_ACCEL_CAP")
    return HIST_ONEHOT_MXU_MAX_SEGMENTS if configured is None else configured


def resolve_hist_variant(
    widths,
    rows: Optional[int] = None,
    platform: Optional[str] = None,
    force: Optional[str] = None,
) -> str:
    """Resolve the histogram kernel variant for one dispatch or plan.

    ``widths`` — the histogram segment-counts the consumer will run
    (a plan lists every pass; a host-driven kernel its one width); the
    resolution is over the MAX, so a multi-pass program never mixes
    variants (the plan-hist-scatter lint contract is per program).
    ``rows`` — rows per dispatch; ``None`` means "large" (resident
    chunks). ``force`` overrides everything (explicit argument first,
    then the DEEQU_TPU_HIST_VARIANT env knob — the A/B hatch).

    The pallas variant NEVER resolves by default: this environment's
    tunnel compiler SIGABRTs on grid-accumulation Pallas kernels
    (round 4, ops/hll.py), so it is force-knob-only until a chip-side
    session proves the lowering — exactly how the chip acceptances are
    banked as pending-parallel-hw."""
    from deequ_tpu.envcfg import env_value

    if force is None:
        force = env_value("DEEQU_TPU_HIST_VARIANT")
    if force is not None:
        if force not in ("scatter", "onehot", "pallas"):
            raise ValueError(
                "hist variant must be one of ('scatter', 'onehot', "
                f"'pallas'), got {force!r}"
            )
        return force
    widths = tuple(int(w) for w in widths)
    if not widths:
        return "scatter"
    if rows is not None and rows < HIST_MIN_ROWS:
        return "scatter"
    if platform is None:
        import jax

        platform = jax.default_backend()
    cap = hist_cpu_cap() if platform == "cpu" else hist_accel_cap()
    if max(widths) <= cap:
        return "onehot"
    return "scatter"


# -- compute watchdog --------------------------------------------------------


def default_device_deadline() -> Optional[float]:
    """Process-wide watchdog deadline (seconds) from
    ``DEEQU_TPU_DEVICE_DEADLINE`` (envcfg registry); unset/empty/0
    disables the watchdog, malformed values raise typed
    ``EnvConfigError`` (pre-round-10 this silently disarmed the
    watchdog a deployment thought it had armed)."""
    from deequ_tpu.envcfg import env_value

    return env_value("DEEQU_TPU_DEVICE_DEADLINE")


def default_shard_deadline() -> Optional[float]:
    """Process-wide per-shard dispatch deadline (seconds) from
    ``DEEQU_TPU_SHARD_DEADLINE`` (envcfg registry), armed only on
    MULTI-CHIP mesh scans: a straggling chip that stalls a collective
    past it raises ``DeviceHangException`` (recorded as a
    ``mesh_straggler`` event) instead of freezing the whole mesh.
    Unset/empty/0 disables it; malformed values raise typed."""
    from deequ_tpu.envcfg import env_value

    return env_value("DEEQU_TPU_SHARD_DEADLINE")


#: worker-thread-local view of the watchdog call currently executing on
#: that thread. ScanStats' fetch accounting consults it: a late-waking
#: ABANDONED call (its caller already raised DeviceHangException and the
#: ladder moved on) must not bump process-global counters mid-way
#: through a LATER run — the cross-test device_fetches race the tier-1
#: oom_mid_fold deflake closes (round 14).
_WATCHDOG_TLS = threading.local()


def current_watchdog_call_abandoned() -> bool:
    """True iff the CALLING thread is a watchdog worker whose in-flight
    call timed out and was abandoned — its side effects on shared
    telemetry must be dropped, not recorded against whatever run is
    active by the time the hung call finally wakes."""
    state = getattr(_WATCHDOG_TLS, "state", None)
    return bool(state is not None and state.get("abandoned"))


class _WatchdogPool:
    """Reusable daemon workers for deadline-bounded calls.

    Spawning a fresh thread per watchdog-wrapped call costs ~1ms —
    enough to break the <1% governed-healthy-path contract when the run
    budget wraps every scan attempt. Workers here park on a per-worker
    inbox between calls, so the healthy path pays only a queue handoff.
    A worker whose call TIMED OUT is abandoned (a genuinely hung device
    call cannot be cancelled from Python, only detected): it is never
    returned to the idle stack, and exits on its own if the hung call
    ever finishes. Pool size is bounded by the peak number of
    concurrently armed watchdogs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._idle: list = []

    def _spawn(self):
        import queue

        inbox: "queue.SimpleQueue" = queue.SimpleQueue()

        def loop():
            while True:
                fn, box, done, state = inbox.get()
                # publish the call state to this thread before running:
                # telemetry written from INSIDE the call (record_fetch)
                # can then check whether the call was abandoned mid-way
                _WATCHDOG_TLS.state = state
                try:
                    box["value"] = fn()
                # deequ-lint: ignore[bare-except] -- watchdog worker forwards the exception to the caller thread via box['error'], re-raised there
                except BaseException as e:  # noqa: BLE001 — re-raised on
                    # the caller thread
                    box["error"] = e
                finally:
                    _WATCHDOG_TLS.state = None
                done.set()
                # drop the job references BEFORE parking: an idle worker
                # must not pin the last call's closure (which can hold a
                # whole in-memory table) or its result box until the
                # next job arrives
                fn = box = done = None
                with self._lock:
                    abandoned, state = state["abandoned"], None
                    if abandoned:
                        return  # timed out: this thread may be poisoned
                    self._idle.append(inbox)

        threading.Thread(
            target=loop, daemon=True, name="deequ-tpu-watchdog"
        ).start()
        return inbox

    def call(self, fn: Callable, deadline: float, what: str,
             boundary: str):
        with self._lock:
            inbox = self._idle.pop() if self._idle else None
        if inbox is None:
            inbox = self._spawn()
        box: Dict[str, Any] = {}
        done = threading.Event()
        state = {"abandoned": False}
        inbox.put((fn, box, done, state))
        if not done.wait(deadline):
            with self._lock:
                # the worker may have finished at the wire: only abandon
                # (and raise) if it is still genuinely in flight — the
                # lock orders this against the worker's requeue decision
                if not done.is_set():
                    state["abandoned"] = True
            if state["abandoned"]:
                raise DeviceHangException(
                    f"[{boundary}] {what} exceeded the {deadline:g}s "
                    "compute watchdog deadline — treating the device as "
                    "hung",
                    boundary=boundary,
                    deadline=deadline,
                )
        if "error" in box:
            raise box["error"]
        return box.get("value")


_WATCHDOG_POOL = _WatchdogPool()


def _call_with_deadline(fn: Callable, deadline: float, what: str,
                        boundary: str):
    """Run ``fn`` on a (pooled, reusable) watchdog worker thread; if it
    does not finish within ``deadline`` seconds, raise
    DeviceHangException. A timed-out worker is abandoned — a genuinely
    hung device call cannot be cancelled from Python, only *detected*."""
    return _WATCHDOG_POOL.call(fn, deadline, what, boundary)


def device_call(
    fn: Callable,
    boundary: str,
    what: str = "device call",
    deadline: Optional[float] = None,
    hook_ctx: Optional[Dict[str, Any]] = None,
):
    """Run one device-boundary call under classification (+ optional
    watchdog + optional fault injection).

    Raw jaxlib/XLA failures re-raise as their typed DeviceException (with
    ``__cause__`` preserved); non-device errors propagate untouched.
    ``hook_ctx`` is passed only at the execute seam — the one place the
    deterministic fault hook fires.

    Cost note: an armed deadline spawns one short-lived watchdog thread
    per call (~0.1ms) — noise next to a device round trip, but reason
    enough that the watchdog is opt-in and off by default."""
    hook = _SCAN_FAULT_HOOK if hook_ctx is not None else None

    def body():
        if hook is not None:
            hook(boundary, hook_ctx)
        return fn()

    def classified():
        try:
            if deadline is not None:
                return _call_with_deadline(body, deadline, what, boundary)
            return body()
        except DeviceException:
            raise
        except Exception as e:  # noqa: BLE001 — classified below;
            # non-device errors (logic bugs; KeyboardInterrupt is not an
            # Exception) propagate exactly as before
            typed = classify_device_error(e, boundary)
            if typed is not None:
                raise typed from e
            raise

    # flight-recorder seam (deequ_tpu/obs): every device boundary is a
    # span when a recorder is armed — the span opens on the CALLER
    # thread (its track), wrapping the watchdog wait too, so a hang
    # shows as a long span ending in a typed error. Disarmed cost: one
    # module-global integer check.
    rec = current_recorder()
    if rec is not None:
        with rec.span(boundary, what=what):
            return classified()
    return classified()


# -- backend health ----------------------------------------------------------


class DeviceHealth:
    """Consecutive-fault counter for the accelerator backend.

    After ``threshold`` consecutive classified device faults with no
    successful device pass in between, ``should_force_fallback()`` turns
    true and scans running with ``on_device_error="fallback"`` go
    STRAIGHT to the CPU backend — a flapping device must not re-fail
    every scan before each fallback. Forced fallback is never permanent:
    every ``probe_interval``-th forced scan probes the accelerator again
    (half-open, circuit-breaker style), and one successful accelerator
    pass resets the counter — transient weather forgives. Faults observed
    ON the CPU fallback attempt are the host's, not the accelerator's,
    and must not be recorded here."""

    def __init__(self, threshold: int = 3, probe_interval: int = 8):
        self.threshold = int(threshold)
        self.probe_interval = int(probe_interval)
        self.reset()

    def reset(self) -> None:
        self.consecutive_faults = 0
        self.total_faults = 0
        self._forced = 0

    def record_fault(self, exc: DeviceException) -> None:
        self.consecutive_faults += 1
        self.total_faults += 1

    def record_success(self) -> None:
        self.consecutive_faults = 0
        self._forced = 0

    def should_force_fallback(self) -> bool:
        if self.consecutive_faults < self.threshold:
            return False
        self._forced += 1
        if self.probe_interval and self._forced % self.probe_interval == 0:
            return False  # half-open probe: try the accelerator this once
        return True


#: process-wide accelerator health, read by run_scan's fallback policy
DEVICE_HEALTH = DeviceHealth()


# -- mesh health -------------------------------------------------------------


class MeshHealth:
    """Per-device fault registry for multi-chip meshes — ``DeviceHealth``
    at mesh-member granularity.

    Every classified device fault that NAMES its chip
    (``DeviceException.device_ids``) is recorded against that chip, not
    the whole backend: one flaky chip on an 8-chip mesh must cost one
    chip, never all eight. A chip whose consecutive faults reach
    ``threshold`` is quarantined — subsequent scans build their mesh over
    the healthy remainder up front instead of re-failing into the same
    dead member — with the same half-open circuit-breaker escape hatch as
    DeviceHealth: every ``probe_interval``-th quarantine decision
    readmits the quarantined chips for one probe scan, and a successful
    pass over a probed chip clears its record (transient weather
    forgives; a genuinely dead chip re-quarantines on the next fault).

    A ``DeviceLostException`` / ``MeshDegradedException`` quarantines its
    chips IMMEDIATELY (a lost chip is lost, not flaky); other attributable
    faults (per-chip OOM, stragglers) count one step toward the
    threshold."""

    def __init__(self, threshold: int = 2, probe_interval: int = 8):
        self.threshold = int(threshold)
        self.probe_interval = int(probe_interval)
        self.reset()

    def reset(self) -> None:
        self.consecutive_faults: Dict[int, int] = {}
        self.total_faults: Dict[int, int] = {}
        self._filtered = 0

    def record_fault(self, exc: "DeviceException") -> None:
        """Record one classified fault against every chip it implicates
        (no-op for unattributable faults — those are DeviceHealth's)."""
        from deequ_tpu.exceptions import (
            DeviceLostException,
            MeshDegradedException,
        )

        fatal = isinstance(exc, (DeviceLostException, MeshDegradedException))
        for did in getattr(exc, "device_ids", ()) or ():
            count = self.consecutive_faults.get(did, 0) + 1
            if fatal:
                count = max(count, self.threshold)
            self.consecutive_faults[did] = count
            self.total_faults[did] = self.total_faults.get(did, 0) + 1

    def record_success(self, device_ids) -> None:
        """A scan completed over these chips: their records clear. Only
        the chips that actually PARTICIPATED are forgiven — a success on
        the shrunken mesh says nothing about the quarantined member, and
        must not reset the probe cadence that will eventually retry it."""
        for did in device_ids:
            self.consecutive_faults.pop(int(did), None)

    def quarantined(self) -> frozenset:
        return frozenset(
            did
            for did, count in self.consecutive_faults.items()
            if count >= self.threshold
        )

    def healthy_subset(self, device_ids):
        """Partition ``device_ids`` into (healthy, excluded) for a scan
        about to build its mesh. Advances the half-open probe counter only
        when something would actually be excluded; on every
        ``probe_interval``-th such decision the quarantined chips are
        readmitted for one probe."""
        bad = self.quarantined()
        ids = [int(d) for d in device_ids]
        excluded = [d for d in ids if d in bad]
        if not excluded:
            return ids, []
        self._filtered += 1
        if self.probe_interval and self._filtered % self.probe_interval == 0:
            return ids, []  # half-open probe: trust the full mesh this once
        healthy = [d for d in ids if d not in bad]
        return healthy, excluded

    def snapshot(self) -> dict:
        return {
            "quarantined": sorted(self.quarantined()),
            "consecutive_faults": dict(self.consecutive_faults),
            "total_faults": dict(self.total_faults),
        }


#: process-wide per-chip health, read by run_scan's degraded-mesh policy
MESH_HEALTH = MeshHealth()
