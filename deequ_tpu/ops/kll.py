"""KLL quantile sketch (mergeable, serializable).

Re-implementation of the KLL algorithm (Karnin–Lang–Liberty, FOCS'16 —
public algorithm) with the reference's parameterization: ``sketch_size``
(k, default 2048) and ``shrinking_factor`` (c, default 0.64), compactor
capacity ``2 * (ceil(k * c^depth / 2) + 1)`` where depth counts down from
the top compactor (reference analyzers/QuantileNonSample.scala:78-80,
defaults at analyzers/KLLSketch.scala:172-176).

Vectorized batch updates: a whole chunk of values is appended at once and
levels compact with one numpy sort per overflow — the amortized analogue of
the reference's per-row update loop (KLLRunner.scala:167-174), ~C-speed on
host. Chunks stream from the device scan; per-shard sketches merge with the
levelwise concatenate-and-compact rule, which is also how cross-device and
incremental (persisted-state) merges work.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

DEFAULT_SKETCH_SIZE = 2048
DEFAULT_SHRINKING_FACTOR = 0.64


class KLLSketchState:
    """One KLL sketch: a hierarchy of compactors; items at level h have
    weight 2^h. Not thread-safe; treated as a value by the engine."""

    def __init__(
        self,
        sketch_size: int = DEFAULT_SKETCH_SIZE,
        shrinking_factor: float = DEFAULT_SHRINKING_FACTOR,
        compactors: Optional[List[np.ndarray]] = None,
        count: int = 0,
        rng_count: int = 0,
    ):
        self.sketch_size = int(sketch_size)
        self.shrinking_factor = float(shrinking_factor)
        self.compactors: List[np.ndarray] = (
            [np.empty(0, dtype=np.float64)] if compactors is None else compactors
        )
        self.count = int(count)  # total items represented (by weight)
        # compaction-randomness position: bits are drawn by hashing this
        # counter (see _next_bit), so persisting it round-trips the random
        # promote/retain choices exactly across save/load/update cycles
        # (a resumed sketch continues the SAME bit stream instead of
        # replaying it from the seed)
        self.rng_count = int(rng_count)

    def _next_bit(self) -> int:
        """Deterministic, serializable bit source: splitmix64 finalizer of
        the draw index. Machine-independent and position-restorable —
        unlike a numpy Generator, whose internal state did not survive the
        binary state codec (states/serde.py)."""
        m = (1 << 64) - 1
        z = (self.rng_count * 0x9E3779B97F4A7C15 + 0xDEE0DEE0) & m
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4B9B1) & m
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & m
        self.rng_count += 1
        return int((z ^ (z >> 31)) & 1)

    # -- capacities ---------------------------------------------------------

    def _capacity(self, level: int) -> int:
        depth = len(self.compactors) - 1 - level
        k = self.sketch_size * (self.shrinking_factor ** depth)
        return 2 * (math.ceil(k / 2) + 1)

    # -- updates ------------------------------------------------------------

    def update_batch(self, values: np.ndarray) -> None:
        """Insert a batch of values (no NaNs/nulls — caller filters)."""
        if len(values) == 0:
            return
        self.compactors[0] = np.concatenate(
            # deequ-lint: ignore[host-fetch] -- compactors and update values are host arrays by design (KLL keeps the host fold)
            [self.compactors[0], np.asarray(values, dtype=np.float64)]
        )
        self.count += len(values)
        self._compress()

    def _compress(self) -> None:
        level = 0
        while level < len(self.compactors):
            buf = self.compactors[level]
            if len(buf) <= self._capacity(level):
                level += 1
                continue
            if level + 1 == len(self.compactors):
                self.compactors.append(np.empty(0, dtype=np.float64))
                # appending a level shrinks the depth-based capacities of
                # every lower level — restart the walk from 0 so all buffers
                # end within capacity (QuantileNonSample capacity invariant)
                level = 0
                continue
            buf = np.sort(buf)
            # an odd-length buffer keeps one leftover item at this level so
            # total weight is preserved exactly; the even remainder compacts
            if len(buf) % 2 == 1:
                keep_last = self._next_bit()
                if keep_last:
                    retained, to_compact = buf[-1:], buf[:-1]
                else:
                    retained, to_compact = buf[:1], buf[1:]
            else:
                retained = np.empty(0, dtype=np.float64)
                to_compact = buf
            offset = self._next_bit()
            promoted = to_compact[offset::2]
            self.compactors[level] = retained
            self.compactors[level + 1] = np.concatenate(
                [self.compactors[level + 1], promoted]
            )
            level += 1

    # -- merge --------------------------------------------------------------

    def merge(self, other: "KLLSketchState") -> "KLLSketchState":
        """Levelwise concatenation followed by compaction."""
        if (self.sketch_size, self.shrinking_factor) != (
            other.sketch_size, other.shrinking_factor,
        ):
            raise ValueError("cannot merge KLL sketches with different parameters")
        levels = max(len(self.compactors), len(other.compactors))
        merged = []
        for i in range(levels):
            a = self.compactors[i] if i < len(self.compactors) else np.empty(0)
            b = other.compactors[i] if i < len(other.compactors) else np.empty(0)
            merged.append(np.concatenate([a, b]).astype(np.float64))
        out = KLLSketchState(
            self.sketch_size, self.shrinking_factor, merged,
            self.count + other.count, self.rng_count + other.rng_count,
        )
        out._compress()
        return out

    # -- queries ------------------------------------------------------------

    def _weighted_items(self) -> Tuple[np.ndarray, np.ndarray]:
        items = []
        weights = []
        for level, buf in enumerate(self.compactors):
            if len(buf):
                items.append(buf)
                weights.append(np.full(len(buf), 2 ** level, dtype=np.int64))
        if not items:
            return np.empty(0), np.empty(0, dtype=np.int64)
        items = np.concatenate(items)
        weights = np.concatenate(weights)
        order = np.argsort(items, kind="stable")
        return items[order], weights[order]

    def rank(self, value: float) -> int:
        """Estimated number of items <= value."""
        items, weights = self._weighted_items()
        return int(weights[items <= value].sum())

    def rank_exclusive(self, value: float) -> int:
        """Estimated number of items < value."""
        items, weights = self._weighted_items()
        return int(weights[items < value].sum())

    def cdf(self, values: Sequence[float]) -> List[float]:
        total = max(self.count, 1)
        return [self.rank(v) / total for v in values]

    def quantile(self, q: float) -> float:
        """Estimated q-quantile, q in [0, 1]."""
        items, weights = self._weighted_items()
        if len(items) == 0:
            return float("nan")
        cum = np.cumsum(weights)
        target = q * cum[-1]
        idx = int(np.searchsorted(cum, target, side="left"))
        return float(items[min(idx, len(items) - 1)])

    def quantiles(self, qs: Sequence[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    # -- serde (analogue of KLLSketchSerializer.scala:26-121) ---------------

    def serialize(self) -> tuple:
        return (
            self.sketch_size,
            self.shrinking_factor,
            self.count,
            tuple(tuple(float(x) for x in buf) for buf in self.compactors),
            self.rng_count,
        )

    @staticmethod
    def deserialize(data: tuple) -> "KLLSketchState":
        sketch_size, shrinking_factor, count, buffers = data[:4]
        rng_count = data[4] if len(data) > 4 else 0
        # deequ-lint: ignore[host-fetch] -- serde: buffers are host lists from decoded state
        compactors = [np.array(buf, dtype=np.float64) for buf in buffers]
        if not compactors:
            compactors = [np.empty(0, dtype=np.float64)]
        return KLLSketchState(
            sketch_size, shrinking_factor, compactors, count, rng_count
        )

    @staticmethod
    def reconstruct(raw_buffers, parameters) -> "KLLSketchState":
        """Rebuild from BucketDistribution.data/.parameters
        (analogue of QuantileNonSample.reconstruct, reference L46-60)."""
        shrinking_factor, sketch_size = parameters
        # deequ-lint: ignore[host-fetch] -- serde: raw_buffers are host lists from decoded state
        compactors = [np.array(buf, dtype=np.float64) for buf in raw_buffers]
        count = sum(len(b) * (2 ** i) for i, b in enumerate(compactors))
        return KLLSketchState(int(sketch_size), float(shrinking_factor), compactors, count)
