"""Metrics repository — time-series store for analysis results
(reference layer L9, repository/).

Results are keyed by ``ResultKey(data_set_date, tags)`` and queried through
a small DSL (``load().with_tag_values(...).after(...).for_analyzers(...)``)
— the substrate for anomaly detection over metric history.
"""

from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.repository.memory import InMemoryMetricsRepository
from deequ_tpu.repository.fs import FileSystemMetricsRepository
from deequ_tpu.repository.columnar import ColumnarMetricsRepository
from deequ_tpu.repository.monitor import QualityAlert, QualityMonitor
from deequ_tpu.repository.query import (
    RepositoryQuery,
    RepositoryQueryResult,
    run_repository_query,
)

__all__ = [
    "AnalysisResult",
    "MetricsRepository",
    "MetricsRepositoryMultipleResultsLoader",
    "ResultKey",
    "InMemoryMetricsRepository",
    "FileSystemMetricsRepository",
    "ColumnarMetricsRepository",
    "QualityAlert",
    "QualityMonitor",
    "RepositoryQuery",
    "RepositoryQueryResult",
    "run_repository_query",
]
