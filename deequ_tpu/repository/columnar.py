"""Serving-scale COLUMNAR metrics repository (ROADMAP item 5).

The reference repositories (``memory.py`` / ``fs.py``) are key-value
stores of JSON documents — fine for one pipeline, absurd for the round-12
fleet emitting per-tenant results for millions of streams: the fs backend
rewrites the FULL document per save (O(N²) across a run), and every query
decodes every save on host before a single Python comparison runs.

This backend stores metric history in the repo's own idiom — the PR-8
:class:`~deequ_tpu.data.table.ColumnChunk` layout — so the repository IS
a columnar table the engine can verify:

- **append segments**: each ``save()`` appends ONE immutable segment
  (atomic + checksummed through the PR-2 serde, ``resilience/atomic.py``)
  holding the result's scalar metric rows as planes: ``dataset_date`` as
  i64, the (analyzer, instance, metric-name) identity and every tag
  key/value dictionary-encoded as int16 codes, metric values as the f64
  plane the engine's f32-pair split consumes. Saves are O(rows of THIS
  result), never O(history) — the fs backend's quadratic wall is gone.
  Same-key re-saves append a superseding segment (last write wins, like
  the reference); ``compact()`` batches live results into
  ``DEEQU_TPU_REPO_SEGMENT_ROWS``-row segments and drops dead ones —
  plus, with ``DEEQU_TPU_REPO_TTL`` armed, results wholly older than
  (newest live dataset date - TTL): retention is a compaction policy,
  so loader bit-identity holds unchanged over the surviving window.
- **loader bit-identity**: ``load()`` / ``load_by_key`` decode segments
  back into :class:`AnalysisResult`s through the SAME
  ``MetricsRepositoryMultipleResultsLoader`` DSL — scalar values ride
  the exact f64 plane, non-scalar metrics (Histogram/KLL/Keyed) ride a
  per-result serde overflow, and the original ``metric_map`` insertion
  order is preserved, so loader results are bit-identical to
  :class:`~deequ_tpu.repository.memory.InMemoryMetricsRepository` on the
  same saves (tier-1 ``mrepo`` pins it).
- **queries compile into engine scans**: :meth:`history_table`
  materializes the live history as ONE dictionary-encoded
  ``ColumnarTable`` (cached, invalidated by saves), and
  ``repository/query.py`` lowers trend-window / tag-filter /
  cross-tenant aggregate queries onto it through the ordinary
  ``run_scan`` path — plan-linted, ``ScanStats``-counted, riding the
  encoded int16 plane (Eiger, arXiv:2607.04489: the library-as-
  compiled-data-path shape).

Torn appends: a crash mid-save leaves either the previous complete
segment set (atomic rename) or a checksummed-detectable partial — a torn
TAIL segment raises typed :class:`CorruptStateException` on open (prior
segments stay intact and loadable with ``on_torn_segment="recover"``);
damage anywhere before the tail always raises.
"""

from __future__ import annotations

import json
import struct
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.analyzers.runner import AnalyzerContext
from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.metrics import DoubleMetric, Entity
from deequ_tpu.repository import serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.tryresult import Success

SEGMENT_MAGIC = b"DQMR"
SEGMENT_VERSION = 1
SEGMENT_SUFFIX = ".dqmr"
#: torn tail segments recovered past are renamed, not deleted — kept
#: for forensics, excluded from replay by the suffix filter
CORRUPT_SUFFIX = ".corrupt"

#: int16 code planes cap their per-segment dictionaries exactly like
#: ColumnChunk (data/table.py): identities or tag values past the cap
#: ride the serde overflow instead of a code plane
MAX_SEGMENT_DICT = (1 << 15) - 1

#: superseded (dead) results tolerated before a persisted repository
#: auto-compacts on the next save
AUTO_COMPACT_DEAD = 64

_u16 = struct.Struct("<H")
_i64 = struct.Struct("<q")


class _RepoStats:
    """Process-wide repository observables — the ``repository`` section
    of the unified metrics registry (obs/registry.py) reads through this
    singleton at scrape time, exactly like ScanStats."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.saves = 0
        self.segments_written = 0
        self.segment_rows_written = 0
        self.bytes_appended = 0
        self.compactions = 0
        self.dead_results_dropped = 0
        self.ttl_dropped = 0
        self.torn_segments_dropped = 0
        self.nonserializable_dropped = 0
        self.queries = 0
        self.query_scan_passes = 0
        self.query_rows_scanned = 0
        self.table_builds = 0

    def snapshot(self) -> dict:
        return dict(self.__dict__)


REPO_STATS = _RepoStats()


def series_identity(analyzer, metric) -> Optional[str]:
    """The canonical identity label of one scalar metric series: the
    analyzer's serde JSON plus (entity, name, instance), serialized
    deterministically. None when the analyzer is not serializable (such
    metrics ride the overflow path or, like the reference serde, drop)."""
    try:
        a_json = serde.analyzer_to_json(analyzer)
    except ValueError:
        return None
    return json.dumps(
        {
            "a": a_json,
            "e": metric.entity.value,
            "m": metric.name,
            "i": metric.instance,
        },
        sort_keys=True,
        separators=(",", ":"),
    )


def _identity_from_label(label: str) -> Tuple[Any, str, str, str]:
    """label -> (analyzer, entity value, metric name, instance)."""
    meta = json.loads(label)
    analyzer = serde.analyzer_from_json(meta["a"])
    return analyzer, meta["e"], meta["m"], meta["i"]


class _Segment:
    """One immutable append batch: N scalar metric rows as planes plus a
    JSON header carrying the result keys, per-segment dictionaries, and
    the non-scalar overflow. Decoded results are cached (segments never
    mutate)."""

    def __init__(
        self,
        header: Dict[str, Any],
        dates: np.ndarray,
        series: np.ndarray,
        values: np.ndarray,
        tag_codes: Dict[str, np.ndarray],
        seq: int = -1,
        file: Optional[str] = None,
    ):
        self.header = header
        self.dates = dates          # i64[N]
        self.series = series        # int16[N] -> header["series_dict"]
        self.values = values        # f64[N]
        self.tag_codes = tag_codes  # key -> int16[N] (-1 = absent)
        self.seq = seq
        self.file = file
        self._decoded: Optional[List[AnalysisResult]] = None

    @property
    def num_rows(self) -> int:
        return len(self.dates)

    @property
    def num_results(self) -> int:
        return len(self.header["results"])

    @property
    def nbytes(self) -> int:
        planes = (
            self.dates.nbytes + self.series.nbytes + self.values.nbytes
            + sum(c.nbytes for c in self.tag_codes.values())
        )
        return planes

    # -- encode ----------------------------------------------------------

    @staticmethod
    def encode(results: Sequence[AnalysisResult], seq: int = -1) -> "_Segment":
        """Batch one or more AnalysisResults into a segment. Scalar
        (successful DoubleMetric, float-valued) entries become plane
        rows; everything else serde-serializable rides the per-result
        overflow; non-serializable analyzers drop like the reference
        serde (counted). The per-result ``order`` string records the
        original metric_map interleaving so decode reconstructs the
        exact insertion order."""
        series_dict: List[str] = []
        series_index: Dict[str, int] = {}
        tag_dicts: Dict[str, List[str]] = {}
        tag_index: Dict[str, Dict[str, int]] = {}
        dates: List[int] = []
        series: List[int] = []
        values: List[float] = []
        tag_rows: Dict[str, List[int]] = {}
        header_results: List[Dict[str, Any]] = []

        for result in results:
            key = result.result_key
            row_start = len(dates)
            overflow: List[Dict[str, Any]] = []
            order: List[str] = []
            # this result's tag codes, resolved once (constant per row)
            row_tag_code: Dict[str, int] = {}
            for tk, tv in key.tags:
                idx_map = tag_index.setdefault(tk, {})
                code = idx_map.get(tv)
                if code is None and len(idx_map) < MAX_SEGMENT_DICT:
                    code = len(idx_map)
                    idx_map[tv] = code
                    tag_dicts.setdefault(tk, []).append(tv)
                row_tag_code[tk] = -1 if code is None else code
            for analyzer, metric in result.analyzer_context.metric_map.items():
                label = None
                if (
                    isinstance(metric, DoubleMetric)
                    and metric.value.is_success
                    and isinstance(metric.value.get(), float)
                ):
                    label = series_identity(analyzer, metric)
                if label is not None:
                    code = series_index.get(label)
                    if code is None and len(series_index) < MAX_SEGMENT_DICT:
                        code = len(series_index)
                        series_index[label] = code
                        series_dict.append(label)
                    if code is not None:
                        dates.append(int(key.data_set_date))
                        series.append(code)
                        values.append(metric.value.get())
                        for tk in tag_rows:
                            tag_rows[tk].append(row_tag_code.get(tk, -1))
                        for tk in row_tag_code:
                            if tk not in tag_rows:
                                # backfill rows encoded before this key
                                # introduced the tag
                                tag_rows[tk] = [-1] * (len(dates) - 1)
                                tag_rows[tk].append(row_tag_code[tk])
                        order.append("r")
                        continue
                # non-scalar / dict-overflow metrics: serde JSON
                try:
                    entry = {
                        "analyzer": serde.analyzer_to_json(analyzer),
                        "metric": serde.metric_to_json(metric),
                    }
                except ValueError:
                    REPO_STATS.nonserializable_dropped += 1
                    continue
                overflow.append(entry)
                order.append("o")
            header_results.append(
                {
                    "key": {
                        "dataSetDate": int(key.data_set_date),
                        "tags": key.tags_dict,
                    },
                    "row_start": row_start,
                    "row_stop": len(dates),
                    "overflow": overflow,
                    "order": "".join(order),
                }
            )

        n = len(dates)
        tag_keys = sorted(tag_rows)
        header = {
            "rows": n,
            "results": header_results,
            "series_dict": series_dict,
            "tag_keys": tag_keys,
            "tag_dicts": {k: tag_dicts.get(k, []) for k in tag_keys},
        }
        return _Segment(
            header,
            np.fromiter(dates, dtype=np.int64, count=n),
            np.fromiter(series, dtype=np.int16, count=n),
            np.fromiter(values, dtype=np.float64, count=n),
            {
                k: np.fromiter(tag_rows[k], dtype=np.int16, count=n)
                for k in tag_keys
            },
            seq=seq,
        )

    # -- binary round trip ----------------------------------------------

    def to_bytes(self) -> bytes:
        head = json.dumps(self.header, separators=(",", ":")).encode("utf-8")
        out = [
            SEGMENT_MAGIC,
            _u16.pack(SEGMENT_VERSION),
            _i64.pack(len(head)),
            head,
            self.dates.tobytes(),
            self.series.tobytes(),
            self.values.tobytes(),
        ]
        for k in self.header["tag_keys"]:
            out.append(self.tag_codes[k].tobytes())
        return b"".join(out)

    @staticmethod
    def from_bytes(payload: bytes, what: str, seq: int = -1) -> "_Segment":
        if payload[:4] != SEGMENT_MAGIC:
            raise CorruptStateException(what, "bad segment magic")
        (version,) = _u16.unpack_from(payload, 4)
        if version > SEGMENT_VERSION:
            raise CorruptStateException(
                what,
                f"segment version {version} newer than supported "
                f"{SEGMENT_VERSION}",
            )
        (head_len,) = _i64.unpack_from(payload, 6)
        off = 14
        try:
            header = json.loads(payload[off:off + head_len].decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise CorruptStateException(
                what, f"undecodable segment header: {e}"
            ) from e
        off += head_len
        n = int(header["rows"])

        def plane(dtype, itemsize):
            nonlocal off
            end = off + n * itemsize
            if end > len(payload):
                raise CorruptStateException(
                    what, "torn segment: plane bytes truncated"
                )
            arr = np.frombuffer(payload[off:end], dtype=dtype)
            off = end
            return arr

        dates = plane(np.int64, 8)
        series = plane(np.int16, 2)
        values = plane(np.float64, 8)
        tag_codes = {k: plane(np.int16, 2) for k in header["tag_keys"]}
        return _Segment(header, dates, series, values, tag_codes, seq=seq)

    # -- decode ----------------------------------------------------------

    def decode_results(self) -> List[AnalysisResult]:
        if self._decoded is not None:
            return self._decoded
        out: List[AnalysisResult] = []
        labels = self.header["series_dict"]
        for entry in self.header["results"]:
            key = ResultKey(
                entry["key"]["dataSetDate"], entry["key"].get("tags", {})
            )
            metric_map: Dict[Any, Any] = {}
            row = entry["row_start"]
            ovf = 0
            for kind in entry.get("order", ""):
                if kind == "r":
                    label = labels[int(self.series[row])]
                    analyzer, entity, name, instance = _identity_from_label(
                        label
                    )
                    metric_map[analyzer] = DoubleMetric(
                        Entity(entity), name, instance,
                        Success(float(self.values[row])),
                    )
                    row += 1
                else:
                    o = entry["overflow"][ovf]
                    ovf += 1
                    metric_map[serde.analyzer_from_json(o["analyzer"])] = (
                        serde.metric_from_json(o["metric"])
                    )
            out.append(AnalysisResult(key, AnalyzerContext(metric_map)))
        self._decoded = out
        return out


class _HistoryView:
    """The live history materialized once: the engine-facing
    ``ColumnarTable`` plus the host-side filter planes ``query.py``
    masks on (raw dates, global series/tag codes and their label
    indexes). Immutable — rebuilt when the repository version moves."""

    def __init__(self, table, dates, series_codes, series_labels,
                 series_meta, tag_codes, tag_labels):
        self.table = table
        self.dates = dates                  # i64[N]
        self.series_codes = series_codes    # i32[N]
        self.series_labels = series_labels  # [label]
        #: per label: (analyzer_json_str, entity, name, instance)
        self.series_meta = series_meta
        self.tag_codes = tag_codes          # key -> i32[N] (-1 absent)
        self.tag_labels = tag_labels        # key -> [value]

    @property
    def num_rows(self) -> int:
        return len(self.dates)


def _object_array(items: Sequence[str]) -> np.ndarray:
    out = np.empty(len(items), dtype=object)
    for i, s in enumerate(items):
        out[i] = s
    return out


class ColumnarMetricsRepository(MetricsRepository):
    """Drop-in :class:`MetricsRepository` storing history as columnar
    append segments (see module doc). ``path=None`` keeps segments in
    memory only (the InMemory analogue — every load still exercises the
    columnar codec); a path makes it durable with crash-consistent
    appends.

    ``monitor`` (a :class:`~deequ_tpu.repository.monitor.QualityMonitor`)
    observes every save online — the anomaly strategies run at
    result-ingest time instead of via batch history pulls."""

    def __init__(
        self,
        path: Optional[str] = None,
        segment_rows: Optional[int] = None,
        on_torn_segment: str = "raise",
        monitor=None,
        retry=None,
        ttl: Optional[float] = None,
    ):
        if on_torn_segment not in ("raise", "recover"):
            raise ValueError(
                "on_torn_segment must be 'raise' or 'recover', got "
                f"{on_torn_segment!r}"
            )
        if segment_rows is None:
            from deequ_tpu.envcfg import env_value

            segment_rows = env_value("DEEQU_TPU_REPO_SEGMENT_ROWS")
        if int(segment_rows) < 1:
            raise ValueError(f"segment_rows must be >= 1, got {segment_rows}")
        self.segment_rows = int(segment_rows)
        # retention window (round 15, ROADMAP item-5 leftover): results
        # older than (newest live dataset date - ttl) drop at COMPACTION
        # — never on the load path, so a reader between compactions
        # still sees exactly what the last compaction kept. None (the
        # DEEQU_TPU_REPO_TTL default) keeps everything.
        if ttl is None:
            from deequ_tpu.envcfg import env_value

            ttl = env_value("DEEQU_TPU_REPO_TTL")
        if ttl is not None and not float(ttl) > 0:
            raise ValueError(f"ttl must be > 0 dataset-date units, got {ttl}")
        self.ttl = None if ttl is None else float(ttl)
        self.on_torn_segment = on_torn_segment
        self.monitor = monitor
        self._lock = threading.RLock()
        self._segments: List[_Segment] = []
        #: key -> (position in _segments, result index) of the LIVE
        #: result; dict insertion order IS the loader order (same-key
        #: re-saves keep the original position, matching InMemory)
        self._live: Dict[ResultKey, Tuple[int, int]] = {}
        self._dead_results = 0
        self._next_seq = 0
        self._version = 0
        self._view: Optional[_HistoryView] = None
        self._view_version = -1
        self._fs = None
        self.path = None
        if path is not None:
            from deequ_tpu.data.fs import filesystem_for, strip_scheme
            from deequ_tpu.resilience.retry import RetryingFileSystem

            self.path = strip_scheme(path)
            self._fs = RetryingFileSystem(filesystem_for(path), retry)
            self._recover()

    # -- persistence -----------------------------------------------------

    def _segment_path(self, seq: int) -> str:
        return self._fs.join(self.path, f"seg_{seq:010d}{SEGMENT_SUFFIX}")

    def _list_segment_files(self) -> List[Tuple[int, str]]:
        if not self._fs.exists(self.path):
            return []
        out = []
        for name in self._fs.listdir(self.path):
            if name.startswith("seg_") and name.endswith(SEGMENT_SUFFIX):
                try:
                    seq = int(name[4:-len(SEGMENT_SUFFIX)])
                except ValueError:
                    continue
                out.append((seq, name))
        return sorted(out)

    def _recover(self) -> None:
        """Replay persisted segments in sequence order. A corrupt TAIL
        run (the torn-append shape) raises typed — or, with
        ``on_torn_segment="recover"``, drops it and keeps every prior
        segment; corruption strictly BEFORE valid segments always
        raises (that is damage, not a torn append)."""
        from deequ_tpu.resilience.atomic import read_checksummed

        files = self._list_segment_files()
        loaded: List[_Segment] = []
        errors: List[Tuple[int, str, CorruptStateException]] = []
        for seq, name in files:
            what = f"metrics repository segment {name}"
            full = self._fs.join(self.path, name)
            try:
                payload = read_checksummed(self._fs, full, what)
                seg = _Segment.from_bytes(payload, what, seq=seq)
            except CorruptStateException as e:
                errors.append((seq, name, e))
                continue
            if errors:
                # a valid segment AFTER a corrupt one: the damage is not
                # a torn tail — surface the first corruption typed
                raise errors[0][2]
            seg.file = full
            loaded.append(seg)
        if errors:
            if self.on_torn_segment == "raise":
                raise errors[0][2]
            # quarantine the torn tail ON DISK (seg_*.dqmr -> *.corrupt,
            # preserved for forensics but no longer replayed): once a
            # later save() appends a valid segment past the torn seq, a
            # reopen would otherwise see corrupt-before-valid "damage"
            # and raise in BOTH modes, permanently bricking the repo
            # counter-suffixed sidecar names: after a quarantine the
            # reopened repo recomputes _next_seq WITHOUT the torn file,
            # so the same seq can tear again — the second quarantine
            # must not overwrite the first's evidence
            from deequ_tpu.resilience.atomic import quarantine_path

            for _seq, name, _exc in errors:
                full = self._fs.join(self.path, name)
                self._fs.rename(
                    full, quarantine_path(self._fs, full, CORRUPT_SUFFIX)
                )
            REPO_STATS.torn_segments_dropped += len(errors)
        self._segments = loaded
        self._next_seq = (files[-1][0] + 1) if files else 0
        self._live = {}
        self._dead_results = 0
        for pos, seg in enumerate(self._segments):
            for ridx, entry in enumerate(seg.header["results"]):
                key = ResultKey(
                    entry["key"]["dataSetDate"], entry["key"].get("tags", {})
                )
                if key in self._live:
                    self._dead_results += 1
                self._live[key] = (pos, ridx)
        self._version += 1

    def _persist_segment(self, seg: _Segment) -> None:
        from deequ_tpu.resilience.atomic import atomic_write_bytes, wrap_checksum

        self._fs.makedirs(self.path)
        data = wrap_checksum(seg.to_bytes())
        full = self._segment_path(seg.seq)
        atomic_write_bytes(
            self._fs, full, data,
            what=f"metrics repository segment {seg.seq}",
        )
        seg.file = full
        REPO_STATS.bytes_appended += len(data)

    # -- MetricsRepository contract --------------------------------------

    def save(self, result: AnalysisResult) -> None:
        # keep only successful metrics, like the reference (and both
        # sibling backends)
        successful = AnalyzerContext(
            {
                a: m
                for a, m in result.analyzer_context.metric_map.items()
                if m.value.is_success
            }
        )
        to_save = AnalysisResult(result.result_key, successful)
        with self._lock:
            seg = _Segment.encode([to_save], seq=self._next_seq)
            self._next_seq += 1
            if self._fs is not None:
                self._persist_segment(seg)
            pos = len(self._segments)
            self._segments.append(seg)
            if result.result_key in self._live:
                self._dead_results += 1
            self._live[result.result_key] = (pos, 0)
            self._version += 1
            REPO_STATS.saves += 1
            REPO_STATS.segments_written += 1
            REPO_STATS.segment_rows_written += seg.num_rows
            if self._dead_results >= AUTO_COMPACT_DEAD:
                self._compact_locked()
        if self.monitor is not None:
            try:
                self.monitor.observe_result(to_save)
            # deequ-lint: ignore[bare-except] -- monitoring is observation, never outcome: the segment is already durably persisted, so a watch-rule or checkpoint-IO error must not fail the save; counted on MONITOR_STATS (same contract as the serve resolve seam)
            except Exception:  # noqa: BLE001
                from deequ_tpu.repository.monitor import MONITOR_STATS

                MONITOR_STATS.monitor_errors += 1

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalysisResult]:
        with self._lock:
            pos = self._live.get(result_key)
            if pos is None:
                return None
            seg_idx, ridx = pos
            return self._segments[seg_idx].decode_results()[ridx]

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        def provider() -> List[AnalysisResult]:
            with self._lock:
                return [
                    self._segments[seg_idx].decode_results()[ridx]
                    for seg_idx, ridx in self._live.values()
                ]

        return MetricsRepositoryMultipleResultsLoader(provider)

    # -- compaction ------------------------------------------------------

    def compact(self) -> int:
        """Rewrite the live history into batched segments of up to
        ``segment_rows`` rows each and drop superseded results — plus,
        with a ``ttl`` armed, results wholly older than (newest live
        dataset date - ttl). Returns the total results dropped (dead +
        TTL-expired). Crash-safe: new segments are written (atomic,
        fresh sequence numbers) before old files are deleted — a crash
        mid-compaction leaves a replayable superset whose
        last-write-wins replay yields the same live set."""
        with self._lock:
            return self._compact_locked()

    def _compact_locked(self) -> int:
        dead = self._dead_results
        dropped = dead
        live = [
            self._segments[seg_idx].decode_results()[ridx]
            for seg_idx, ridx in self._live.values()
        ]
        if self.ttl is not None and live:
            # retention: the horizon trails the NEWEST live result (not
            # the wall clock — dataset dates are the caller's axis), so
            # an idle repository never silently empties itself
            horizon = max(
                r.result_key.data_set_date for r in live
            ) - self.ttl
            kept = [
                r for r in live if r.result_key.data_set_date >= horizon
            ]
            expired = len(live) - len(kept)
            if expired:
                REPO_STATS.ttl_dropped += expired
                dropped += expired
                live = kept
        old_files = [s.file for s in self._segments if s.file is not None]
        # batch by rows: a result's scalar-row count decides the split
        batches: List[List[AnalysisResult]] = []
        current: List[AnalysisResult] = []
        current_rows = 0
        for result in live:
            rows = sum(
                1
                for a, m in result.analyzer_context.metric_map.items()
                if isinstance(m, DoubleMetric) and m.value.is_success
            )
            if current and (
                current_rows + rows > self.segment_rows
                or len(current) >= MAX_SEGMENT_DICT
            ):
                batches.append(current)
                current, current_rows = [], 0
            current.append(result)
            current_rows += rows
        if current:
            batches.append(current)
        new_segments: List[_Segment] = []
        for batch in batches:
            seg = _Segment.encode(batch, seq=self._next_seq)
            self._next_seq += 1
            if self._fs is not None:
                self._persist_segment(seg)
            new_segments.append(seg)
        self._segments = new_segments
        self._live = {}
        for pos, seg in enumerate(self._segments):
            for ridx, entry in enumerate(seg.header["results"]):
                key = ResultKey(
                    entry["key"]["dataSetDate"], entry["key"].get("tags", {})
                )
                self._live[key] = (pos, ridx)
        self._dead_results = 0
        self._version += 1
        if self._fs is not None:
            for stale in old_files:
                try:
                    self._fs.delete(stale)
                # deequ-lint: ignore[bare-except] -- stale pre-compaction segments are harmless (replay is last-write-wins); deletion is best-effort housekeeping
                except Exception:  # noqa: BLE001
                    pass
        REPO_STATS.compactions += 1
        REPO_STATS.dead_results_dropped += dead
        return dropped

    # -- the history table (query substrate) -----------------------------

    @property
    def num_segments(self) -> int:
        with self._lock:
            return len(self._segments)

    def history_table(self):
        """The live history as ONE ``ColumnarTable`` (cached until the
        next save/compact): ``dataset_date`` (INTEGRAL), ``value``
        (FRACTIONAL), ``series``/``metric``/``instance`` (STRING), and
        one ``tag:<key>`` STRING column per tag key. Dict-heavy numeric
        planes carry int16 ``ColumnChunk`` encodings (2-byte codes to
        the device instead of full-width planes — the PR-8 staged-byte
        win); ``run_scan(encoded_ingest=False)`` still packs them
        decoded for A/B runs, so the cache never forks per switch."""
        return self._history_view().table

    def _history_view(self) -> _HistoryView:
        with self._lock:
            if self._view is not None and self._view_version == self._version:
                return self._view
            view = self._build_view()
            self._view = view
            self._view_version = self._version
            REPO_STATS.table_builds += 1
            return view

    def _build_view(self) -> _HistoryView:
        from deequ_tpu.data.table import Column, ColumnarTable, DType

        date_parts: List[np.ndarray] = []
        value_parts: List[np.ndarray] = []
        series_parts: List[np.ndarray] = []
        tag_parts: Dict[str, List[np.ndarray]] = {}
        series_labels: List[str] = []
        series_index: Dict[str, int] = {}
        tag_labels: Dict[str, List[str]] = {}
        tag_index: Dict[str, Dict[str, int]] = {}
        all_tag_keys = sorted({
            k
            for seg_idx, _ in self._live.values()
            for k in self._segments[seg_idx].header["tag_keys"]
        })
        part_rows: List[int] = []

        for seg_idx, ridx in self._live.values():
            seg = self._segments[seg_idx]
            entry = seg.header["results"][ridx]
            a, b = entry["row_start"], entry["row_stop"]
            if a == b:
                continue
            date_parts.append(seg.dates[a:b])
            value_parts.append(seg.values[a:b])
            # remap per-segment int16 codes into the global label space
            labels = seg.header["series_dict"]
            remap = np.full(max(len(labels), 1), -1, dtype=np.int32)
            for local, label in enumerate(labels):
                gcode = series_index.get(label)
                if gcode is None:
                    gcode = len(series_labels)
                    series_index[label] = gcode
                    series_labels.append(label)
                remap[local] = gcode
            series_parts.append(remap[seg.series[a:b]])
            n_part = b - a
            part_rows.append(n_part)
            for k in all_tag_keys:
                codes = seg.tag_codes.get(k)
                if codes is None:
                    tag_parts.setdefault(k, []).append(
                        np.full(n_part, -1, dtype=np.int32)
                    )
                    continue
                seg_vals = seg.header["tag_dicts"].get(k, [])
                tmap = np.full(max(len(seg_vals), 1) + 1, -1, dtype=np.int32)
                idx_map = tag_index.setdefault(k, {})
                vals = tag_labels.setdefault(k, [])
                for local, v in enumerate(seg_vals):
                    g = idx_map.get(v)
                    if g is None:
                        g = len(vals)
                        idx_map[v] = g
                        vals.append(v)
                    tmap[local] = g
                # -1 (absent) indexes the trailing -1 slot
                tag_parts.setdefault(k, []).append(
                    tmap[seg.tag_codes[k][a:b]]
                )

        n = int(sum(part_rows))
        if n:
            dates = np.concatenate(date_parts)
            values = np.concatenate(value_parts)
            series_codes = np.concatenate(series_parts)
        else:
            dates = np.zeros(0, dtype=np.int64)
            values = np.zeros(0, dtype=np.float64)
            series_codes = np.zeros(0, dtype=np.int32)
        tag_codes = {
            k: (
                np.concatenate(parts) if n else np.zeros(0, dtype=np.int32)
            )
            for k, parts in tag_parts.items()
        }

        series_meta = []
        for label in series_labels:
            meta = json.loads(label)
            series_meta.append((
                json.dumps(meta["a"], sort_keys=True, separators=(",", ":")),
                meta["e"], meta["m"], meta["i"],
            ))
        name_of = np.full(max(len(series_labels), 1), -1, dtype=np.int32)
        inst_of = np.full(max(len(series_labels), 1), -1, dtype=np.int32)
        names: List[str] = []
        name_idx: Dict[str, int] = {}
        insts: List[str] = []
        inst_idx: Dict[str, int] = {}
        for i, (_, _, m, inst) in enumerate(series_meta):
            if m not in name_idx:
                name_idx[m] = len(names)
                names.append(m)
            name_of[i] = name_idx[m]
            if inst not in inst_idx:
                inst_idx[inst] = len(insts)
                insts.append(inst)
            inst_of[i] = inst_idx[inst]

        mask = np.ones(n, dtype=np.bool_)
        columns = [
            Column("dataset_date", DType.INTEGRAL, values=dates, mask=mask),
            Column("value", DType.FRACTIONAL, values=values, mask=mask),
            Column(
                "series", DType.STRING, codes=series_codes,
                dictionary=_object_array(series_labels),
            ),
            Column(
                "metric", DType.STRING,
                codes=(name_of[series_codes] if n else series_codes),
                dictionary=_object_array(names),
            ),
            Column(
                "instance", DType.STRING,
                codes=(inst_of[series_codes] if n else series_codes),
                dictionary=_object_array(insts),
            ),
        ]
        for k in sorted(tag_codes):
            columns.append(Column(
                f"tag:{k}", DType.STRING, codes=tag_codes[k],
                dictionary=_object_array(tag_labels.get(k, [])),
            ))
        table = ColumnarTable(columns)
        if n:
            # dict-heavy numeric planes ride int16 codes to the device;
            # near-unique planes silently stay decoded (the ColumnChunk
            # cardinality rule)
            table.encode(["dataset_date", "value"])
        return _HistoryView(
            table, dates, series_codes, series_labels, series_meta,
            tag_codes, tag_labels,
        )

    # -- queries ---------------------------------------------------------

    def query(self, query=None, **kw):
        """Run one :class:`~deequ_tpu.repository.query.RepositoryQuery`
        as a fused engine scan over :meth:`history_table` (see
        repository/query.py). Keyword form:
        ``repo.query(metric_name="Completeness", after=..., tag_values=...)``."""
        from deequ_tpu.repository.query import RepositoryQuery, run_repository_query

        if query is None:
            query = RepositoryQuery(**kw)
        return run_repository_query(self, query)
