"""Repository queries compiled into engine scans (ROADMAP item 5).

A :class:`RepositoryQuery` — a trend window over one metric series, a
tag-filtered slice, or a cross-tenant aggregate ("completeness of column
X across all tenants this hour") — lowers onto the columnar repository's
own history table and executes through the SAME fused-scan path every
verification run uses: ``run_scan`` over analyzers on the ``value``
plane, kernel variants resolved by ``plan_scan_ops``, the plan declared
to the static plan lint, passes and fetches counted by ``ScanStats``.
The repository is just another table the engine verifies (Eiger,
arXiv:2607.04489).

Filter predicates (date bounds, series identity, tag equality) evaluate
on the HOST over the int32/int16 code planes — O(N) integer compares,
no decode — and the surviving rows stay dictionary-encoded through
``filter_rows``/``take`` into the scan, so a dict-heavy history ships
2-byte codes to the device instead of full-width planes.

``loader_side_aggregates`` is the A/B baseline the bench probe compares
against: the same query answered the pre-columnar way — pull every save
through the loader DSL, iterate AnalysisResults in Python, rebuild a
decoded table, re-scan. Both paths end in the same engine arithmetic,
so their results must be BIT-identical (the probe refuses to report
otherwise); the columnar path just skips the decode.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.repository.columnar import REPO_STATS

#: aggregate name -> analyzer factory over the history value plane
_AGGREGATES = {
    "count": lambda: _analyzers_mod().Size(),
    "completeness": lambda: _analyzers_mod().Completeness("value"),
    "mean": lambda: _analyzers_mod().Mean("value"),
    "min": lambda: _analyzers_mod().Minimum("value"),
    "max": lambda: _analyzers_mod().Maximum("value"),
    "sum": lambda: _analyzers_mod().Sum("value"),
    "stddev": lambda: _analyzers_mod().StandardDeviation("value"),
}

DEFAULT_AGGREGATES = ("count", "mean", "min", "max")


def _analyzers_mod():
    import deequ_tpu.analyzers as analyzers

    return analyzers


class RepositoryQuery:
    """One declarative repository query (normalized, hashable-ish).

    - ``analyzers``: restrict to these exact analyzer series (the trend
      window / anomaly-history shape);
    - ``metric_name`` / ``instance``: restrict by flattened identity
      (the cross-tenant shape: ``metric_name="Completeness",
      instance="x"`` is "completeness of column x across all tenants");
    - ``tag_values``: every (key, value) must match the saving
      ``ResultKey``'s tags;
    - ``after`` / ``before``: inclusive dataset-date bounds, identical
      semantics to the loader DSL;
    - ``aggregates``: which reductions run over the matching value rows.
    """

    def __init__(
        self,
        analyzers: Optional[Sequence] = None,
        metric_name: Optional[str] = None,
        instance: Optional[str] = None,
        tag_values: Optional[Dict[str, str]] = None,
        after: Optional[int] = None,
        before: Optional[int] = None,
        aggregates: Sequence[str] = DEFAULT_AGGREGATES,
    ):
        self.analyzers = tuple(analyzers) if analyzers is not None else None
        self.metric_name = metric_name
        self.instance = instance
        self.tag_values = (
            tuple(sorted(tag_values.items())) if tag_values else None
        )
        self.after = after
        self.before = before
        aggregates = tuple(aggregates)
        for agg in aggregates:
            if agg not in _AGGREGATES:
                raise ValueError(
                    f"unknown aggregate {agg!r}; choose from "
                    f"{sorted(_AGGREGATES)}"
                )
        if not aggregates:
            raise ValueError("aggregates must not be empty")
        self.aggregates = aggregates

    def __repr__(self) -> str:
        parts = []
        for name in ("analyzers", "metric_name", "instance", "tag_values",
                     "after", "before"):
            v = getattr(self, name)
            if v is not None:
                parts.append(f"{name}={v!r}")
        parts.append(f"aggregates={self.aggregates!r}")
        return f"RepositoryQuery({', '.join(parts)})"


@dataclass
class RepositoryQueryResult:
    """What one compiled query returns: the matched-row count, the
    scalar aggregate values, and the full metric objects (failure
    metrics included — an empty window fails its mean typed, never
    silently)."""

    rows: int
    aggregates: Dict[str, float] = field(default_factory=dict)
    metrics: Dict[str, Any] = field(default_factory=dict)


def _series_code_set(view, query: RepositoryQuery) -> Optional[set]:
    """Global series codes matching the query's identity filters, or
    None when the query does not filter by identity."""
    if (
        query.analyzers is None
        and query.metric_name is None
        and query.instance is None
    ):
        return None
    import json

    from deequ_tpu.repository import serde

    targets = None
    if query.analyzers is not None:
        targets = {
            json.dumps(
                serde.analyzer_to_json(a), sort_keys=True,
                separators=(",", ":"),
            )
            for a in query.analyzers
        }
    out = set()
    for code, (ajson, _entity, name, instance) in enumerate(view.series_meta):
        if targets is not None and ajson not in targets:
            continue
        if query.metric_name is not None and name != query.metric_name:
            continue
        if query.instance is not None and instance != query.instance:
            continue
        out.add(code)
    return out


def _row_mask(view, query: RepositoryQuery) -> np.ndarray:
    mask = np.ones(view.num_rows, dtype=np.bool_)
    if query.after is not None:
        mask &= view.dates >= int(query.after)
    if query.before is not None:
        mask &= view.dates <= int(query.before)
    codes = _series_code_set(view, query)
    if codes is not None:
        if codes:
            wanted = np.fromiter(
                sorted(codes), dtype=np.int32, count=len(codes)
            )
            mask &= np.isin(view.series_codes, wanted)
        else:
            mask &= False
    if query.tag_values:
        for k, v in query.tag_values:
            col = view.tag_codes.get(k)
            if col is None:
                mask &= False
                continue
            idx = -1
            labels = view.tag_labels.get(k, [])
            try:
                idx = labels.index(v)
            except ValueError:
                mask &= False
                continue
            mask &= col == idx
    return mask


def run_repository_query(
    repository,
    query: RepositoryQuery,
    plan_lint: Optional[str] = None,
    encoded_ingest: Optional[bool] = None,
) -> RepositoryQueryResult:
    """Lower ``query`` onto the repository's history table and execute
    it as ONE fused engine scan (see module doc). ``plan_lint`` /
    ``encoded_ingest`` pass straight through to ``run_scan`` — a query
    is linted and counted exactly like any verification scan."""
    view = repository._history_view()
    mask = _row_mask(view, query)
    sub = view.table.filter_rows(mask)

    analyzers = [_AGGREGATES[a]() for a in query.aggregates]
    ctx, scanned = _scan_aggregates(
        sub, analyzers, plan_lint=plan_lint, encoded_ingest=encoded_ingest
    )
    if scanned:
        REPO_STATS.query_scan_passes += 1
    REPO_STATS.queries += 1
    REPO_STATS.query_rows_scanned += sub.num_rows

    return _result_from_ctx(query, sub.num_rows, analyzers, ctx)


def _scan_aggregates(table, analyzers, plan_lint=None, encoded_ingest=None):
    """ONE scan-execution block shared by both query paths (compiled
    columnar and loader-side baseline) — they must stay bit-identical,
    so failure-metric handling and scan finalization cannot fork."""
    from deequ_tpu.analyzers.runner import AnalysisRunner, AnalyzerContext
    from deequ_tpu.ops.scan_engine import run_scan

    ops, scannable, op_failures = AnalysisRunner._build_scan_ops(
        table, analyzers
    )
    ctx = AnalyzerContext.empty()
    for analyzer, err in op_failures.items():
        ctx.metric_map[analyzer] = analyzer.to_failure_metric(err)
    if not scannable:
        return ctx, False
    exec_ops, plan = AnalysisRunner._coalesce_scan_ops(ops)
    results = run_scan(
        table, exec_ops,
        plan_lint=plan_lint,
        encoded_ingest=encoded_ingest,
    )
    ctx = AnalysisRunner._finalize_scanning_analyzers(
        ctx, scannable, plan, results
    )
    return ctx, True


def _result_from_ctx(query, rows, analyzers, ctx) -> RepositoryQueryResult:
    out = RepositoryQueryResult(rows=rows)
    for agg, analyzer in zip(query.aggregates, analyzers):
        metric = ctx.metric_map.get(analyzer)
        out.metrics[agg] = metric
        if metric is not None and metric.value.is_success:
            out.aggregates[agg] = metric.value.get()
    return out


def loader_side_aggregates(
    repository, query: RepositoryQuery
) -> RepositoryQueryResult:
    """The pre-columnar baseline: answer the SAME query through the
    loader interface — decode every save into AnalysisResults, filter
    and collect matching values by Python iteration, rebuild a decoded
    in-memory table, and scan it with the same aggregate analyzers
    (encoded ingest off: the decoded f64 planes ship full-width). The
    bench A/B gates on this path's results being bit-identical to the
    compiled columnar query."""
    from deequ_tpu.data.table import Column, ColumnarTable, DType
    from deequ_tpu.metrics import DoubleMetric
    from deequ_tpu.repository.columnar import series_identity

    loader = repository.load()
    if query.tag_values:
        loader = loader.with_tag_values(dict(query.tag_values))
    if query.after is not None:
        loader = loader.after(query.after)
    if query.before is not None:
        loader = loader.before(query.before)
    if query.analyzers is not None:
        loader = loader.for_analyzers(list(query.analyzers))

    values: List[float] = []
    for result in loader.get():
        for analyzer, metric in result.analyzer_context.metric_map.items():
            if not isinstance(metric, DoubleMetric):
                continue
            if not metric.value.is_success:
                continue
            if not isinstance(metric.value.get(), float):
                continue
            if series_identity(analyzer, metric) is None:
                continue
            if (
                query.metric_name is not None
                and metric.name != query.metric_name
            ):
                continue
            if query.instance is not None and metric.instance != query.instance:
                continue
            values.append(metric.value.get())

    n = len(values)
    table = ColumnarTable([
        Column(
            "value", DType.FRACTIONAL,
            values=np.fromiter(values, dtype=np.float64, count=n),
            mask=np.ones(n, dtype=np.bool_),
        ),
    ])
    analyzers = [_AGGREGATES[a]() for a in query.aggregates]
    ctx, _ = _scan_aggregates(table, analyzers, encoded_ingest=False)
    return _result_from_ctx(query, n, analyzers, ctx)
