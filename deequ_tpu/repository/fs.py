"""Filesystem metrics repository — one JSON file of all results, read-modify-
write (reference repository/fs/FileSystemMetricsRepository.scala:32-226).
Local paths play the role of HDFS/S3.

Crash safety (resilience layer): ``_write_all`` commits via
write-temp-fsync-rename, so a crash mid-write leaves the previous complete
history, never a torn one; the file is wrapped in the shared checksum
envelope (resilience/atomic.py), so corruption that does reach disk (torn
writes on non-atomic stores, bit rot) is detected on read and surfaced as
a typed ``CorruptStateException`` instead of a raw ``JSONDecodeError``.
Storage calls run under the process retry policy (transient IOErrors are
retried with backoff). Legacy plain-JSON files keep loading.

Scaling note (round-13 audit): ``save`` is read-modify-write over ONE
JSON document of the full history — each save re-serializes every prior
result, so N saves cost O(N²) total bytes written. That is the reference
backend's own shape (FileSystemMetricsRepository.scala does the same)
and is kept here for conformance; a fleet emitting hundreds of saves per
run should use :class:`~deequ_tpu.repository.columnar.
ColumnarMetricsRepository` instead, whose append segments make each save
O(rows of that result) — the tier-1 ``mrepo`` regression pins ≥100
saves/run without a quadratic wall (docs/repository.md).
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.repository import serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.analyzers.runner import AnalyzerContext


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        from deequ_tpu.data.fs import filesystem_for, strip_scheme
        from deequ_tpu.resilience.retry import RetryingFileSystem

        self.path = strip_scheme(path)
        self._fs = RetryingFileSystem(filesystem_for(path))
        self._lock = threading.Lock()

    def _read_all(self) -> List[AnalysisResult]:
        if not self._fs.exists(self.path):
            return []
        from deequ_tpu.resilience.atomic import read_checksummed

        # enveloped files validate + strip; legacy plain-JSON files (no
        # envelope magic) pass through as raw bytes
        data = read_checksummed(
            self._fs, self.path, f"metrics repository {self.path}"
        )
        try:
            text = data.decode("utf-8")
        except UnicodeDecodeError as e:
            raise CorruptStateException(
                f"metrics repository {self.path}", f"undecodable bytes: {e}"
            ) from e
        if not text.strip():
            return []
        try:
            return serde.deserialize(text)
        except (ValueError, KeyError, TypeError) as e:
            raise CorruptStateException(
                f"metrics repository {self.path}",
                f"undecodable results payload: {e}",
            ) from e

    def _write_all(self, results: List[AnalysisResult]) -> None:
        parent = os.path.dirname(self.path)
        if parent:
            self._fs.makedirs(parent)
        from deequ_tpu.resilience.atomic import atomic_write_bytes, wrap_checksum

        payload = serde.serialize(results).encode("utf-8")
        atomic_write_bytes(self._fs, self.path, wrap_checksum(payload))

    def save(self, result: AnalysisResult) -> None:
        successful = AnalyzerContext(
            {
                a: m
                for a, m in result.analyzer_context.metric_map.items()
                if m.value.is_success
            }
        )
        to_save = AnalysisResult(result.result_key, successful)
        with self._lock:
            existing = self._read_all()
            existing = [
                r for r in existing if r.result_key != result.result_key
            ]
            existing.append(to_save)
            self._write_all(existing)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalysisResult]:
        with self._lock:
            for r in self._read_all():
                if r.result_key == result_key:
                    return r
        return None

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        def provider() -> List[AnalysisResult]:
            with self._lock:
                return self._read_all()

        return MetricsRepositoryMultipleResultsLoader(provider)
