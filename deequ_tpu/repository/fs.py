"""Filesystem metrics repository — one JSON file of all results, read-modify-
write (reference repository/fs/FileSystemMetricsRepository.scala:32-226).
Local paths play the role of HDFS/S3."""

from __future__ import annotations

import os
import threading
from typing import List, Optional

from deequ_tpu.repository import serde
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)
from deequ_tpu.analyzers.runner import AnalyzerContext


class FileSystemMetricsRepository(MetricsRepository):
    def __init__(self, path: str):
        from deequ_tpu.data.fs import filesystem_for, strip_scheme

        self.path = strip_scheme(path)
        self._fs = filesystem_for(path)
        self._lock = threading.Lock()

    def _read_all(self) -> List[AnalysisResult]:
        if not self._fs.exists(self.path):
            return []
        with self._fs.open(self.path, "r") as f:
            text = f.read()
        if not text.strip():
            return []
        return serde.deserialize(text)

    def _write_all(self, results: List[AnalysisResult]) -> None:
        parent = os.path.dirname(self.path)
        if parent:
            self._fs.makedirs(parent)
        with self._fs.open(self.path, "w") as f:
            f.write(serde.serialize(results))

    def save(self, result: AnalysisResult) -> None:
        successful = AnalyzerContext(
            {
                a: m
                for a, m in result.analyzer_context.metric_map.items()
                if m.value.is_success
            }
        )
        to_save = AnalysisResult(result.result_key, successful)
        with self._lock:
            existing = self._read_all()
            existing = [
                r for r in existing if r.result_key != result.result_key
            ]
            existing.append(to_save)
            self._write_all(existing)

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalysisResult]:
        with self._lock:
            for r in self._read_all():
                if r.result_key == result_key:
                    return r
        return None

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        def provider() -> List[AnalysisResult]:
            with self._lock:
                return self._read_all()

        return MetricsRepositoryMultipleResultsLoader(provider)
