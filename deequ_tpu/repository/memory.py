"""In-memory metrics repository
(reference repository/memory/InMemoryMetricsRepository.scala:28-136)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from deequ_tpu.analyzers.runner import AnalyzerContext
from deequ_tpu.repository.base import (
    AnalysisResult,
    MetricsRepository,
    MetricsRepositoryMultipleResultsLoader,
    ResultKey,
)


class InMemoryMetricsRepository(MetricsRepository):
    def __init__(self):
        self._results: Dict[ResultKey, AnalysisResult] = {}
        self._lock = threading.Lock()

    def save(self, result: AnalysisResult) -> None:
        # keep only successful metrics, like the reference (L44-49)
        successful = AnalyzerContext(
            {
                a: m
                for a, m in result.analyzer_context.metric_map.items()
                if m.value.is_success
            }
        )
        with self._lock:
            self._results[result.result_key] = AnalysisResult(
                result.result_key, successful
            )

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalysisResult]:
        with self._lock:
            return self._results.get(result_key)

    def load(self) -> MetricsRepositoryMultipleResultsLoader:
        def provider() -> List[AnalysisResult]:
            with self._lock:
                return list(self._results.values())

        return MetricsRepositoryMultipleResultsLoader(provider)
