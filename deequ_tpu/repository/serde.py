"""Canonical JSON serde for analysis results (the analogue of the
reference's Gson-based AnalysisResultSerde, repository/AnalysisResultSerde.
scala:38-635). Round-trip (serialize then deserialize) is the identity for
every analyzer and metric type — asserted by tests/test_repository.py."""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional

from deequ_tpu.analyzers import (
    ApproxCountDistinct,
    ApproxQuantile,
    ApproxQuantiles,
    Completeness,
    Compliance,
    Correlation,
    CountDistinct,
    DataType,
    Distinctness,
    Entropy,
    Histogram,
    KLLParameters,
    KLLSketch,
    Maximum,
    MaxLength,
    Mean,
    Minimum,
    MinLength,
    MutualInformation,
    PatternMatch,
    Size,
    StandardDeviation,
    Sum,
    Uniqueness,
    UniqueValueRatio,
)
from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.runner import AnalyzerContext
from deequ_tpu.metrics import (
    BucketDistribution,
    BucketValue,
    Distribution,
    DistributionValue,
    DoubleMetric,
    Entity,
    HistogramMetric,
    KeyedDoubleMetric,
    KLLMetric,
    Metric,
)
from deequ_tpu.repository.base import AnalysisResult, ResultKey
from deequ_tpu.tryresult import Failure, Success


def analyzer_to_json(analyzer: Analyzer) -> Dict[str, Any]:
    name = type(analyzer).__name__
    out: Dict[str, Any] = {"analyzerName": name}
    if isinstance(analyzer, Size):
        out["where"] = analyzer.where
    elif isinstance(
        analyzer,
        (Completeness, Minimum, Maximum, MinLength, MaxLength, Mean, Sum,
         StandardDeviation, ApproxCountDistinct, DataType),
    ):
        out["column"] = analyzer.column
        out["where"] = analyzer.where
    elif isinstance(analyzer, Compliance):
        out["instance"] = analyzer.instance_name
        out["expression"] = analyzer.predicate
        out["where"] = analyzer.where
    elif isinstance(analyzer, PatternMatch):
        out["column"] = analyzer.column
        out["pattern"] = analyzer.pattern
        out["where"] = analyzer.where
    elif isinstance(analyzer, Correlation):
        out["firstColumn"] = analyzer.first_column
        out["secondColumn"] = analyzer.second_column
        out["where"] = analyzer.where
    elif isinstance(
        analyzer, (Uniqueness, UniqueValueRatio, Distinctness, CountDistinct)
    ):
        out["columns"] = list(analyzer.columns)
    elif isinstance(analyzer, Entropy):
        out["column"] = analyzer.column
    elif isinstance(analyzer, MutualInformation):
        out["columns"] = list(analyzer.columns)
    elif isinstance(analyzer, Histogram):
        if analyzer.binning_udf is not None:
            raise ValueError(
                "Unable to serialize Histogram with binningUdf!"
            )  # mirrors the reference's restriction
        out["column"] = analyzer.column
        out["maxDetailBins"] = analyzer.max_detail_bins
    elif isinstance(analyzer, KLLSketch):
        out["column"] = analyzer.column
        if analyzer.kll_parameters is not None:
            p = analyzer.kll_parameters
            out["kllParameters"] = {
                "sketchSize": p.sketch_size,
                "shrinkingFactor": p.shrinking_factor,
                "numberOfBuckets": p.number_of_buckets,
            }
    elif isinstance(analyzer, ApproxQuantile):
        out["column"] = analyzer.column
        out["quantile"] = analyzer.quantile
        out["relativeError"] = analyzer.relative_error
        out["where"] = analyzer.where
    elif isinstance(analyzer, ApproxQuantiles):
        out["column"] = analyzer.column
        out["quantiles"] = list(analyzer.quantiles)
        out["relativeError"] = analyzer.relative_error
    else:
        raise ValueError(f"Unable to serialize analyzer {analyzer!r}")
    return out


def analyzer_from_json(data: Dict[str, Any]) -> Analyzer:
    name = data["analyzerName"]
    where = data.get("where")
    if name == "Size":
        return Size(where=where)
    if name == "Completeness":
        return Completeness(data["column"], where)
    if name == "Compliance":
        return Compliance(data["instance"], data["expression"], where)
    if name == "PatternMatch":
        return PatternMatch(data["column"], data["pattern"], where)
    if name in ("Minimum", "Maximum", "MinLength", "MaxLength", "Mean", "Sum",
                "StandardDeviation", "ApproxCountDistinct", "DataType"):
        cls = {
            "Minimum": Minimum, "Maximum": Maximum, "MinLength": MinLength,
            "MaxLength": MaxLength, "Mean": Mean, "Sum": Sum,
            "StandardDeviation": StandardDeviation,
            "ApproxCountDistinct": ApproxCountDistinct, "DataType": DataType,
        }[name]
        return cls(data["column"], where)
    if name == "Correlation":
        return Correlation(data["firstColumn"], data["secondColumn"], where)
    if name in ("Uniqueness", "UniqueValueRatio", "Distinctness", "CountDistinct"):
        cls = {
            "Uniqueness": Uniqueness, "UniqueValueRatio": UniqueValueRatio,
            "Distinctness": Distinctness, "CountDistinct": CountDistinct,
        }[name]
        return cls(tuple(data["columns"]))
    if name == "Entropy":
        return Entropy(data["column"])
    if name == "MutualInformation":
        return MutualInformation(tuple(data["columns"]))
    if name == "Histogram":
        return Histogram(data["column"], None, data.get("maxDetailBins", 1000))
    if name == "KLLSketch":
        params = None
        if "kllParameters" in data:
            p = data["kllParameters"]
            params = KLLParameters(
                p["sketchSize"], p["shrinkingFactor"], p["numberOfBuckets"]
            )
        return KLLSketch(data["column"], params)
    if name == "ApproxQuantile":
        return ApproxQuantile(
            data["column"], data["quantile"], data.get("relativeError", 0.01), where
        )
    if name == "ApproxQuantiles":
        return ApproxQuantiles(
            data["column"], data["quantiles"], data.get("relativeError", 0.01)
        )
    raise ValueError(f"Unable to deserialize analyzer {name}")


def _sanitize(value: float):
    if isinstance(value, float) and (math.isnan(value) or math.isinf(value)):
        return {"__special__": repr(value)}
    return value


def _unsanitize(value):
    if isinstance(value, dict) and "__special__" in value:
        return float(value["__special__"])
    return value


def metric_to_json(metric: Metric) -> Dict[str, Any]:
    base = {
        "entity": metric.entity.value,
        "instance": metric.instance,
        "name": metric.name,
    }
    if metric.value.is_failure:
        base["isSuccess"] = False
        base["error"] = str(metric.value.exception)
        base["metricType"] = type(metric).__name__
        return base
    base["isSuccess"] = True
    value = metric.value.get()
    if isinstance(metric, DoubleMetric):
        base["metricType"] = "DoubleMetric"
        base["value"] = _sanitize(value)
    elif isinstance(metric, KeyedDoubleMetric):
        base["metricType"] = "KeyedDoubleMetric"
        base["value"] = {k: _sanitize(v) for k, v in value.items()}
    elif isinstance(metric, HistogramMetric):
        base["metricType"] = "HistogramMetric"
        base["value"] = {
            "numberOfBins": value.number_of_bins,
            "values": {
                k: {"absolute": dv.absolute, "ratio": dv.ratio}
                for k, dv in value.values.items()
            },
        }
    elif isinstance(metric, KLLMetric):
        base["metricType"] = "KLLMetric"
        base["value"] = {
            "buckets": [
                {"lowValue": b.low_value, "highValue": b.high_value, "count": b.count}
                for b in value.buckets
            ],
            "parameters": list(value.parameters),
            "data": [list(buf) for buf in value.data],
        }
    else:
        raise ValueError(f"Unable to serialize metric {metric!r}")
    return base


def metric_from_json(data: Dict[str, Any]) -> Metric:
    entity = Entity(data["entity"])
    instance = data["instance"]
    name = data["name"]
    metric_type = data["metricType"]
    if not data.get("isSuccess", True):
        from deequ_tpu.exceptions import MetricCalculationRuntimeException

        failure = Failure(MetricCalculationRuntimeException(data.get("error", "")))
        if metric_type == "HistogramMetric":
            return HistogramMetric(instance, failure, entity, name)
        if metric_type == "KLLMetric":
            return KLLMetric(instance, failure, entity, name)
        if metric_type == "KeyedDoubleMetric":
            return KeyedDoubleMetric(entity, name, instance, failure)
        return DoubleMetric(entity, name, instance, failure)
    value = data["value"]
    if metric_type == "DoubleMetric":
        return DoubleMetric(entity, name, instance, Success(_unsanitize(value)))
    if metric_type == "KeyedDoubleMetric":
        return KeyedDoubleMetric(
            entity, name, instance,
            Success({k: _unsanitize(v) for k, v in value.items()}),
        )
    if metric_type == "HistogramMetric":
        dist = Distribution(
            {
                k: DistributionValue(v["absolute"], v["ratio"])
                for k, v in value["values"].items()
            },
            value["numberOfBins"],
        )
        return HistogramMetric(instance, Success(dist), entity, name)
    if metric_type == "KLLMetric":
        dist = BucketDistribution(
            [
                BucketValue(b["lowValue"], b["highValue"], b["count"])
                for b in value["buckets"]
            ],
            tuple(value["parameters"]),
            tuple(tuple(buf) for buf in value["data"]),
        )
        return KLLMetric(instance, Success(dist), entity, name)
    raise ValueError(f"Unable to deserialize metric type {metric_type}")


def serialize(results: List[AnalysisResult]) -> str:
    payload = []
    for result in results:
        entries = []
        for analyzer, metric in result.analyzer_context.metric_map.items():
            try:
                a_json = analyzer_to_json(analyzer)
            except ValueError:
                continue  # non-serializable analyzers are skipped, like the reference
            entries.append({"analyzer": a_json, "metric": metric_to_json(metric)})
        payload.append(
            {
                "resultKey": {
                    "dataSetDate": result.result_key.data_set_date,
                    "tags": result.result_key.tags_dict,
                },
                "analyzerContext": entries,
            }
        )
    return json.dumps(payload)


def deserialize(text: str) -> List[AnalysisResult]:
    payload = json.loads(text)
    results = []
    for item in payload:
        key = ResultKey(
            item["resultKey"]["dataSetDate"], item["resultKey"].get("tags", {})
        )
        metric_map = {}
        for entry in item["analyzerContext"]:
            analyzer = analyzer_from_json(entry["analyzer"])
            metric_map[analyzer] = metric_from_json(entry["metric"])
        results.append(AnalysisResult(key, AnalyzerContext(metric_map)))
    return results
