"""Repository core types (reference repository/MetricsRepository.scala,
repository/AnalysisResult.scala, MetricsRepositoryMultipleResultsLoader.scala)."""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.analyzers.runner import AnalyzerContext


@dataclass(frozen=True)
class ResultKey:
    """(reference repository/MetricsRepository.scala:51)"""

    data_set_date: int
    tags: Tuple[Tuple[str, str], ...] = ()

    def __init__(self, data_set_date: int, tags=None):
        object.__setattr__(self, "data_set_date", int(data_set_date))
        if tags is None:
            normalized: Tuple[Tuple[str, str], ...] = ()
        elif isinstance(tags, dict):
            normalized = tuple(sorted(tags.items()))
        else:
            normalized = tuple(sorted(tuple(t) for t in tags))
        object.__setattr__(self, "tags", normalized)

    @property
    def tags_dict(self) -> Dict[str, str]:
        return dict(self.tags)

    @staticmethod
    def current_milli_time() -> int:
        return int(time.time() * 1000)


@dataclass
class AnalysisResult:
    """(reference repository/AnalysisResult.scala:25)"""

    result_key: ResultKey
    analyzer_context: AnalyzerContext


class MetricsRepository:
    """(reference repository/MetricsRepository.scala:25-43)"""

    def save(self, result: AnalysisResult) -> None:
        raise NotImplementedError

    def load_by_key(self, result_key: ResultKey) -> Optional[AnalysisResult]:
        raise NotImplementedError

    def load(self) -> "MetricsRepositoryMultipleResultsLoader":
        raise NotImplementedError


class MetricsRepositoryMultipleResultsLoader:
    """Query DSL over the stored time series
    (reference repository/MetricsRepositoryMultipleResultsLoader.scala:33-137)."""

    def __init__(self, results_provider):
        self._results_provider = results_provider  # () -> List[AnalysisResult]
        self._tag_values: Optional[Dict[str, str]] = None
        self._analyzers: Optional[List[Analyzer]] = None
        self._after: Optional[int] = None
        self._before: Optional[int] = None

    def with_tag_values(self, tag_values: Dict[str, str]):
        self._tag_values = dict(tag_values)
        return self

    def for_analyzers(self, analyzers: Sequence[Analyzer]):
        self._analyzers = list(analyzers)
        return self

    def after(self, data_set_date: int):
        self._after = data_set_date
        return self

    def before(self, data_set_date: int):
        self._before = data_set_date
        return self

    def get(self) -> List[AnalysisResult]:
        results = self._results_provider()
        out = []
        for result in results:
            key = result.result_key
            if self._after is not None and key.data_set_date < self._after:
                continue
            if self._before is not None and key.data_set_date > self._before:
                continue
            if self._tag_values is not None:
                key_tags = key.tags_dict
                if not all(
                    key_tags.get(k) == v for k, v in self._tag_values.items()
                ):
                    continue
            ctx = result.analyzer_context
            if self._analyzers is not None:
                ctx = AnalyzerContext(
                    {
                        a: m
                        for a, m in ctx.metric_map.items()
                        if a in self._analyzers
                    }
                )
            out.append(AnalysisResult(key, ctx))
        return out

    def get_success_metrics_as_rows(
        self, with_tags: Optional[Sequence[str]] = None
    ) -> List[dict]:
        """Flattened metric rows with dataset_date + tag columns
        (DataFrame exporter analogue)."""
        rows = []
        for result in self.get():
            base_rows = AnalyzerContext.success_metrics_as_rows(
                result.analyzer_context
            )
            tags = result.result_key.tags_dict
            for row in base_rows:
                row = dict(row)
                row["dataset_date"] = result.result_key.data_set_date
                for tag_name, tag_value in tags.items():
                    if with_tags is None or tag_name in with_tags:
                        row[tag_name] = tag_value
                rows.append(row)
        return rows

    def get_success_metrics_as_json(
        self, with_tags: Optional[Sequence[str]] = None
    ) -> str:
        return json.dumps(self.get_success_metrics_as_rows(with_tags))
