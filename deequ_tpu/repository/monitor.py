"""Online quality monitoring: anomaly strategies fed at result-ingest
time (ROADMAP item 5; TiLT, arXiv:2301.12030 — time-centric state
carried forward, never refit from scratch).

The batch anomaly path (``checks.is_newest_point_non_anomalous``) pulls
the FULL history through the repository loader per check — O(history)
per verification, and only when someone asks. The
:class:`QualityMonitor` inverts that: it hooks the repository's save
seam (``ColumnarMetricsRepository(monitor=...)``) and the serving
layer's resolve seam (``VerificationService(monitor=...)``), folding
every new metric point into PER-SERIES incremental state:

- **Holt-Winters** (``anomaly/seasonal.py``): (alpha, beta, gamma) fit
  ONCE when the series reaches two full cycles (the same jax-autodiff
  fit the batch strategy uses), then level/trend/season carried forward
  per point — O(1) per observation, no refit;
- **OnlineNormal**: the Welford (mean, M2) recursion carried forward,
  anomalous points optionally excluded from the running stats;
- any other :class:`AnomalyDetectionStrategy`: a bounded per-series
  history window replayed through the strategy's own
  ``is_new_point_anomalous`` (exact batch semantics, O(window) per
  point).

Out-of-bounds points emit typed :class:`QualityAlert` events — onto the
monitor's bounded ledger, the flight recorder (an instant event when
tracing is armed), and the unified metrics registry's ``repository``
section (``deequ_tpu.execution_report()`` shows ``alerts_emitted``).

Kill-and-resume is bit-identical: per-series state (floats serialized
as ``float.hex`` — exact) plus the alert ledger checkpoint atomically
through the PR-2 machinery (checksummed envelope + atomic rename,
``resilience/atomic.py``). On resume, :meth:`catch_up` replays the
repository's history; each state's ``last_time`` gate skips
already-folded points, so the resumed state equals the uninterrupted
run's bit for bit and no :class:`QualityAlert` is ever emitted twice
(pre-checkpoint alerts live in the persisted ledger; replay emits only
post-checkpoint times).

``DEEQU_TPU_MONITOR=0`` (envcfg) disables observation process-wide —
saves and serving are unaffected, alerts just stop.
"""

from __future__ import annotations

import json
import math
import threading
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.metrics import DoubleMetric

STATE_FILE = "monitor_state.dqmn"
STATE_VERSION = 1


class _MonitorStats:
    """Process-wide monitor observables (merged into the ``repository``
    registry section beside REPO_STATS)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.monitor_observations = 0
        self.monitor_points_folded = 0
        self.monitor_stale_points = 0
        self.alerts_emitted = 0
        self.monitor_checkpoints = 0
        self.monitor_resumes = 0
        self.monitor_errors = 0

    def snapshot(self) -> dict:
        return dict(self.__dict__)


MONITOR_STATS = _MonitorStats()


def _hex(x: float) -> str:
    return float(x).hex()


def _unhex(s: str) -> float:
    return float.fromhex(s)


@dataclass
class QualityAlert:
    """One typed anomaly event: which watch rule fired, on which series
    (identity + tags), at which dataset time, with the offending value
    and the strategy's confidence/detail."""

    rule: str
    series: str
    time: int
    value: float
    confidence: float = 1.0
    detail: Optional[str] = None

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "series": self.series,
            "time": self.time,
            "value": self.value,
            "confidence": self.confidence,
            "detail": self.detail,
        }


# -- per-series incremental states ------------------------------------------


class _SeriesState:
    """One (rule, series) incremental state. ``update`` folds one point
    and returns the anomalies it triggered; points at or before
    ``last_time`` are STALE (already folded — the resume/replay dedup
    gate) and must be skipped by the caller."""

    kind = "generic"

    def __init__(self):
        self.last_time: Optional[int] = None
        self.count = 0

    def update(self, time: int, value: float) -> List[Tuple[float, str]]:
        raise NotImplementedError

    def to_blob(self) -> dict:
        raise NotImplementedError

    @classmethod
    def from_blob(cls, rule, blob: dict) -> "_SeriesState":
        raise NotImplementedError


class _GenericSeriesState(_SeriesState):
    """Fallback for arbitrary strategies: keep a bounded window of the
    series and ask the strategy's own ``is_new_point_anomalous`` —
    exact batch semantics per point (a strategy that raises on
    insufficient history is in warmup: no anomaly yet)."""

    kind = "generic"

    def __init__(self, strategy, max_history: int):
        super().__init__()
        self.strategy = strategy
        self.max_history = max_history
        self.history: List[Tuple[int, float]] = []

    def update(self, time: int, value: float) -> List[Tuple[float, str]]:
        from deequ_tpu.anomaly import AnomalyDetector
        from deequ_tpu.anomaly.history import DataPoint

        out: List[Tuple[float, str]] = []
        if self.history:
            detector = AnomalyDetector(self.strategy)
            points = [DataPoint(t, v) for t, v in self.history]
            try:
                result = detector.is_new_point_anomalous(
                    points, DataPoint(time, value)
                )
                out = [
                    (a.confidence, a.detail)
                    for _, a in result.anomalies
                ]
            except ValueError:
                # the strategy needs more history than the window holds
                # yet (HoltWinters two-cycle minimum, BatchNormal's
                # training requirement): warmup, not an anomaly
                out = []
        self.history.append((time, value))
        if len(self.history) > self.max_history:
            self.history = self.history[-self.max_history:]
        self.last_time = time
        self.count += 1
        return out

    def to_blob(self) -> dict:
        return {
            "last_time": self.last_time,
            "count": self.count,
            "history": [(t, _hex(v)) for t, v in self.history],
        }

    @classmethod
    def from_blob(cls, rule, blob: dict) -> "_GenericSeriesState":
        state = cls(rule.strategy, rule.max_history)
        state.last_time = blob["last_time"]
        state.count = blob["count"]
        state.history = [(t, _unhex(v)) for t, v in blob["history"]]
        return state


class _OnlineNormalSeriesState(_SeriesState):
    """Welford mean/variance carried forward; a point outside
    mean ± factor·stddev (after ``warmup`` points) alerts, and —
    matching the batch strategy's ``ignore_anomalies`` — is excluded
    from the running statistics so one outlier cannot widen the
    envelope that should keep catching its successors."""

    kind = "online_normal"

    def __init__(self, strategy, warmup: int):
        super().__init__()
        self.strategy = strategy
        self.warmup = warmup
        self.n = 0
        self.mean = 0.0
        self.m2 = 0.0

    def _bounds(self) -> Tuple[float, float]:
        std = math.sqrt(self.m2 / self.n) if self.n > 0 else 0.0
        lo_f = self.strategy.lower_deviation_factor
        hi_f = self.strategy.upper_deviation_factor
        lower = self.mean - (lo_f * std if lo_f is not None else math.inf)
        upper = self.mean + (hi_f * std if hi_f is not None else math.inf)
        return lower, upper

    def update(self, time: int, value: float) -> List[Tuple[float, str]]:
        out: List[Tuple[float, str]] = []
        anomalous = False
        if self.n >= self.warmup:
            lower, upper = self._bounds()
            if value < lower or value > upper:
                anomalous = True
                out.append((
                    1.0,
                    f"[OnlineNormal] value {value} outside "
                    f"[{lower}, {upper}] after {self.n} points",
                ))
        if not (anomalous and self.strategy.ignore_anomalies):
            self.n += 1
            delta = value - self.mean
            self.mean += delta / self.n
            self.m2 += delta * (value - self.mean)
        self.last_time = time
        self.count += 1
        return out

    def to_blob(self) -> dict:
        return {
            "last_time": self.last_time,
            "count": self.count,
            "n": self.n,
            "mean": _hex(self.mean),
            "m2": _hex(self.m2),
        }

    @classmethod
    def from_blob(cls, rule, blob: dict) -> "_OnlineNormalSeriesState":
        state = cls(rule.strategy, rule.warmup)
        state.last_time = blob["last_time"]
        state.count = blob["count"]
        state.n = blob["n"]
        state.mean = _unhex(blob["mean"])
        state.m2 = _unhex(blob["m2"])
        return state


class _HoltWintersSeriesState(_SeriesState):
    """Level/trend/season carried forward (ETS(A,A), the reference
    recursion from ``anomaly/seasonal.py``): the first ``2p`` points
    are warmup; at the boundary (alpha, beta, gamma) fit ONCE via the
    strategy's jax-autodiff objective and the recursion replays the
    warmup to seed state + one-step residual spread (Welford over
    |residual|). Every later point is O(1): forecast from carried
    state, alert past 1.96 residual sigmas, fold the observation in."""

    kind = "holt_winters"

    def __init__(self, strategy):
        super().__init__()
        self.strategy = strategy
        self.p = strategy.series_periodicity
        self.warmup_values: List[float] = []
        self.armed = False
        self.abg: Optional[Tuple[float, float, float]] = None
        self.level = 0.0
        self.trend = 0.0
        self.season: List[float] = []
        self.rn = 0
        self.rmean = 0.0
        self.rm2 = 0.0

    def _residual_sd(self) -> float:
        if self.rn <= 1:
            return 0.0
        return math.sqrt(self.rm2 / (self.rn - 1))

    def _fold_residual(self, r: float) -> None:
        self.rn += 1
        delta = r - self.rmean
        self.rmean += delta / self.rn
        self.rm2 += delta * (r - self.rmean)

    def _step(self, observed: float) -> float:
        """One recursion step: returns the one-step-ahead forecast the
        state held BEFORE folding ``observed`` in."""
        a, b, g = self.abg
        st = self.season[0]
        forecast = self.level + self.trend + st
        new_level = a * (observed - st) + (1 - a) * (self.level + self.trend)
        new_trend = b * (new_level - self.level) + (1 - b) * self.trend
        new_season = g * (observed - self.level - self.trend) + (1 - g) * st
        self.level = new_level
        self.trend = new_trend
        self.season = self.season[1:] + [new_season]
        return forecast

    def _arm(self) -> None:
        import numpy as np

        from deequ_tpu.anomaly.seasonal import _fit_parameters_jax

        p = self.p
        training = np.fromiter(
            self.warmup_values, dtype=np.float64,
            count=len(self.warmup_values),
        )
        self.abg = _fit_parameters_jax(training, p)
        level0 = float(training[:p].sum() / p)
        trend0 = float(
            (training[p:2 * p].sum() - training[:p].sum()) / (p * p)
        )
        self.level = level0
        self.trend = trend0
        self.season = [float(v - level0) for v in self.warmup_values[:p]]
        # replay the warmup through the recursion: state ends where a
        # batch fit over the same points would, and the one-step
        # residuals seed the alert envelope
        for observed in self.warmup_values:
            forecast = self._step(observed)
            self._fold_residual(abs(observed - forecast))
        self.armed = True
        self.warmup_values = []

    def update(self, time: int, value: float) -> List[Tuple[float, str]]:
        out: List[Tuple[float, str]] = []
        if not self.armed:
            self.warmup_values.append(value)
            if len(self.warmup_values) >= 2 * self.p:
                self._arm()
        else:
            sd = self._residual_sd()
            forecast = self._step(value)
            if abs(value - forecast) > 1.96 * sd:
                out.append((
                    1.0,
                    f"[HoltWinters] forecasted {forecast} for observed "
                    f"value {value}",
                ))
            self._fold_residual(abs(value - forecast))
        self.last_time = time
        self.count += 1
        return out

    def to_blob(self) -> dict:
        return {
            "last_time": self.last_time,
            "count": self.count,
            "p": self.p,
            "armed": self.armed,
            "warmup": [_hex(v) for v in self.warmup_values],
            "abg": [_hex(v) for v in self.abg] if self.abg else None,
            "level": _hex(self.level),
            "trend": _hex(self.trend),
            "season": [_hex(v) for v in self.season],
            "rn": self.rn,
            "rmean": _hex(self.rmean),
            "rm2": _hex(self.rm2),
        }

    @classmethod
    def from_blob(cls, rule, blob: dict) -> "_HoltWintersSeriesState":
        state = cls(rule.strategy)
        state.last_time = blob["last_time"]
        state.count = blob["count"]
        state.p = blob["p"]
        state.armed = blob["armed"]
        state.warmup_values = [_unhex(v) for v in blob["warmup"]]
        state.abg = (
            tuple(_unhex(v) for v in blob["abg"]) if blob["abg"] else None
        )
        state.level = _unhex(blob["level"])
        state.trend = _unhex(blob["trend"])
        state.season = [_unhex(v) for v in blob["season"]]
        state.rn = blob["rn"]
        state.rmean = _unhex(blob["rmean"])
        state.rm2 = _unhex(blob["rm2"])
        return state


_STATE_KINDS = {
    cls.kind: cls
    for cls in (
        _GenericSeriesState, _OnlineNormalSeriesState, _HoltWintersSeriesState
    )
}


@dataclass
class _WatchRule:
    """One registered watch: which metric points it matches and which
    strategy judges them."""

    name: str
    strategy: Any
    analyzer: Optional[Any] = None
    metric_name: Optional[str] = None
    instance: Optional[str] = None
    tag_values: Optional[Tuple[Tuple[str, str], ...]] = None
    warmup: int = 5
    max_history: int = 512

    def matches(self, analyzer, metric, tags: Dict[str, str]) -> bool:
        if self.analyzer is not None and analyzer != self.analyzer:
            return False
        if self.metric_name is not None and metric.name != self.metric_name:
            return False
        if self.instance is not None and metric.instance != self.instance:
            return False
        if self.tag_values:
            for k, v in self.tag_values:
                if tags.get(k) != v:
                    return False
        return True

    def make_state(self) -> _SeriesState:
        from deequ_tpu.anomaly.seasonal import HoltWinters
        from deequ_tpu.anomaly.strategies import OnlineNormalStrategy

        if isinstance(self.strategy, HoltWinters):
            return _HoltWintersSeriesState(self.strategy)
        if isinstance(self.strategy, OnlineNormalStrategy):
            return _OnlineNormalSeriesState(self.strategy, self.warmup)
        return _GenericSeriesState(self.strategy, self.max_history)


class QualityMonitor:
    """The online monitor (see module doc). Thread-safe: repository
    saves and serve-worker resolutions observe concurrently."""

    def __init__(
        self,
        state_dir: Optional[str] = None,
        checkpoint_every: int = 1,
        max_alerts: int = 4096,
        retry=None,
    ):
        if checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        self.checkpoint_every = int(checkpoint_every)
        self.max_alerts = int(max_alerts)
        self._rules: List[_WatchRule] = []
        self._states: Dict[Tuple[str, str], _SeriesState] = {}
        self.alerts: List[QualityAlert] = []
        self.alerts_dropped = 0
        self._lock = threading.RLock()
        self._obs_since_ckpt = 0
        self._fs = None
        self.state_dir = None
        if state_dir is not None:
            from deequ_tpu.data.fs import filesystem_for, strip_scheme
            from deequ_tpu.resilience.retry import RetryingFileSystem

            self.state_dir = strip_scheme(state_dir)
            self._fs = RetryingFileSystem(filesystem_for(state_dir), retry)
            self._load_state()

    # -- registration ----------------------------------------------------

    def watch(
        self,
        strategy,
        analyzer=None,
        metric_name: Optional[str] = None,
        instance: Optional[str] = None,
        tags: Optional[Dict[str, str]] = None,
        name: Optional[str] = None,
        warmup: int = 5,
        max_history: int = 512,
    ) -> str:
        """Register one watch rule; returns its name. At least one of
        ``analyzer`` / ``metric_name`` / ``instance`` should narrow the
        match (a bare rule watches EVERY scalar metric)."""
        with self._lock:
            rule_name = name or f"watch-{len(self._rules)}"
            if any(r.name == rule_name for r in self._rules):
                raise ValueError(f"duplicate watch rule name {rule_name!r}")
            self._rules.append(_WatchRule(
                name=rule_name,
                strategy=strategy,
                analyzer=analyzer,
                metric_name=metric_name,
                instance=instance,
                tag_values=(
                    tuple(sorted(tags.items())) if tags else None
                ),
                warmup=warmup,
                max_history=max_history,
            ))
            return rule_name

    @staticmethod
    def enabled() -> bool:
        from deequ_tpu.envcfg import env_value

        return bool(env_value("DEEQU_TPU_MONITOR"))

    # -- observation seams ----------------------------------------------

    def observe_result(self, result) -> List[QualityAlert]:
        """The repository save seam: fold one AnalysisResult's scalar
        metrics into every matching rule's series state. Returns the
        alerts this observation emitted."""
        key = result.result_key
        return self._observe_metrics(
            result.analyzer_context.metric_map,
            dict(key.tags),
            int(key.data_set_date),
        )

    def observe_verification(self, tenant, result) -> List[QualityAlert]:
        """The serving resolve seam (``VerificationService(monitor=...)``):
        fold a resolved VerificationResult's metrics as the tenant's
        series, timestamped by a per-series sequence (serving traffic
        has no dataset date — the stream position is the time axis)."""
        metric_map = getattr(result, "metrics", None)
        if not metric_map:
            return []
        tags = {"tenant": "?" if tenant is None else str(tenant)}
        return self._observe_metrics(metric_map, tags, None)

    def _observe_metrics(
        self,
        metric_map: Dict[Any, Any],
        tags: Dict[str, str],
        time: Optional[int],
    ) -> List[QualityAlert]:
        if not self.enabled():
            return []
        from deequ_tpu.repository.columnar import series_identity

        emitted: List[QualityAlert] = []
        with self._lock:
            if not self._rules:
                return []
            self._rebind_states()
            MONITOR_STATS.monitor_observations += 1
            tag_label = json.dumps(
                tags, sort_keys=True, separators=(",", ":")
            )
            for analyzer, metric in metric_map.items():
                if not isinstance(metric, DoubleMetric):
                    continue
                if not metric.value.is_success:
                    continue
                value = metric.value.get()
                if not isinstance(value, float):
                    continue
                identity = series_identity(analyzer, metric)
                if identity is None:
                    continue
                for rule in self._rules:
                    if not rule.matches(analyzer, metric, tags):
                        continue
                    series = f"{identity}|{tag_label}"
                    state = self._states.get((rule.name, series))
                    if state is None:
                        state = rule.make_state()
                        self._states[(rule.name, series)] = state
                    point_time = time
                    if point_time is None:
                        point_time = (
                            0 if state.last_time is None
                            else state.last_time + 1
                        )
                    if (
                        state.last_time is not None
                        and point_time <= state.last_time
                    ):
                        # already folded (a catch_up replay, or an
                        # out-of-order save): skipping is what makes
                        # resume alerts exactly-once
                        MONITOR_STATS.monitor_stale_points += 1
                        continue
                    for confidence, detail in state.update(
                        point_time, float(value)
                    ):
                        alert = QualityAlert(
                            rule=rule.name, series=series,
                            time=point_time, value=float(value),
                            confidence=confidence, detail=detail,
                        )
                        self._emit(alert)
                        emitted.append(alert)
                    MONITOR_STATS.monitor_points_folded += 1
            self._obs_since_ckpt += 1
            if (
                self._fs is not None
                and self._obs_since_ckpt >= self.checkpoint_every
            ):
                self._write_state()
        return emitted

    def _emit(self, alert: QualityAlert) -> None:
        self.alerts.append(alert)
        if len(self.alerts) > self.max_alerts:
            self.alerts = self.alerts[-self.max_alerts:]
            self.alerts_dropped += 1
        MONITOR_STATS.alerts_emitted += 1
        from deequ_tpu.obs.recorder import current_recorder

        rec = current_recorder()
        if rec is not None:
            rec.event(
                "quality_alert", rule=alert.rule, time=alert.time,
                value=alert.value, detail=alert.detail,
            )

    # -- checkpoint / resume ---------------------------------------------

    def catch_up(self, repository) -> int:
        """Replay a repository's live history through the observation
        seam (dataset-date order — the order a live monitor saw the
        saves in). Stale points are skipped by the per-series gate, so
        calling this after a resume folds exactly the points the killed
        monitor missed. Returns the number of results replayed."""
        results = repository.load().get()
        results = sorted(results, key=lambda r: r.result_key.data_set_date)
        for result in results:
            self.observe_result(result)
        return len(results)

    def _state_path(self) -> str:
        return self._fs.join(self.state_dir, STATE_FILE)

    def state_blob(self) -> dict:
        """The full serialized monitor state (also the bit-identity
        observable tests compare across kill-and-resume)."""
        with self._lock:
            states = {
                f"{rule_name}\x00{series}": {
                    "kind": state.kind,
                    "blob": state.to_blob(),
                }
                for (rule_name, series), state in sorted(
                    self._states.items()
                )
            }
            # recovered states whose rules were never re-registered ride
            # along unchanged — a checkpoint taken before registration
            # completes must not lose them
            for key, entry in (
                getattr(self, "_pending_states", None) or {}
            ).items():
                states.setdefault(key, entry)
            return {
                "version": STATE_VERSION,
                "rules": sorted(r.name for r in self._rules),
                "states": states,
                "alerts": [a.as_dict() for a in self.alerts],
                "alerts_dropped": self.alerts_dropped,
            }

    def _write_state(self) -> None:
        from deequ_tpu.resilience.atomic import atomic_write_bytes, wrap_checksum

        payload = json.dumps(
            self.state_blob(), separators=(",", ":")
        ).encode("utf-8")
        self._fs.makedirs(self.state_dir)
        atomic_write_bytes(
            self._fs, self._state_path(), wrap_checksum(payload),
            what="quality-monitor state",
        )
        self._obs_since_ckpt = 0
        MONITOR_STATS.monitor_checkpoints += 1

    def checkpoint(self) -> None:
        """Force a state checkpoint now (the periodic one runs every
        ``checkpoint_every`` observations)."""
        with self._lock:
            if self._fs is not None:
                self._write_state()

    def _load_state(self) -> None:
        from deequ_tpu.resilience.atomic import read_checksummed

        path = self._state_path()
        if not self._fs.exists(path):
            return
        payload = read_checksummed(
            self._fs, path, "quality-monitor state"
        )
        try:
            blob = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise CorruptStateException(
                "quality-monitor state", f"undecodable payload: {e}"
            ) from e
        if blob.get("version", 0) > STATE_VERSION:
            raise CorruptStateException(
                "quality-monitor state",
                f"version {blob.get('version')} newer than supported "
                f"{STATE_VERSION}",
            )
        self._pending_states = blob.get("states", {})
        self.alerts = [
            QualityAlert(**a) for a in blob.get("alerts", [])
        ]
        self.alerts_dropped = blob.get("alerts_dropped", 0)
        MONITOR_STATS.monitor_resumes += 1

    def _rebind_states(self) -> None:
        """Attach recovered state blobs to their (re-registered) rules.
        Called lazily after ``watch`` registrations so construction
        order (resume then register, like PR-2 checkpointers) works."""
        pending = getattr(self, "_pending_states", None)
        if not pending:
            return
        by_name = {r.name: r for r in self._rules}
        still_pending: Dict[str, dict] = {}
        for key, entry in pending.items():
            rule_name, _, series = key.partition("\x00")
            rule = by_name.get(rule_name)
            cls = _STATE_KINDS.get(entry.get("kind"))
            if rule is None or cls is None:
                # rule not (yet) re-registered: keep the blob pending so
                # a later registration — or the next checkpoint — still
                # carries it
                still_pending[key] = entry
                continue
            self._states[(rule_name, series)] = cls.from_blob(
                rule, entry["blob"]
            )
        self._pending_states = still_pending or None

    def resume(self) -> None:
        """Bind recovered per-series states to the registered rules.
        Call AFTER re-registering the same ``watch`` rules the killed
        monitor ran with."""
        with self._lock:
            self._rebind_states()
