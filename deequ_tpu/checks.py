"""The fluent Check DSL (reference checks/Check.scala, 1056 LoC).

A Check is an immutable list of constraints with a severity level; every
fluent method returns a new Check. Methods that accept a ``where`` filter
return a CheckWithLastConstraintFilterable whose ``.where(...)`` rebuilds the
last-added constraint with the filter
(reference checks/CheckWithLastConstraintFilterable.scala:22-53).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from deequ_tpu.analyzers.base import Analyzer
from deequ_tpu.constraints import (
    AnalysisBasedConstraint,
    ConstrainableDataTypes,
    Constraint,
    ConstraintDecorator,
    ConstraintResult,
    ConstraintStatus,
    anomaly_constraint,
    approx_count_distinct_constraint,
    approx_quantile_constraint,
    completeness_constraint,
    compliance_constraint,
    correlation_constraint,
    data_type_constraint,
    distinctness_constraint,
    entropy_constraint,
    histogram_bin_constraint,
    histogram_constraint,
    kll_constraint,
    max_constraint,
    max_length_constraint,
    mean_constraint,
    min_constraint,
    min_length_constraint,
    mutual_information_constraint,
    pattern_match_constraint,
    size_constraint,
    standard_deviation_constraint,
    sum_constraint,
    unique_value_ratio_constraint,
    uniqueness_constraint,
)
from deequ_tpu.metrics import Metric


class CheckLevel(enum.Enum):
    ERROR = "Error"
    WARNING = "Warning"


class CheckStatus(enum.Enum):
    SUCCESS = "Success"
    WARNING = "Warning"
    ERROR = "Error"

    @property
    def severity(self) -> int:
        return {"Success": 0, "Warning": 1, "Error": 2}[self.value]


@dataclass
class CheckResult:
    check: "Check"
    status: CheckStatus
    constraint_results: List[ConstraintResult] = field(default_factory=list)


IsOne: Callable[[float], bool] = lambda v: v == 1.0  # noqa: E731


def _columns_tuple(columns) -> Tuple[str, ...]:
    return (columns,) if isinstance(columns, str) else tuple(columns)


class Check:
    """A named group of constraints with an assertion level
    (reference checks/Check.scala:60-63)."""

    def __init__(
        self,
        level: CheckLevel,
        description: str,
        constraints: Tuple[Constraint, ...] = (),
    ):
        self.level = level
        self.description = description
        self.constraints: Tuple[Constraint, ...] = tuple(constraints)

    # -- plumbing -----------------------------------------------------------

    def add_constraint(self, constraint: Constraint) -> "Check":
        return Check(self.level, self.description, self.constraints + (constraint,))

    def _add_filterable(
        self, creation_fn: Callable[[Optional[str]], Constraint]
    ) -> "CheckWithLastConstraintFilterable":
        return CheckWithLastConstraintFilterable(
            self.level,
            self.description,
            self.constraints + (creation_fn(None),),
            creation_fn,
        )

    # -- completeness / size ------------------------------------------------

    def has_size(self, assertion, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: size_constraint(assertion, where, hint)
        )

    def is_complete(self, column: str, hint=None) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: completeness_constraint(column, IsOne, where, hint)
        )

    def has_completeness(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: completeness_constraint(column, assertion, where, hint)
        )

    # -- uniqueness ---------------------------------------------------------

    def is_unique(self, column: str, hint=None) -> "Check":
        return self.add_constraint(
            uniqueness_constraint(_columns_tuple(column), IsOne, hint)
        )

    def is_primary_key(self, column: str, *more_columns: str, hint=None) -> "Check":
        return self.add_constraint(
            uniqueness_constraint((column,) + tuple(more_columns), IsOne, hint)
        )

    def has_uniqueness(self, columns, assertion, hint=None) -> "Check":
        return self.add_constraint(
            uniqueness_constraint(_columns_tuple(columns), assertion, hint)
        )

    def has_distinctness(self, columns, assertion, hint=None) -> "Check":
        return self.add_constraint(
            distinctness_constraint(_columns_tuple(columns), assertion, hint)
        )

    def has_unique_value_ratio(self, columns, assertion, hint=None) -> "Check":
        return self.add_constraint(
            unique_value_ratio_constraint(_columns_tuple(columns), assertion, hint)
        )

    # -- histogram-based ----------------------------------------------------

    def has_number_of_distinct_values(
        self, column: str, assertion, binning_udf=None, max_bins: int = 1000, hint=None
    ) -> "Check":
        return self.add_constraint(
            histogram_bin_constraint(column, assertion, binning_udf, max_bins, hint)
        )

    def has_histogram_values(
        self, column: str, assertion, binning_udf=None, max_bins: int = 1000, hint=None
    ) -> "Check":
        return self.add_constraint(
            histogram_constraint(column, assertion, binning_udf, max_bins, hint)
        )

    def kll_sketch_satisfies(
        self, column: str, assertion, kll_parameters=None, hint=None
    ) -> "Check":
        return self.add_constraint(
            kll_constraint(column, assertion, kll_parameters, hint)
        )

    # -- information theory -------------------------------------------------

    def has_entropy(self, column: str, assertion, hint=None) -> "Check":
        return self.add_constraint(entropy_constraint(column, assertion, hint))

    def has_mutual_information(
        self, column_a: str, column_b: str, assertion, hint=None
    ) -> "Check":
        return self.add_constraint(
            mutual_information_constraint(column_a, column_b, assertion, hint)
        )

    # -- quantiles ----------------------------------------------------------

    def has_approx_quantile(
        self, column: str, quantile: float, assertion, relative_error: float = 0.01,
        hint=None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: approx_quantile_constraint(
                column, quantile, assertion, relative_error, where, hint
            )
        )

    # -- value ranges -------------------------------------------------------

    def has_min_length(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: min_length_constraint(column, assertion, where, hint)
        )

    def has_max_length(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: max_length_constraint(column, assertion, where, hint)
        )

    def has_min(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: min_constraint(column, assertion, where, hint)
        )

    def has_max(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: max_constraint(column, assertion, where, hint)
        )

    def has_mean(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: mean_constraint(column, assertion, where, hint)
        )

    def has_sum(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: sum_constraint(column, assertion, where, hint)
        )

    def has_standard_deviation(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: standard_deviation_constraint(column, assertion, where, hint)
        )

    def has_approx_count_distinct(
        self, column: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: approx_count_distinct_constraint(column, assertion, where, hint)
        )

    def has_correlation(
        self, column_a: str, column_b: str, assertion, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: correlation_constraint(column_a, column_b, assertion, where, hint)
        )

    # -- predicates / patterns ----------------------------------------------

    def satisfies(
        self, column_condition: str, constraint_name: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: compliance_constraint(
                constraint_name, column_condition, assertion, where, hint
            )
        )

    def has_pattern(
        self, column: str, pattern: str, assertion=IsOne, name=None, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: pattern_match_constraint(
                column, pattern, assertion, where, name, hint
            )
        )

    def contains_credit_card_number(
        self, column: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        from deequ_tpu.analyzers import Patterns

        return self.has_pattern(
            column, Patterns.CREDITCARD, assertion,
            name=f"containsCreditCardNumber({column})", hint=hint,
        )

    def contains_email(
        self, column: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        from deequ_tpu.analyzers import Patterns

        return self.has_pattern(
            column, Patterns.EMAIL, assertion,
            name=f"containsEmail({column})", hint=hint,
        )

    def contains_url(
        self, column: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        from deequ_tpu.analyzers import Patterns

        return self.has_pattern(
            column, Patterns.URL, assertion,
            name=f"containsURL({column})", hint=hint,
        )

    def contains_social_security_number(
        self, column: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        from deequ_tpu.analyzers import Patterns

        return self.has_pattern(
            column, Patterns.SOCIAL_SECURITY_NUMBER_US, assertion,
            name=f"containsSocialSecurityNumber({column})", hint=hint,
        )

    def has_data_type(
        self,
        column: str,
        data_type: ConstrainableDataTypes,
        assertion=IsOne,
        hint=None,
    ) -> "CheckWithLastConstraintFilterable":
        return self._add_filterable(
            lambda where: data_type_constraint(column, data_type, assertion, where, hint)
        )

    # -- numeric sign / comparisons -----------------------------------------

    def is_non_negative(
        self, column: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        # coalesce so NULLs don't count as non-compliant (reference L727-735)
        return self.satisfies(
            f"COALESCE(`{column}`, 0.0) >= 0", f"{column} is non-negative",
            assertion, hint=hint,
        )

    def is_positive(
        self, column: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"COALESCE(`{column}`, 1.0) > 0", f"{column} is positive",
            assertion, hint=hint,
        )

    def is_less_than(
        self, column_a: str, column_b: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"`{column_a}` < `{column_b}`", f"{column_a} is smaller than {column_b}",
            assertion, hint=hint,
        )

    def is_less_than_or_equal_to(
        self, column_a: str, column_b: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"`{column_a}` <= `{column_b}`",
            f"{column_a} is smaller than or equal to {column_b}",
            assertion, hint=hint,
        )

    def is_greater_than(
        self, column_a: str, column_b: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"`{column_a}` > `{column_b}`", f"{column_a} is greater than {column_b}",
            assertion, hint=hint,
        )

    def is_greater_than_or_equal_to(
        self, column_a: str, column_b: str, assertion=IsOne, hint=None
    ) -> "CheckWithLastConstraintFilterable":
        return self.satisfies(
            f"`{column_a}` >= `{column_b}`",
            f"{column_a} is greater than or equal to {column_b}",
            assertion, hint=hint,
        )

    def is_contained_in(
        self,
        column: str,
        allowed_values=None,
        assertion=IsOne,
        hint=None,
        lower_bound: Optional[float] = None,
        upper_bound: Optional[float] = None,
        include_lower_bound: bool = True,
        include_upper_bound: bool = True,
    ) -> "CheckWithLastConstraintFilterable":
        """Value-set or numeric-interval containment
        (reference checks/Check.scala:844-943)."""
        if allowed_values is not None:
            value_list = ",".join(
                "'" + str(v).replace("\\", "\\\\").replace("'", "\\'") + "'"
                for v in allowed_values
            )
            predicate = f"`{column}` IS NULL OR `{column}` IN ({value_list})"
            return self.satisfies(
                predicate,
                f"{column} contained in {','.join(str(v) for v in allowed_values)}",
                assertion, hint=hint,
            )
        if lower_bound is None or upper_bound is None:
            raise ValueError(
                "is_contained_in needs allowed_values or lower_bound+upper_bound"
            )
        left = ">=" if include_lower_bound else ">"
        right = "<=" if include_upper_bound else "<"
        predicate = (
            f"`{column}` IS NULL OR "
            f"(`{column}` {left} {lower_bound} AND `{column}` {right} {upper_bound})"
        )
        return self.satisfies(
            predicate, f"{column} between {lower_bound} and {upper_bound}",
            assertion, hint=hint,
        )

    # -- anomaly detection ---------------------------------------------------

    def is_newest_point_non_anomalous(
        self,
        metrics_repository,
        anomaly_detection_strategy,
        analyzer: Analyzer,
        with_tag_values: Optional[dict] = None,
        after_date: Optional[int] = None,
        before_date: Optional[int] = None,
    ) -> "Check":
        """Anomaly constraint over the repository history of this analyzer's
        metric (reference checks/Check.scala:998-1055)."""
        assertion = _is_newest_point_non_anomalous_assertion(
            metrics_repository,
            anomaly_detection_strategy,
            analyzer,
            with_tag_values or {},
            after_date,
            before_date,
        )
        return self.add_constraint(anomaly_constraint(analyzer, assertion))

    # -- evaluation ----------------------------------------------------------

    def evaluate(self, context) -> CheckResult:
        """Evaluate all constraints against computed metrics
        (reference checks/Check.scala:950-962)."""
        metric_map: Dict[Analyzer, Metric] = context.metric_map
        results = [c.evaluate(metric_map) for c in self.constraints]
        any_failure = any(r.status == ConstraintStatus.FAILURE for r in results)
        if not any_failure:
            status = CheckStatus.SUCCESS
        elif self.level == CheckLevel.ERROR:
            status = CheckStatus.ERROR
        else:
            status = CheckStatus.WARNING
        return CheckResult(self, status, results)

    def required_analyzers(self) -> List[Analyzer]:
        """(reference checks/Check.scala:964-973)"""
        out = []
        for c in self.constraints:
            inner = c.inner if isinstance(c, ConstraintDecorator) else c
            if isinstance(inner, AnalysisBasedConstraint):
                out.append(inner.analyzer)
        return out

    def __repr__(self) -> str:
        return (
            f"Check({self.level.value}, {self.description!r}, "
            f"{len(self.constraints)} constraints)"
        )


class CheckWithLastConstraintFilterable(Check):
    """Allows replacing the last constraint with a filtered variant
    (reference checks/CheckWithLastConstraintFilterable.scala:22-53)."""

    def __init__(self, level, description, constraints, creation_fn):
        super().__init__(level, description, constraints)
        self._creation_fn = creation_fn

    def where(self, filter_expr: str) -> Check:
        return Check(
            self.level,
            self.description,
            self.constraints[:-1] + (self._creation_fn(filter_expr),),
        )


def _is_newest_point_non_anomalous_assertion(
    metrics_repository,
    anomaly_detection_strategy,
    analyzer,
    with_tag_values: dict,
    after_date: Optional[int],
    before_date: Optional[int],
) -> Callable[[float], bool]:
    """Build the assertion closure querying repository history
    (reference checks/Check.scala:998-1055)."""

    def assertion(current_metric_value: float) -> bool:
        from deequ_tpu.anomaly import AnomalyDetector
        from deequ_tpu.anomaly.history import DataPoint, history_from_loader

        loader = metrics_repository.load()
        if with_tag_values:
            loader = loader.with_tag_values(with_tag_values)
        if after_date is not None:
            loader = loader.after(after_date)
        if before_date is not None:
            loader = loader.before(before_date)
        # the ONE backend-agnostic history pull (anomaly/history.py):
        # strictly through the loader DSL, so any MetricsRepository —
        # in-memory, filesystem, columnar — yields the same DataPoints
        data_points = history_from_loader(loader, analyzer)

        detector = AnomalyDetector(anomaly_detection_strategy)
        test_time = (
            max((p.time for p in data_points), default=0) + 1
        )
        result = detector.is_new_point_anomalous(
            data_points, DataPoint(test_time, float(current_metric_value))
        )
        return len(result.anomalies) == 0

    return assertion
