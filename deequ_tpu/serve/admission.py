"""Per-tenant SLO admission control, deadline-aware fair queuing, and
the brownout ladder — the serving layer's OVERLOAD tier (round 15).

PR 12's fleet survives worker death, poison tenants, and device faults —
but not its most common production failure mode: SUSTAINED OVERLOAD. The
pre-round-15 service had exactly one overload behavior, a binary
``ServiceOverloadedException`` at a fixed queue bound, which meant a
flood tenant could starve everyone (FIFO queue), queued requests were
dispatched long after their caller gave up (no deadlines), and "this
tenant's check must resolve in 200 ms, that one is best-effort" was
inexpressible. TiLT (arXiv:2301.12030) frames why this matters: when
verification becomes a standing service over streams, deadline-aware
scheduling is what keeps it a MONITOR rather than a lagging batch job.

Three mechanisms, composed at the service's submit/queue seam:

- :class:`Slo` + :class:`AdmissionController` — every submission carries
  an SLO (``deadline_ms``, ``weight``, ``cls`` in ``critical`` |
  ``standard`` | ``best_effort``; envcfg-registered defaults). Admission
  runs at ``submit()``: each class owns a bounded share of the pending
  queue (:data:`CLASS_QUEUE_SHARE` — ``critical`` may use all of it,
  lower classes progressively less, so a best_effort flood can never
  fill the headroom critical requests admit into), and refusals are
  TYPED with a drain-rate-derived ``retry_after_s``
  (:class:`~deequ_tpu.exceptions.AdmissionRejectedException`, a
  :class:`~deequ_tpu.exceptions.ServiceOverloadedException`) —
  backpressure with a schedule, not an error.

- :class:`TenantFairQueue` — the pending queue becomes class-tiered
  weighted deficit round-robin across PER-TENANT queues: classes pop in
  strict priority order (a ``critical`` request never waits behind a
  lower class — the structural no-priority-inversion guarantee chaos
  oracle 10 checks), and within a class each rotation visit grants a
  tenant ``weight`` credits and one credit buys one pop, so a flooding
  tenant gets its fair share of coalesced batches and no more. Requests
  whose ABSOLUTE deadline expired in-queue are shed at pop time, before
  any dispatch: a typed
  :class:`~deequ_tpu.exceptions.DeadlineExceededException` resolved
  exactly once on the original future (a shed IS a resolution — chaos
  oracle 9 counts it), with the shed charged to the tenant's run budget
  (kind ``deadline_shed``, exhaustion swallowed — the shed is already
  the terminal outcome). The same rule extends to fleet failover: an
  expired victim request is shed, not replayed stale.

- :class:`BrownoutController` — a 3-level ladder driven by the
  queue-depth / latency feeds the PR-11 registry publishes
  (``serve_queue_depth``, the serve latency histograms): level 1 sheds
  ``best_effort`` ADMISSIONS, level 2 additionally caps per-tenant
  inflight, level 3 admits ``critical`` only. Transitions are
  hysteretic (separate up/down thresholds, one step down per update) so
  the ladder doesn't flap at a boundary. The invariant the whole tier
  keeps: COMPUTATION IS NEVER DEGRADED — brownout changes which
  requests run, never how, so every completed result stays bit-identical
  to an unloaded serial run (``measure_overload_shedding`` gates on it).

Observables: per-class ``serve_admitted_* / serve_admission_rejected_* /
serve_shed_*`` counters and the ``serve_brownout_level`` gauge
(deequ_tpu/obs/registry.py), ``brownout`` / ``deadline_shed``
degradation events on ScanStats (and thus the flight recorder).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from deequ_tpu.exceptions import (
    AdmissionRejectedException,
    ServiceOverloadedException,
)

#: the SLO classes, in strict pop-priority order (index = priority)
SLO_CLASSES = ("critical", "standard", "best_effort")

#: fraction of ``max_pending`` each class may occupy while queued:
#: critical may use the whole queue, lower classes progressively less —
#: the reserved headroom is what keeps critical admissible (and its p99
#: inside its SLO) under a lower-class flood
CLASS_QUEUE_SHARE = {
    "critical": 1.0,
    "standard": 0.75,
    "best_effort": 0.5,
}


@dataclass(frozen=True)
class Slo:
    """One submission's service-level objective.

    ``deadline_ms`` is the ABSOLUTE submit->dispatch budget: a request
    still queued when it expires is shed typed pre-dispatch (None = no
    deadline, best-effort latency). ``weight`` is the tenant's
    fair-share weight inside its class (2.0 = twice the batch slots of
    a weight-1 tenant under contention). ``cls`` picks the admission /
    scheduling tier."""

    deadline_ms: Optional[float] = None
    weight: float = 1.0
    cls: str = "standard"

    def __post_init__(self):
        if self.cls not in SLO_CLASSES:
            raise ValueError(
                f"Slo.cls must be one of {list(SLO_CLASSES)}, "
                f"got {self.cls!r}"
            )
        if self.deadline_ms is not None and not self.deadline_ms > 0:
            raise ValueError(
                f"Slo.deadline_ms must be > 0 ms or None, "
                f"got {self.deadline_ms!r}"
            )
        if not self.weight > 0:
            raise ValueError(f"Slo.weight must be > 0, got {self.weight!r}")

    @property
    def deadline_seconds(self) -> Optional[float]:
        if self.deadline_ms is None:
            return None
        return self.deadline_ms / 1000.0

    @staticmethod
    def default() -> "Slo":
        """The envcfg-registered default for submissions carrying no
        SLO: ``DEEQU_TPU_SLO_CLASS`` (default ``standard``) +
        ``DEEQU_TPU_SLO_DEADLINE_MS`` (default none)."""
        from deequ_tpu.envcfg import env_value

        return Slo(
            deadline_ms=env_value("DEEQU_TPU_SLO_DEADLINE_MS"),
            cls=env_value("DEEQU_TPU_SLO_CLASS"),
        )


def resolve_slo(slo: Optional[Slo]) -> Slo:
    """Argument > envcfg default — the resolution every submit applies."""
    if slo is None:
        return Slo.default()
    if not isinstance(slo, Slo):
        raise TypeError(f"slo must be an Slo, got {type(slo).__name__}")
    return slo


class BrownoutController:
    """The 3-level overload ladder (module doc). ``update(depth)``
    recomputes the level from the queue-depth fraction (the same number
    the registry's ``serve_queue_depth`` gauge publishes) plus the
    recent-latency feed (``observe_latency`` — the same values the
    registry's serve latency histograms observe): ascent jumps straight
    to the highest threshold crossed; descent is hysteretic, one level
    per update, only once depth falls below that level's DOWN
    threshold. Level changes set the ``serve_brownout_level`` gauge and
    record a ``brownout`` degradation event (which the armed flight
    recorder picks up like every other rung)."""

    #: queue-depth fractions (of capacity) that RAISE to level 1/2/3
    UP = (0.5, 0.75, 0.9)
    #: fractions to DROP back below level 1/2/3 (hysteresis)
    DOWN = (0.25, 0.5, 0.7)

    def __init__(
        self,
        capacity: int,
        up: Tuple[float, ...] = UP,
        down: Tuple[float, ...] = DOWN,
        latency_high: Optional[float] = None,
        latency_window: int = 64,
        latency_horizon: float = 30.0,
        enabled: bool = True,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if len(up) != 3 or len(down) != 3:
            raise ValueError("up/down need one threshold per level (3)")
        if any(d >= u for d, u in zip(down, up)):
            raise ValueError(
                "each DOWN threshold must sit below its UP threshold "
                "(hysteresis)"
            )
        if list(up) != sorted(up) or list(down) != sorted(down):
            raise ValueError("brownout thresholds must ascend with level")
        self.capacity = int(capacity)
        self.up = tuple(up)
        self.down = tuple(down)
        #: recent submit->resolve latency (s) above which the ladder
        #: holds at least level 1 even with a shallow queue (a slow
        #: backend is overload too); None disables the latency signal
        self.latency_high = latency_high
        #: samples older than this (s) age out of the p95 window: the
        #: signal is fed by COMPLETIONS, and at level 1 a best_effort
        #: service may complete nothing — without expiry one slow patch
        #: would latch the ladder hot forever on an idle service
        self.latency_horizon = float(latency_horizon)
        self.enabled = bool(enabled)
        self.level = 0
        self.transitions = 0
        self._lat: deque = deque(maxlen=int(latency_window))

    def observe_latency(self, seconds: float) -> None:
        self._lat.append((time.monotonic(), float(seconds)))

    def recent_latency_p95(self) -> Optional[float]:
        horizon = time.monotonic() - self.latency_horizon
        while self._lat and self._lat[0][0] < horizon:
            self._lat.popleft()
        if not self._lat:
            return None
        ordered = sorted(v for _, v in self._lat)
        return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]

    def update(self, depth: int, cost_frac: Optional[float] = None) -> int:
        """Recompute + return the level for the current queue depth.
        ``cost_frac`` (round 19) is queued PREDICTED COST over cost
        capacity (ops/plan_cost.py via the admission controller): the
        ladder reads the max of the two pressures, so a queue of few but
        heavy suites browns out as early as a deep queue of light ones —
        thresholds derived from predicted plan cost, not depth alone."""
        if not self.enabled:
            return 0
        frac = depth / self.capacity
        if cost_frac is not None:
            frac = max(frac, float(cost_frac))
        target = 0
        for i, threshold in enumerate(self.up):
            if frac >= threshold:
                target = i + 1
        latency_hot = False
        if self.latency_high is not None:
            p95 = self.recent_latency_p95()
            latency_hot = p95 is not None and p95 >= self.latency_high
            if latency_hot:
                target = max(target, 1)
        prev = self.level
        if target > prev:
            new = target
        elif (
            prev > 0
            and frac < self.down[prev - 1]
            and not (latency_hot and prev == 1)
        ):
            new = prev - 1  # hysteretic: one step down per update
        else:
            new = prev
        if new != prev:
            self.level = new
            self.transitions += 1
            from deequ_tpu.obs.registry import SERVE_BROWNOUT_LEVEL
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            SERVE_BROWNOUT_LEVEL.set(new)
            SCAN_STATS.record_degradation(
                "brownout", level=new, prev=prev,
                queue_frac=round(frac, 3),
            )
        return self.level


class AdmissionController:
    """The submit()-time gate (module doc): class queue budgets, the
    brownout ladder's admission policy, and the per-tenant inflight cap.
    All refusals are typed ``ServiceOverloadedException`` family with
    ``retry_after_s`` derived from the observed drain rate."""

    def __init__(
        self,
        max_pending: int,
        brownout: Optional[BrownoutController] = None,
        class_share: Optional[Dict[str, float]] = None,
        inflight_cap: Optional[int] = None,
    ):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        self.max_pending = int(max_pending)
        self.brownout = brownout
        share = dict(CLASS_QUEUE_SHARE)
        share.update(class_share or {})
        unknown = set(share) - set(SLO_CLASSES)
        if unknown:
            raise ValueError(f"unknown SLO classes in class_share: {unknown}")
        if any(not 0 < s <= 1.0 for s in share.values()):
            raise ValueError("class_share fractions must be in (0, 1]")
        self.class_share = share
        #: per-tenant queued-request cap applied at brownout level >= 2
        #: ("inflight" at the admission seam = admitted but not yet
        #: dispatched); default: an equal slice of the queue for 16
        #: tenants, never below 1
        self.inflight_cap = (
            int(inflight_cap) if inflight_cap is not None
            else max(1, self.max_pending // 16)
        )
        if self.inflight_cap < 1:
            raise ValueError("inflight_cap must be >= 1")
        # drain-rate EWMA behind retry_after (suites/s; None until the
        # first served batch reports in)
        self._rate: Optional[float] = None
        # cost-drain EWMAs (round 19, ops/plan_cost.py): cost units/s
        # served, and cost units per suite — retry_after prices the
        # QUEUED COST when the service feeds it, and the brownout
        # ladder's cost pressure normalizes against avg-cost * capacity
        self._cost_rate: Optional[float] = None
        self._avg_cost: Optional[float] = None

    def note_served(
        self, n: int, wall_seconds: float, cost: Optional[float] = None,
    ) -> None:
        """Feed the drain-rate estimate (called per served batch).
        ``cost`` is the batch's summed PREDICTED cost (plan-cost units);
        with it the controller also learns cost/s and cost/suite."""
        if n <= 0 or wall_seconds <= 0:
            return
        rate = n / wall_seconds
        self._rate = (
            rate if self._rate is None else 0.8 * self._rate + 0.2 * rate
        )
        if cost is not None and cost > 0:
            crate = cost / wall_seconds
            self._cost_rate = (
                crate if self._cost_rate is None
                else 0.8 * self._cost_rate + 0.2 * crate
            )
            per_suite = cost / n
            self._avg_cost = (
                per_suite if self._avg_cost is None
                else 0.8 * self._avg_cost + 0.2 * per_suite
            )

    def cost_fraction(self, queued_cost: Optional[float]) -> Optional[float]:
        """Queued predicted cost over cost CAPACITY (avg suite cost x
        max_pending) — the brownout ladder's second pressure feed. None
        until both a queued-cost ledger and a served-cost average
        exist."""
        if (
            queued_cost is None
            or self._avg_cost is None
            or self._avg_cost <= 0
        ):
            return None
        return float(queued_cost) / (self._avg_cost * self.max_pending)

    def retry_after(
        self, queue_depth: int, queued_cost: Optional[float] = None,
    ) -> float:
        """When a refused caller could plausibly be admitted. With a
        queued-cost ledger and an observed cost-drain rate (round 19),
        the schedule is time-to-drain the queued PREDICTED COST — a
        queue of heavy profiling suites schedules a later retry than the
        same depth of trivial checks; otherwise the legacy depth/rate
        estimate (bounded), or a small constant before any rate is
        known."""
        if (
            queued_cost is not None
            and self._cost_rate is not None
            and self._cost_rate > 0
        ):
            return min(
                30.0, max(0.005, float(queued_cost) / self._cost_rate)
            )
        if self._rate is None or self._rate <= 0:
            return 0.05
        return min(30.0, max(0.005, (queue_depth + 1) / self._rate))

    def admit(
        self,
        tenant,
        slo: Slo,
        queue_depth: int,
        class_depth: int,
        tenant_pending: int,
        queued_cost: Optional[float] = None,
    ) -> int:
        """Admit or raise typed. Returns the brownout level applied.
        ``class_depth`` is the queued count of ``slo.cls``;
        ``tenant_pending`` the tenant's queued count (the level-2 cap's
        subject); ``queued_cost`` the queue's summed PREDICTED plan cost
        (round 19 — drives cost-aware ``retry_after_s`` and the
        brownout ladder's cost pressure). The caller (the service, under
        its queue lock) supplies the depths so decision and enqueue are
        atomic."""
        from deequ_tpu.obs.registry import (
            SERVE_ADMISSION_REJECTED_BY_CLASS,
            SERVE_ADMITTED_BY_CLASS,
        )

        level = (
            self.brownout.update(
                queue_depth, cost_frac=self.cost_fraction(queued_cost)
            )
            if self.brownout is not None else 0
        )
        retry = self.retry_after(queue_depth, queued_cost=queued_cost)

        def refuse(exc):
            SERVE_ADMISSION_REJECTED_BY_CLASS[slo.cls].inc()
            raise exc

        if queue_depth >= self.max_pending:
            refuse(ServiceOverloadedException(
                f"{queue_depth} requests pending >= "
                f"max_pending={self.max_pending}",
                queue_depth=queue_depth, retry_after_s=retry,
                slo_class=slo.cls,
            ))
        if level >= 3 and slo.cls != "critical":
            refuse(AdmissionRejectedException(
                f"brownout level 3: admitting critical only, "
                f"shedding {slo.cls!r} (tenant {tenant!r})",
                reason="brownout_critical_only", queue_depth=queue_depth,
                retry_after_s=retry, slo_class=slo.cls,
            ))
        if level >= 1 and slo.cls == "best_effort":
            refuse(AdmissionRejectedException(
                f"brownout level {level}: shedding best_effort "
                f"admissions (tenant {tenant!r})",
                reason="brownout_best_effort", queue_depth=queue_depth,
                retry_after_s=retry, slo_class=slo.cls,
            ))
        if level >= 2 and tenant_pending >= self.inflight_cap:
            refuse(AdmissionRejectedException(
                f"brownout level {level}: tenant {tenant!r} at the "
                f"per-tenant inflight cap ({tenant_pending} >= "
                f"{self.inflight_cap})",
                reason="tenant_inflight_cap", queue_depth=queue_depth,
                retry_after_s=retry, slo_class=slo.cls,
            ))
        budget = self.class_share[slo.cls] * self.max_pending
        if class_depth >= budget:
            refuse(AdmissionRejectedException(
                f"SLO class {slo.cls!r} queue budget exhausted "
                f"({class_depth} >= {budget:g} of "
                f"max_pending={self.max_pending})",
                reason="class_budget", queue_depth=queue_depth,
                retry_after_s=retry, slo_class=slo.cls,
            ))
        SERVE_ADMITTED_BY_CLASS[slo.cls].inc()
        return level


class TenantFairQueue:
    """Class-tiered weighted deficit round-robin over per-tenant queues,
    with pop-time deadline shedding (module doc).

    NOT internally locked: the owning service serializes every call
    under its own condition lock (decision + mutation must be atomic
    with the rest of the service state anyway). ``pop`` hands expired
    requests to ``shed`` instead of returning them — the callback must
    only COLLECT (the service resolves the futures after releasing its
    lock, so a resolution callback can never deadlock against it)."""

    def __init__(self):
        # cls -> OrderedDict[tenant_key, deque[request]]; OrderedDict
        # order IS the round-robin rotation (move_to_end on each visit)
        self._tiers: Dict[str, "OrderedDict[str, deque]"] = {
            cls: OrderedDict() for cls in SLO_CLASSES
        }
        self._credit: Dict[Tuple[str, str], float] = {}
        self._len = 0
        # incremental depth ledgers: every submit's admission decision
        # reads class_depth + tenant_depth under the service lock, and
        # summing deques per call would make each submit O(tenants)
        self._class_len: Dict[str, int] = {cls: 0 for cls in SLO_CLASSES}
        self._tenant_len: Dict[str, int] = {}

    @staticmethod
    def _cls_of(req) -> str:
        slo = getattr(req, "slo", None)
        return slo.cls if slo is not None else "standard"

    @staticmethod
    def _tenant_key(req) -> str:
        return str(req.tenant)

    def push(self, req) -> None:
        cls = self._cls_of(req)
        tier = self._tiers[cls]
        key = self._tenant_key(req)
        bucket = tier.get(key)
        if bucket is None:
            bucket = deque()
            tier[key] = bucket
        bucket.append(req)
        self._len += 1
        self._class_len[cls] += 1
        self._tenant_len[key] = self._tenant_len.get(key, 0) + 1

    def _removed(self, cls: str, key: str) -> None:
        """Depth-ledger decrement for one request leaving the queue."""
        self._len -= 1
        self._class_len[cls] -= 1
        left = self._tenant_len.get(key, 0) - 1
        if left <= 0:
            self._tenant_len.pop(key, None)
        else:
            self._tenant_len[key] = left

    def __len__(self) -> int:
        return self._len

    def class_depth(self, cls: str) -> int:
        return self._class_len[cls]

    def tenant_depth(self, tenant) -> int:
        return self._tenant_len.get(str(tenant), 0)

    def depths(self) -> Dict[str, Dict[str, int]]:
        """{cls: {tenant: queued}} — the introspection feed."""
        return {
            cls: {t: len(dq) for t, dq in tier.items() if dq}
            for cls, tier in self._tiers.items()
        }

    def pop(self, now: float, shed: Callable[[object], None]):
        """The next request to dispatch, or None when (after shedding)
        nothing remains. Strict class priority; WDRR across tenants
        within a class; expired heads are handed to ``shed`` and never
        returned."""
        for cls in SLO_CLASSES:
            req = self._pop_tier(cls, now, shed)
            if req is not None:
                return req
        return None

    def _pop_tier(self, cls: str, now: float, shed):
        tier = self._tiers[cls]
        # spin guard: every full rotation grants every tenant its
        # weight, so some credit crosses 1.0 within ceil(1/min_weight)
        # rotations; the cap only backstops a pathological weight
        spins = 0
        while tier:
            tenant, bucket = next(iter(tier.items()))
            while bucket:
                head = bucket[0]
                deadline_at = getattr(head, "deadline_at", None)
                if deadline_at is None or now < deadline_at:
                    break
                bucket.popleft()
                self._removed(cls, tenant)
                shed(head)  # collect-only; resolved by the caller later
            if not bucket:
                del tier[tenant]
                self._credit.pop((cls, tenant), None)
                continue
            credit = self._credit.get((cls, tenant), 0.0)
            if credit < 1.0 and spins <= 4 * len(tier) + 100:
                slo = getattr(bucket[0], "slo", None)
                weight = slo.weight if slo is not None else 1.0
                self._credit[(cls, tenant)] = credit + weight
                tier.move_to_end(tenant)
                spins += 1
                continue
            remaining = max(credit - 1.0, 0.0)
            self._credit[(cls, tenant)] = remaining
            req = bucket.popleft()
            self._removed(cls, tenant)
            if not bucket:
                del tier[tenant]
                self._credit.pop((cls, tenant), None)
            elif remaining < 1.0:
                # spent: rotate away. A tenant still holding a whole
                # credit stays at the head and drains it on the next
                # pop — DRR serves each visit's full quantum as a
                # burst, or a weight-2 tenant would dilute to ~4:3
                # instead of 2:1 (every interleaved visit hands the
                # competition a fresh grant)
                tier.move_to_end(tenant)
            return req
        return None

    def drain(self) -> List:
        """Remove and return every queued request (class-priority then
        rotation order) — the ``stop(drain=False)`` carrier."""
        out: List = []
        for cls in SLO_CLASSES:
            tier = self._tiers[cls]
            for bucket in tier.values():
                out.extend(bucket)
            tier.clear()
        self._credit.clear()
        self._len = 0
        self._class_len = {cls: 0 for cls in SLO_CLASSES}
        self._tenant_len.clear()
        return out


# re-exported for callers that only need the typed refusal surface
__all__ = [
    "AdmissionController",
    "AdmissionRejectedException",
    "BrownoutController",
    "CLASS_QUEUE_SHARE",
    "resolve_slo",
    "Slo",
    "SLO_CLASSES",
    "TenantFairQueue",
]
