"""The coalesced packed executor — N tenant suites, ONE dispatch, ONE fetch.

``run_coalesced`` takes K admitted members of one ServePlan (same schema
signature, analyzer set, packer layout, and row count), packs each into
the plan's single-chunk layout, stacks the buffers along a leading
TENANT axis padded to a pow2 bucket, and runs one vmapped fused program
— the ``run_scan_group`` construction (tests pin it bit-identical to
per-tenant serial scans) extended with:

- tenant-axis PADDING: dummy all-invalid slices (row_valid False, masks
  False, codes/enc -1) fill the bucket so the program-per-batch-size
  count stays O(log max_batch) instead of O(max_batch). vmap maps each
  slice independently — a padding slice can influence no real member's
  result by construction, which is what makes padding provably inert
  (the real rows are never padded: members coalesce only on EXACT row
  count, because chunk padding shifts the f32-pair reduction
  association at the ulp level — measured, and exactly what
  ``group_scannable`` forbids);
- per-tenant dictionary LUT stacking for string AND encoded columns
  (each member's LUT pads to the group max pow2; gathers never touch
  padding — codes index below each member's own cardinality);
- the packed PLAN-LINT pass: the shared program lints under its own
  memo key (tenant-axis bucket + member contract fingerprints on top of
  the program identity) with per-member slice checks
  (lint/plan_lint.py:_check_packed_members);
- fault-ladder seams: the dispatch runs under ``device_call`` at the
  execute boundary (watchdog + chaos-hook injection), the single fetch
  at the fetch boundary; a classified device fault raises out to the
  service, which BISECTS the tenant axis (isolation in O(log K)).

The one-fetch contract here is per coalesced BATCH: exactly one
device->host materialization of the (K, S) state matrix regardless of K.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deequ_tpu.ops.scan_engine import (
    SCAN_STATS,
    _ChunkPacker,
    _split_lut_key,
)
from deequ_tpu.ops.device_policy import device_call


def _member_packer(plan, table) -> _ChunkPacker:
    """A packer for one member's columns pinned to the PLAN layout (the
    admission key guarantees the member classifies onto it)."""
    cols = {n: table[n] for n in plan.needed}
    return _ChunkPacker(cols, plan.key.chunk, layout=plan.layout)


def _pad_slice(shapes: Sequence[Tuple], chunk: int):
    """One all-invalid padding slice: value planes zero, masks False,
    string/enc codes -1 (null), row_valid all False — the neutral fill
    ``_ChunkPacker.pack`` uses for the tail of a short chunk, applied to
    every row."""
    values, hi, lo, narrow_i, masks, codes, row_valid, enc = shapes
    return (
        np.zeros(values, dtype=np.float64),
        np.zeros(hi, dtype=np.float32),
        np.zeros(lo, dtype=np.float32),
        np.zeros(narrow_i, dtype=np.int32),
        np.zeros(masks, dtype=np.bool_),
        np.full(codes, -1, dtype=np.int32),
        np.zeros((chunk,), dtype=np.bool_),
        np.full(enc, -1, dtype=np.int16),
    )


def _stack_member_buffers(
    plan, tables: Sequence, k_bucket: int, packers: Sequence = (),
):
    """Pack every member with the shared layout and stack to (K, ...)
    buffers, padding the tenant axis to ``k_bucket``. ``packers`` may
    carry each member's admission-time packer (its layout signature
    already matched the plan key) to skip a second classification."""
    chunk = plan.key.chunk
    stacked: Optional[List[List[np.ndarray]]] = None
    for j, t in enumerate(tables):
        packer = packers[j] if j < len(packers) and packers[j] is not None \
            else _member_packer(plan, t)
        args = packer.pack(0, int(t.num_rows))
        SCAN_STATS.bytes_packed += sum(a.nbytes for a in args)
        if stacked is None:
            stacked = [[a] for a in args]
        else:
            for lst, a in zip(stacked, args):
                lst.append(a)
    assert stacked is not None
    n_pad = k_bucket - len(tables)
    if n_pad > 0:
        pad = _pad_slice([lst[0].shape for lst in stacked], chunk)
        for lst, p in zip(stacked, pad):
            lst.extend([p] * n_pad)
    return tuple(np.stack(lst) for lst in stacked)


def _enc_lut_specs(plan) -> List[Tuple[str, str, Any]]:
    """(column, kind, builder) rows for the plan's ENCODED columns —
    mirrors ``scan_engine._collect_enc_luts`` but emits specs the
    per-member stacking loop below consumes uniformly with ``op.luts``."""
    from deequ_tpu.data.table import DType
    from deequ_tpu.ops.scan_engine import (
        _enc_hi_lut,
        _enc_i32_lut,
        _enc_lo_lut,
    )

    specs: List[Tuple[str, str, Any]] = []
    enc_names = plan.layout.get("enc", ())
    dtypes = (plan.unpack_view.col_dtype if plan.unpack_view else {})
    for name in enc_names:
        if dtypes.get(name) == DType.INTEGRAL:
            specs.append((name, "_enc_i32", _enc_i32_lut))
        else:
            specs.append((name, "_enc_hi", _enc_hi_lut))
            specs.append((name, "_enc_lo", _enc_lo_lut))
    return specs


def _member_lut(table, col: str, kind: str, builder) -> np.ndarray:
    """One member's host LUT array (memoized per dictionary identity by
    lut_cache). Encoded kinds build from the column's ENCODING
    dictionary; string kinds from the string dictionary."""
    from deequ_tpu.ops.lut_cache import dictionary_lut

    if kind.startswith("_enc_"):
        d = table[col].encoding.dictionary
    else:
        d = table[col].dictionary
    return dictionary_lut(d, kind, builder)


def stack_luts(plan, tables: Sequence, k_bucket: int):
    """Per-tenant LUT arguments stacked to (K, L_groupmax): every
    member's LUT pads to the group max pow2 (padding rows are zeros and
    never gathered — each member's codes index below its own
    cardinality, so per-slice results equal the serial path's
    individually-padded LUTs). Padding SLICES get zero LUTs (their codes
    are all -1 → masked; gathers clamp to index 0 of a zero row, and
    the slice's result is discarded anyway). Returns (host dict,
    lut_sig)."""
    specs: Dict[str, Tuple[str, str, Any]] = {}
    for op in plan.exec_ops:
        for col, kind, builder in op.luts:
            specs.setdefault(col + "\x00" + kind, (col, kind, builder))
    for col, kind, builder in _enc_lut_specs(plan):
        specs.setdefault(col + "\x00" + kind, (col, kind, builder))

    lut_stacked: Dict[str, np.ndarray] = {}
    for key, (col, kind, builder) in sorted(specs.items()):
        per_member = [
            _member_lut(t, col, kind, builder) for t in tables
        ]
        target = 1
        while target < max(len(a) for a in per_member):
            target <<= 1
        padded = []
        for a in per_member:
            if len(a) < target:
                out = np.zeros(target, dtype=a.dtype)
                out[: len(a)] = a
                a = out
            padded.append(a)
        for _ in range(k_bucket - len(tables)):
            padded.append(np.zeros(target, dtype=padded[0].dtype))
        lut_stacked[key] = np.stack(padded)
    lut_sig = tuple(
        sorted(
            (key, tuple(int(d) for d in arr.shape), str(arr.dtype))
            for key, arr in lut_stacked.items()
        )
    )
    return lut_stacked, lut_sig


def _build_packed_program(plan, lut_keys: Tuple[str, ...], op_order=None):
    """Trace the shared single-member flat step and vmap it over the
    tenant axis — the run_scan_group program shape, built from the
    plan's metadata-only unpack view (never pinning member tables).
    ``op_order`` (round 19) traces the ops in CANONICAL order so the
    program is shareable across suites below the exact PlanKey; the
    caller permutes results back to exec order."""
    view = plan.unpack_view
    ops = (
        plan.exec_ops
        if op_order is None
        else tuple(plan.exec_ops[i] for i in op_order)
    )
    chunk = plan.key.chunk

    def single_tree(values, hi, lo, narrow_i, masks, codes, row_valid, enc, luts):
        from deequ_tpu.ops.scan_engine import _tag_identity_wrap

        col_luts: Dict[str, Dict[str, Any]] = {}
        for key, arr in luts.items():
            lcol, lkind = _split_lut_key(key)
            col_luts.setdefault(lcol, {})[lkind] = arr
        vals = view.unpack_vals(
            values, hi, lo, narrow_i, masks, codes, jnp, row_valid,
            col_luts=col_luts, enc=enc,
        )
        return tuple(
            jax.tree.map(
                _tag_identity_wrap,
                op.tags,
                op.update(vals, row_valid, jnp, chunk),
            )
            for op in ops
        )

    def single_flat(*args):
        leaves = jax.tree.leaves(single_tree(*args))
        return jnp.concatenate(
            [jnp.ravel(leaf).astype(jnp.float64) for leaf in leaves]
        )

    return single_tree, single_flat, jax.jit(jax.vmap(single_flat))


def _unflatten_recipe(shapes):
    """Precompute the per-op slice/reshape/dtype plan for unflattening
    one member's flat f64 state vector — built once per traced program
    (alongside it in the plan's program table) instead of re-deriving
    dtype promotions per member per batch. Integer leaves widen to i64
    exactly like ``scan_engine._unflatten_partials``."""
    recipes = []
    offset = 0
    for op_shapes in shapes:
        leaves, treedef = jax.tree.flatten(op_shapes)
        specs = []
        for sd in leaves:
            size = int(np.prod(sd.shape)) if sd.shape else 1
            dtype = (
                np.int64 if np.issubdtype(sd.dtype, np.integer)
                else sd.dtype
            )
            specs.append((offset, size, sd.shape, dtype))
            offset += size
        recipes.append((specs, treedef))
    return recipes


def _unflatten_member(flat: np.ndarray, recipes) -> List[Any]:
    out = []
    for specs, treedef in recipes:
        leaves = []
        for offset, size, shape, dtype in specs:
            leaf = flat[offset:offset + size].astype(dtype)
            leaves.append(
                leaf.reshape(shape) if shape else leaf.reshape(())
            )
        out.append(jax.tree.unflatten(treedef, leaves))
    return out


def packed_lint_memo_key(plan, k_bucket: int, lut_sig, members) -> Tuple:
    """The packed program's OWN lint memo identity: tenant-axis bucket +
    per-member contract fingerprints on top of the plan fingerprint —
    a packed plan never inherits its single-tenant twin's verdict, and a
    batch with different member contracts lints fresh. The canonical op
    ordering (round 19: the traced program runs ops in shareable
    canonical order, not submission order) rides in the key too, so a
    verdict memoized against the canonical program can never be replayed
    against a differently-ordered one."""
    from deequ_tpu.serve.plan_cache import canonical_op_order

    canon, _ = canonical_op_order(getattr(plan, "exec_ops", ()))
    member_fp = tuple(
        (m.label if m.padding else "", m.variant, m.ingest_variant,
         m.encoded_columns, m.padding)
        for m in members
    )
    return ("packed", plan.key, canon, k_bucket, lut_sig, member_fp)


def run_coalesced(
    plan,
    tables: Sequence,
    labels: Sequence[str],
    plan_lint: str = "off",
    device_deadline: Optional[float] = None,
    attempt: int = 0,
    packers: Sequence = (),
) -> List[List[Any]]:
    """Execute K member tables of ``plan`` as ONE padded vmapped dispatch
    + ONE fetch. Returns per-member results lists (exec-op order, the
    shape ``run_scan`` returns), real members only — padding slices are
    computed and discarded. Raises typed ``Device*Exception`` on device
    faults (the service's tenant-axis bisection catches them) and
    ``PlanLintError`` when an armed lint rejects the packed program.

    Cache accounting (per coalesced batch): a ``plan_cache_hit`` found
    the traced program for this plan's (tenant bucket, LUT signature) —
    the batch runs with zero op builds, zero traces, zero compiles, and
    zero plan-lint traces (lint verdicts memoize under the packed key);
    a ``plan_cache_miss`` paid the one-time trace."""
    from deequ_tpu.lint.plan_lint import enforce_plan_lint, lint_plan_cached
    from deequ_tpu.ops.scan_plan import PackedMember, plan_packed_scan

    K = len(tables)
    assert K == len(labels) and K > 0
    if device_deadline is None:
        from deequ_tpu.ops.device_policy import default_device_deadline

        device_deadline = default_device_deadline()
    k_bucket = 1
    while k_bucket < K:
        k_bucket <<= 1

    t_start = time.time()
    # coalesced-batch assembly is host work worth its own span: K tables
    # pack + stack + LUTs pad to the group max — the serving path's one
    # per-batch host cost that scales with K
    from contextlib import nullcontext

    from deequ_tpu.obs.recorder import current_recorder

    rec = current_recorder()
    with (
        rec.span("coalesce_assembly", tenants=K, bucket=k_bucket)
        if rec is not None
        else nullcontext()
    ):
        bufs = _stack_member_buffers(plan, tables, k_bucket, packers)
        lut_host, lut_sig = stack_luts(plan, tables, k_bucket)

    # plan_scan_ops with no packer (members pack host-side, fresh per
    # batch): carry the GROUP layout + encoded declaration explicitly so
    # the per-member encoded checks see the real routing
    from dataclasses import replace as _replace

    from deequ_tpu.serve.plan_cache import layout_signature

    base_ir = plan_packed_scan(plan.exec_ops, packer=None)
    enc_cols = tuple(plan.layout.get("enc", ()))
    members = [
        PackedMember(
            label=str(label),
            variant=base_ir.variant,
            ingest_variant="encoded" if enc_cols else "decoded",
            encoded_columns=enc_cols,
        )
        for label in labels
    ] + [
        PackedMember(label=f"pad[{i}]", padding=True)
        for i in range(k_bucket - K)
    ]
    plan_ir = _replace(
        base_ir,
        tenants=len(members),
        members=tuple(members),
        ingest_variant="encoded" if enc_cols else "decoded",
        encoded_columns=enc_cols,
        layout=layout_signature(plan.layout),
    )

    from deequ_tpu.serve.plan_cache import (
        SUBPLAN_CACHE,
        canonical_op_order,
        subplan_key,
    )

    # programs are traced in CANONICAL op order (round 19) so suites
    # that dedupe to the same op set — permuted submissions included —
    # share ONE traced program below the exact PlanKey; `perm` maps
    # canonical result positions back to this plan's exec order
    canon, perm = canonical_op_order(plan.exec_ops)
    sub_key = subplan_key(
        plan, canon, k_bucket, lut_sig,
        base_ir.variant, base_ir.hist_variant,
        "encoded" if enc_cols else "decoded",
    )
    if plan_lint != "off":
        # the sharing half of plan-fusion-refetch: a sub-plan key that
        # dropped an identity component would alias different programs
        from deequ_tpu.lint.plan_lint import check_subplan_key

        key_findings = check_subplan_key(sub_key)
        if key_findings:
            SCAN_STATS.plan_lints.extend(f.as_dict() for f in key_findings)
            enforce_plan_lint(key_findings, plan_lint)

    cached = plan.program_for(k_bucket, lut_sig)
    if cached is not None:
        single_flat, vstep, shapes, recipes, perm = cached
        SCAN_STATS.programs_reused += 1
        # suite-weighted ledger: every member of this batch was served
        # from the compiled-plan cache (zero builds/traces/compiles/lint
        # traces) — the hit RATE reads as "fraction of suites served
        # from cache", the serving-layer observable
        SCAN_STATS.plan_cache_hits += K
    else:
        shared = SUBPLAN_CACHE.get(sub_key)
        if shared is not None:
            # cross-suite hit: another PlanKey already traced this
            # canonical program at this (bucket, LUT) shape — adopt it
            # with our own exec-order permutation, zero traces paid
            single_flat, vstep, shapes, recipes = shared
            SCAN_STATS.programs_reused += 1
            SCAN_STATS.plan_cache_hits += K
            SCAN_STATS.record_subplan_hit(K)
            plan.put_program(
                k_bucket, lut_sig,
                (single_flat, vstep, shapes, recipes, perm),
            )
        else:
            SCAN_STATS.programs_built += 1
            SCAN_STATS.plan_cache_misses += K
            _tree, single_flat, vstep = _build_packed_program(
                plan, tuple(sorted(lut_host)), op_order=canon
            )
            shapes = device_call(
                lambda: jax.eval_shape(
                    _tree,
                    *(b[0] for b in bufs),
                    {k: v[0] for k, v in lut_host.items()},
                ),
                "trace", what="packed scan trace", deadline=device_deadline,
            )
            recipes = _unflatten_recipe(shapes)
            plan.put_program(
                k_bucket, lut_sig,
                (single_flat, vstep, shapes, recipes, perm),
            )
            SUBPLAN_CACHE.put(
                sub_key, (single_flat, vstep, shapes, recipes)
            )

    # packed plan lint BEFORE dispatch, memoized under the packed key:
    # a cache-hit batch (plan + program + lint verdict all memoized)
    # performs ZERO lint traces — the repeat-tenant contract
    if plan_lint != "off":
        # the LUTs must enter the lint trace as ARGUMENTS (abstract),
        # exactly like the build trace above passes them: closing over
        # the concrete host arrays routes encoded-column ingest through
        # numpy fancy indexing on a traced codes buffer, which raises
        # TracerArrayConversionError for any plan with encoded columns
        # (e.g. ApproxCountDistinct/DataType on strings — the profile
        # pass-1 shape)
        lut_items = sorted(lut_host.items())
        lut_keys = tuple(k for k, _ in lut_items)
        n_bufs = len(bufs)
        avals = tuple(
            jax.ShapeDtypeStruct(b.shape[1:], b.dtype) for b in bufs
        ) + tuple(
            jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
            for _, v in lut_items
        )
        findings, traced = lint_plan_cached(
            plan_ir,
            lambda *a: single_flat(
                *a[:n_bufs], dict(zip(lut_keys, a[n_bufs:]))
            ),
            avals,
            packed_lint_memo_key(plan, k_bucket, lut_sig, members),
        )
        if traced:
            SCAN_STATS.plan_lint_traces += 1
        if findings:
            SCAN_STATS.plan_lints.extend(f.as_dict() for f in findings)
        enforce_plan_lint(findings, plan_lint)

    SCAN_STATS.scan_passes += 1
    SCAN_STATS.rows_scanned += sum(int(t.num_rows) for t in tables)
    SCAN_STATS.coalesced_batches += 1
    SCAN_STATS.coalesced_tenants += K
    SCAN_STATS.coalesce_padded_slots += k_bucket - K
    # kernel census per REAL member (the serial-equivalence accounting
    # run_scan_group uses; padding slices are overhead, visible via
    # coalesce_padded_slots, not kernel passes)
    from deequ_tpu.ops.scan_engine import _record_kernel_passes

    _record_kernel_passes(base_ir, K)

    # one logical scan id per coalesced dispatch — the chaos engine's
    # FaultInjectingScanHook scripts by scan id, so a scripted fault can
    # target a coalesced batch exactly like any other scan; bisection
    # retries arrive as fresh dispatches (fresh ids) with `attempt`
    # carrying the service's tenant-axis split depth
    from deequ_tpu.ops.scan_engine import _SCAN_IDS

    scan_id = next(_SCAN_IDS)
    hook_ctx = {
        "scan_id": scan_id, "attempt": attempt, "fallback": False,
        "chunk_index": 0, "device_ids": (), "coalesced": K,
    }
    lut_dev = {k: jax.device_put(v) for k, v in lut_host.items()}
    t_d = time.time()
    device_out = device_call(
        lambda: vstep(*bufs, lut_dev),
        "execute", what=f"coalesced dispatch (K={K}/{k_bucket})",
        deadline=device_deadline, hook_ctx=hook_ctx,
    )
    SCAN_STATS.dispatch_seconds += time.time() - t_d

    def fetch() -> np.ndarray:
        t0 = time.time()
        host = np.asarray(device_out)  # the batch's ONE round trip
        SCAN_STATS.drain_wait_seconds += time.time() - t0
        SCAN_STATS.record_fetch(host.nbytes)
        return host

    host = device_call(
        fetch, "fetch", what="coalesced drain", deadline=device_deadline,
    )
    out: List[List[Any]] = []
    for k in range(K):  # padding slices [K:] are discarded
        canonical = _unflatten_member(host[k], recipes)
        # the program computed ops in canonical (shareable) order;
        # callers consume exec-op order — permute back
        out.append([canonical[perm[i]] for i in range(len(canonical))])
    SCAN_STATS.chunks_processed += K
    SCAN_STATS.scan_seconds += time.time() - t_start
    return out
