"""VerificationService — async multi-tenant verification serving.

``submit(table, checks, tenant=...)`` returns a
:class:`VerificationFuture` immediately; ONE bounded worker thread
drains the pending queue, groups co-batchable suites (same
:class:`~deequ_tpu.serve.plan_cache.PlanKey` — schema, analyzers,
layout, row count), and executes each group as one coalesced dispatch
(:mod:`deequ_tpu.serve.executor`). Suites the fast path cannot take
(grouping/own-pass analyzers, dictionary-baked predicates, streaming or
multi-chunk tables, an active device mesh) run per-tenant through the
ordinary ``VerificationSuite`` engine — same results, no coalescing.

Isolation ladder (the PR-3/5 fault ladder applied per coalesced
dispatch, with per-tenant blast-radius control on top):

1. a classified device fault during a coalesced dispatch BISECTS the
   tenant axis: the batch splits in half and each half retries — a
   poison tenant is localized in O(log K) while every healthy member
   still completes (each split retry charges the members' own run
   budgets, kind ``coalesce_retry``);
2. a member that still faults alone falls back to the SERIAL per-tenant
   path, where ``run_scan``'s full ladder (OOM bisection, encoded
   demotion, CPU fallback) applies under that member's budget scope;
3. a member whose budget exhausts degrades ONLY its own slice — typed
   failure metrics on its result (``on_budget_exhausted="degrade"``) or
   a typed rejection (``"raise"``), never the batch;
4. a tenant that keeps failing is QUARANTINED (a
   ``tenant_quarantine`` degradation event): its later submissions are
   excluded from coalescing and served serially until one succeeds, so
   a repeat offender cannot keep forcing batch bisections.

Kill-and-resume: ``stop(drain=False)`` halts the worker after the
in-flight batch and returns the still-pending requests; a fresh
service's ``resume(pending)`` re-enqueues them onto the SAME futures, so
a supervisor can recycle a worker process without dropping accepted
work.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import CancelledError
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deequ_tpu.exceptions import (
    DeadlineExceededException,
    DeviceException,
    PlanLintError,
    RunBudgetExhaustedException,
    ServiceClosedException,
    wrap_if_necessary,
)
from deequ_tpu.serve.admission import (
    SLO_CLASSES,
    AdmissionController,
    BrownoutController,
    TenantFairQueue,
    resolve_slo,
)


@dataclass
class ServeConfig:
    """Service knobs. ``max_batch`` / ``coalesce_window`` default from
    the DEEQU_TPU_SERVE_MAX_BATCH / DEEQU_TPU_SERVE_COALESCE_WINDOW env
    vars (deequ_tpu/envcfg registry). ``run_policy`` is the per-tenant
    default fault budget (resilience/governance.RunPolicy; None =
    ungoverned unless a submit overrides); ``quarantine_after`` is the
    consecutive-failure threshold that parks a tenant on the serial
    path."""

    max_batch: Optional[int] = None
    coalesce_window: Optional[float] = None
    max_pending: int = 4096
    run_policy: Any = None
    on_device_error: str = "fail"
    plan_lint: Optional[str] = None
    quarantine_after: int = 2
    plan_cache_size: int = 256
    #: default SLO applied to submissions carrying none (None resolves
    #: the envcfg defaults DEEQU_TPU_SLO_CLASS / _SLO_DEADLINE_MS at
    #: each submit — see serve/admission.Slo.default)
    default_slo: Any = None
    #: brownout ladder switch (None = DEEQU_TPU_BROWNOUT, default on)
    brownout: Optional[bool] = None
    #: recent submit->resolve p95 (seconds) above which the ladder
    #: holds at least level 1 even with a shallow queue — a slow
    #: backend is overload too (None = queue-depth signal only)
    brownout_latency_high: Optional[float] = None
    #: per-tenant queued cap applied at brownout level >= 2 (None =
    #: max_pending // 16, floor 1 — AdmissionController's default)
    inflight_cap: Optional[int] = None
    #: per-class queue-share overrides (merged over
    #: admission.CLASS_QUEUE_SHARE)
    class_share: Optional[Dict[str, float]] = None

    def __post_init__(self):
        from deequ_tpu.envcfg import env_value

        if self.max_batch is None:
            self.max_batch = env_value("DEEQU_TPU_SERVE_MAX_BATCH")
        if self.coalesce_window is None:
            self.coalesce_window = env_value(
                "DEEQU_TPU_SERVE_COALESCE_WINDOW"
            )
        if self.brownout is None:
            self.brownout = env_value("DEEQU_TPU_BROWNOUT")
        self.max_batch = int(self.max_batch)
        if self.max_batch < 1:
            raise ValueError(
                f"max_batch must be >= 1, got {self.max_batch}"
            )
        self.coalesce_window = float(self.coalesce_window)
        if self.coalesce_window < 0:
            raise ValueError("coalesce_window must be >= 0 seconds")
        if self.on_device_error not in ("fail", "fallback"):
            raise ValueError(
                f"on_device_error must be 'fail' or 'fallback', "
                f"got {self.on_device_error!r}"
            )
        if self.quarantine_after < 1:
            raise ValueError("quarantine_after must be >= 1")


class VerificationFuture:
    """Handle for one submitted suite. ``result(timeout)`` blocks for
    the :class:`~deequ_tpu.verification.VerificationResult` (re-raising
    a typed failure); ``cancel()`` succeeds only while the request is
    still queued."""

    def __init__(self, tenant):
        self.tenant = tenant
        self.submitted_at = time.monotonic()
        self.resolved_at: Optional[float] = None
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._started = False
        self._lock = threading.Lock()
        #: applied resolutions (0 or 1 — chaos oracle 8's observable) and
        #: dropped late attempts: after a fleet failover re-dispatches
        #: this future onto a survivor, the original (stalled, presumed
        #: dead) worker may wake and resolve it a second time — the
        #: FIRST resolution wins, later attempts are counted here and
        #: discarded, so an accepted future resolves exactly once
        self.resolve_count = 0
        self.late_resolutions = 0
        # the service's observation seam (obs/registry latency histogram
        # + optional flight-recorder submit->resolve span): called once,
        # after resolve/reject — never for a cancel (no latency to
        # observe on work that never ran)
        self._on_done = None

    # -- consumer side ---------------------------------------------------

    def done(self) -> bool:
        return self._done.is_set()

    def cancelled(self) -> bool:
        return self._cancelled

    def cancel(self) -> bool:
        """Cancel if still pending. Returns True when the request will
        never execute; False when it already started (or finished)."""
        with self._lock:
            if self._started or self._done.is_set():
                return False
            self._cancelled = True
            self._done.set()
        return True

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                "verification result not ready within "
                f"{timeout if timeout is not None else 'inf'}s"
            )
        if self._cancelled:
            raise CancelledError()
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def latency_seconds(self) -> Optional[float]:
        if self.resolved_at is None:
            return None
        return self.resolved_at - self.submitted_at

    # -- service side ----------------------------------------------------

    def _claim(self) -> bool:
        """Mark started; False when the consumer already cancelled — or
        the future already resolved (a zombie worker re-claiming a
        request a fleet failover completed elsewhere skips the work)."""
        with self._lock:
            if self._cancelled or self._done.is_set():
                return False
            self._started = True
            return True

    def _apply(self, result, error) -> bool:
        """First-resolution-wins gate (see ``resolve_count``): outcome,
        timestamp, and the done flag commit atomically under the lock,
        so two racing resolvers can never both apply — nor can a waiter
        wake before the outcome it will read is in place."""
        with self._lock:
            if self._done.is_set():
                self.late_resolutions += 1
                return False
            self._result = result
            self._error = error
            self.resolved_at = time.monotonic()
            self.resolve_count += 1
            self._done.set()
            return True

    def _resolve(self, result) -> None:
        if not self._apply(result, None):
            return
        if self._on_done is not None:
            self._on_done(self, True)

    def _reject(self, error: BaseException) -> None:
        if not self._apply(None, error):
            return
        if self._on_done is not None:
            self._on_done(self, False)


class PendingWork(list):
    """What ``stop(drain=False)`` returns: the accepted-but-unserved
    requests PLUS the per-tenant quarantine snapshot. A plain list of
    requests was the round-10 shape — and silently dropped the
    quarantine ledger across kill-and-resume, so a quarantined poison
    tenant got a fresh start after every worker recycle (the round-12
    audit). Subclassing ``list`` keeps every existing consumer (len,
    iteration, ``resume(pending)``) working; ``resume`` additionally
    restores ``tenant_health`` when present."""

    def __init__(self, requests=(), tenant_health: Optional[dict] = None):
        super().__init__(requests)
        self.tenant_health = tenant_health


@dataclass
class ServeRequest:
    """One queued suite (internal; returned by ``stop(drain=False)`` for
    resume)."""

    data: Any
    checks: Tuple
    required_analyzers: Tuple
    tenant: Any
    run_policy: Any
    future: VerificationFuture
    #: the submission's SLO (serve/admission.Slo; resolved at submit)
    #: and its ABSOLUTE monotonic deadline (None = no deadline). The
    #: deadline is stamped ONCE, at first acceptance — resume() and
    #: fleet failover re-dispatch carry it unchanged, so queue wait
    #: accrues across worker recycles instead of resetting
    slo: Any = None
    deadline_at: Optional[float] = None
    #: filled at admission: the dedup'd analyzers + the plan fingerprint
    analyzers: Tuple = ()
    key: Any = None
    coalescable: bool = False
    #: the admission-time packer (layout already validated against the
    #: plan key) — reused by the executor so members pack without a
    #: second classification pass
    packer: Any = None
    #: PREDICTED plan cost (ops/plan_cost.py units, stamped at submit):
    #: feeds the queued-cost ledger behind cost-aware retry_after_s and
    #: the brownout ladder's cost pressure (round 19)
    predicted_cost: float = 0.0


class _TenantHealth:
    """Consecutive-failure ledger behind tenant quarantine (half-open:
    one success readmits the tenant to coalescing).

    Lock-serialized because the ledger is SHAREABLE: a
    :class:`~deequ_tpu.serve.fleet.VerificationFleet` hands ONE instance
    to every worker service, so a poison tenant quarantined by any
    worker is quarantined fleet-wide (and healed fleet-wide by one
    success) — N worker threads then mutate it concurrently."""

    def __init__(self, threshold: int):
        self.threshold = threshold
        self.failures: Dict[Any, int] = {}
        self.quarantined: set = set()
        self._lock = threading.Lock()

    def record_failure(self, tenant) -> bool:
        """Count one failure; True when this crossed the quarantine
        threshold (the caller records the degradation event)."""
        if tenant is None:
            return False
        with self._lock:
            n = self.failures.get(tenant, 0) + 1
            self.failures[tenant] = n
            if n >= self.threshold and tenant not in self.quarantined:
                self.quarantined.add(tenant)
                return True
            return False

    def record_success(self, tenant) -> None:
        if tenant is None:
            return
        with self._lock:
            self.failures.pop(tenant, None)
            self.quarantined.discard(tenant)

    def is_quarantined(self, tenant) -> bool:
        if tenant is None:
            return False
        with self._lock:
            return tenant in self.quarantined

    def snapshot(self) -> dict:
        """Kill-and-resume carrier (rides ``PendingWork``): the
        per-tenant state a recycled worker must not forget."""
        with self._lock:
            return {
                "threshold": self.threshold,
                "failures": dict(self.failures),
                "quarantined": set(self.quarantined),
            }

    def restore(self, snap: dict) -> None:
        """Merge a donor service's snapshot in (conservative union: a
        tenant quarantined on either side stays quarantined; failure
        counts keep the max)."""
        with self._lock:
            for tenant, n in (snap.get("failures") or {}).items():
                self.failures[tenant] = max(self.failures.get(tenant, 0), n)
            self.quarantined.update(snap.get("quarantined") or ())


class VerificationService:
    """The long-lived serving entry point (see module doc)."""

    def __init__(self, config: Optional[ServeConfig] = None, start: bool = True,
                 trace=None, device=None, tenant_health=None, monitor=None,
                 **knobs):
        from deequ_tpu.obs.recorder import (
            current_recorder,
            maybe_arm_from_env,
            resolve_recorder,
        )
        from deequ_tpu.parallel.mesh import current_mesh
        from deequ_tpu.serve.plan_cache import PlanCache

        self.config = config if config is not None else ServeConfig(**knobs)
        # flight recorder: like the mesh, the recorder is resolved at
        # CONSTRUCTION (the worker thread has no ambient scope of its
        # own) — explicit ``trace`` argument > the constructing thread's
        # ambient scope > the DEEQU_TPU_TRACE-armed global
        maybe_arm_from_env()
        self._recorder = (
            resolve_recorder(trace) if trace is not None
            else current_recorder()
        )
        self.plan_cache = PlanCache(self.config.plan_cache_size)
        # the quarantine ledger is injectable so a fleet can share ONE
        # across all its workers (cross-worker quarantine); standalone
        # services own a private one
        self.tenant_health = (
            tenant_health if tenant_health is not None
            else _TenantHealth(self.config.quarantine_after)
        )
        #: worker placement: when set, the worker thread executes under
        #: ``jax.default_device(device)`` — one service per chip (or
        #: forced-host device) is the fleet's worker shape
        self._device = device
        #: online quality monitoring at the RESOLVE seam
        #: (repository/monitor.py): every successfully resolved suite's
        #: metrics fold into the monitor's per-series anomaly state —
        #: serving traffic feeds the same watch rules repository saves
        #: do. A fleet shares ONE monitor across all its workers.
        self.monitor = monitor
        #: liveness observable for fleet membership: bumped every worker
        #: loop iteration; a worker stuck in a dispatch (or a scripted
        #: stall) stops bumping and the heartbeat probe declares it lost
        self.heartbeat = time.monotonic()
        self._stall_seconds = 0.0
        # the mesh is thread-local: capture the constructing thread's
        # resolution so the worker executes under the same device view
        # (coalescing requires the single-device view; under a mesh
        # every suite runs the serial sharded path for bit-identity
        # with what the caller would have computed inline)
        self._mesh = current_mesh()
        #: analyzer_sig -> (needed columns, predicate mark set,
        #: scan-only?) — discovered by the first HEALTHY op build (see
        #: _admit). LRU-bounded like the plan cache beside it: a
        #: long-lived service meeting unbounded distinct analyzer sets
        #: (per-tenant predicates) must not grow host state forever
        from deequ_tpu.ops.scan_engine import _BoundedLRU

        self._families = _BoundedLRU(4 * self.config.plan_cache_size)
        # service-lifetime switch resolution: one env read at
        # construction, not one per admitted request
        from deequ_tpu.lint.plan_lint import plan_lint_mode
        from deequ_tpu.ops.scan_plan import encoded_ingest_enabled

        self._encode = encoded_ingest_enabled(None)
        self._lint_mode = plan_lint_mode(self.config.plan_lint)
        self._cv = threading.Condition()
        # the overload tier (round 15, serve/admission.py): the pending
        # queue is class-tiered weighted-deficit round-robin across
        # per-tenant queues with pop-time deadline shedding; admission
        # gates submit() by class budget + the brownout ladder
        self._queue = TenantFairQueue()
        self._brownout = BrownoutController(
            capacity=self.config.max_pending,
            latency_high=self.config.brownout_latency_high,
            enabled=bool(self.config.brownout),
        )
        self._admission = AdmissionController(
            max_pending=self.config.max_pending,
            brownout=self._brownout,
            class_share=self.config.class_share,
            inflight_cap=self.config.inflight_cap,
        )
        self._running = False
        self._closed = False
        self._idle = True
        self._thread: Optional[threading.Thread] = None
        self.batches_served = 0
        self.suites_served = 0
        # queued PREDICTED-cost ledger (ops/plan_cost.py units, round
        # 19): summed predicted_cost of every queued request, mutated
        # only under self._cv alongside the queue itself — the feed
        # behind cost-aware retry_after_s and brownout cost pressure
        self._queued_cost = 0.0
        if start:
            self.start()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._closed:
                raise ServiceClosedException("service is stopped")
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._worker, daemon=True, name="deequ-tpu-serve"
        )
        self._thread.start()

    def stop(self, drain: bool = True, join: bool = True) -> "PendingWork":
        """Stop the worker. ``drain=True`` serves everything already
        queued first; ``drain=False`` stops after the in-flight batch
        and RETURNS the still-pending requests (their futures
        unresolved) for :meth:`resume` on another service. The return
        value is a :class:`PendingWork` — a list of the requests
        carrying the per-tenant quarantine snapshot, so resume restores
        WHO was quarantined, not just what was queued. ``join=False``
        skips waiting for the worker thread (the fleet's simulated
        process death: a stalled thread cannot be joined and its late
        resolutions are dropped by the futures' first-wins gate)."""
        if drain:
            self.flush()
        with self._cv:
            self._closed = True
            self._running = False
            pending = self._queue.drain()
            self._queued_cost = 0.0
            self._cv.notify_all()
        if join and self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=30.0)
        return PendingWork(pending, tenant_health=self.tenant_health.snapshot())

    def resume(self, pending: Sequence[ServeRequest]) -> None:
        """Adopt another (stopped) service's pending requests: they
        re-enter this service's queue and resolve their ORIGINAL
        futures. A :class:`PendingWork` (what ``stop`` returns) also
        restores the donor's per-tenant quarantine state — a poison
        tenant must not get a fresh start because its worker was
        recycled."""
        snap = getattr(pending, "tenant_health", None)
        if snap:
            self.tenant_health.restore(snap)
        with self._cv:
            if self._closed:
                raise ServiceClosedException("service is stopped")
            for req in pending:
                # re-bind the observation seam: the adopted future must
                # observe into THIS service's recorder, not the stopped
                # donor's
                req.future._on_done = self._observe_done
                self._queue.push(req)
                self._queued_cost += float(
                    getattr(req, "predicted_cost", 0.0) or 0.0
                )
            self._cv.notify_all()

    def inject_stall(self, seconds: float) -> None:
        """Chaos worker seam: the worker thread sleeps ``seconds`` before
        its next batch take — a scripted stall. The heartbeat stops
        bumping for the duration, so fleet membership sees exactly what
        a wedged worker looks like."""
        with self._cv:
            self._stall_seconds = float(seconds)
            self._cv.notify_all()

    # -- fleet warmup ----------------------------------------------------

    def warm_state(self, limit: Optional[int] = None):
        """Exportable plan-cache warm state: (hot ServePlans — most
        recently used last, optionally the last ``limit`` —, the
        analyzer-family admission cache). In-process transfer: plans and
        their traced programs are host objects shared by reference."""
        plans = self.plan_cache.entries()
        if limit is not None:
            plans = plans[-limit:]
        return plans, dict(self._families._d)

    def warm_from(self, plans, families) -> None:
        """Adopt a donor's warm state (worker-join warmup: the fleet
        calls this BEFORE admitting traffic, so a joining worker's first
        requests hit the plan cache instead of paying trace storms)."""
        for key, family in families.items():
            self._families.put(key, family)
        for plan in plans:
            if plan.key is not None:
                self.plan_cache.put(plan)

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until the queue is empty and the worker is idle."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while len(self._queue) or not self._idle:
                if not self._running and len(self._queue):
                    raise ServiceClosedException(
                        "service stopped with requests pending"
                    )
                wait = None
                if deadline is not None:
                    wait = deadline - time.monotonic()
                    if wait <= 0:
                        raise TimeoutError("flush timed out")
                self._cv.wait(wait if wait is not None else 0.1)

    def __enter__(self) -> "VerificationService":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    # -- submission ------------------------------------------------------

    def submit(
        self,
        data,
        checks: Sequence = (),
        required_analyzers: Sequence = (),
        tenant=None,
        run_policy=None,
        slo=None,
    ) -> VerificationFuture:
        """Enqueue one verification suite; returns its future. The
        suite's fault budget is ``run_policy`` (or the service default);
        ``slo`` (serve/admission.Slo) sets its class, fair-share weight,
        and absolute deadline (default: the service/envcfg default).
        Backpressure is typed and structured — a full queue raises
        ``ServiceOverloadedException``; a class over its queue budget or
        a class the brownout ladder is shedding raises
        ``AdmissionRejectedException`` — both carrying ``queue_depth`` /
        ``retry_after_s`` / ``slo_class`` so callers can schedule a
        retry instead of hammering."""
        from deequ_tpu.obs.registry import SERVE_QUEUE_DEPTH, SERVE_SUBMITTED

        slo = resolve_slo(
            slo if slo is not None else self.config.default_slo
        )
        # price the suite BEFORE taking the queue lock (the estimate
        # walks the analyzer list): the predicted cost feeds the
        # queued-cost ledger, cost-aware retry_after_s, and the
        # brownout ladder's cost pressure (ops/plan_cost.py, round 19)
        from deequ_tpu.ops.plan_cost import PLAN_COST_MODEL

        try:
            suite_analyzers = list(required_analyzers)
            for check in checks:
                suite_analyzers.extend(check.required_analyzers())
            predicted_cost = PLAN_COST_MODEL.estimate_suite(
                suite_analyzers, int(getattr(data, "num_rows", 0) or 0)
            ).total
        # deequ-lint: ignore[bare-except] -- an unpriceable suite admits under the legacy depth-only signals; pricing must never refuse work
        except Exception:  # noqa: BLE001
            predicted_cost = 0.0
        future = VerificationFuture(tenant)
        future._on_done = self._observe_done
        req = ServeRequest(
            data=data,
            checks=tuple(checks),
            required_analyzers=tuple(required_analyzers),
            tenant=tenant,
            run_policy=(
                run_policy if run_policy is not None
                else self.config.run_policy
            ),
            future=future,
            slo=slo,
            deadline_at=(
                future.submitted_at + slo.deadline_seconds
                if slo.deadline_seconds is not None else None
            ),
            predicted_cost=predicted_cost,
        )
        with self._cv:
            # a not-yet-started service accepts work (it queues until
            # start()); only a STOPPED service refuses typed
            if self._closed:
                raise ServiceClosedException(
                    "submit on a stopped VerificationService"
                )
            depth = len(self._queue)
            # publish the depth the admission decision reads — the
            # registry gauge IS the brownout ladder's queue-depth feed
            SERVE_QUEUE_DEPTH.set(depth)
            self._admission.admit(
                tenant=tenant,
                slo=slo,
                queue_depth=depth,
                class_depth=self._queue.class_depth(slo.cls),
                tenant_pending=self._queue.tenant_depth(tenant),
                queued_cost=self._queued_cost + predicted_cost,
            )
            self._queue.push(req)
            self._queued_cost += predicted_cost
            # accounting AFTER the enqueue succeeded but BEFORE the
            # worker is notified: SERVE_SUBMITTED means "accepted" (a
            # typed closed/overload/admission refusal above must not
            # count), and incrementing outside the lock would let a
            # fast worker resolve the request first — a concurrent
            # scrape would see resolved > submitted
            SERVE_SUBMITTED.inc()
            if self._recorder is not None:
                self._recorder.event(
                    "serve_submit", tenant=str(tenant), slo_class=slo.cls,
                )
            self._cv.notify_all()
        return future

    def verify(self, data, checks: Sequence = (), **kw):
        """Synchronous convenience: submit + wait."""
        return self.submit(data, checks, **kw).result()

    def _observe_done(self, future: VerificationFuture, ok: bool) -> None:
        """Per-request observation seam, called exactly once per
        resolved/rejected future: feed the ALWAYS-ON registry latency
        histogram (per-tenant + aggregate — the p50/p95/p99 the bench
        probes previously re-derived per run) and, when tracing is
        armed, record the whole submit->resolve span retroactively on a
        synthetic per-tenant track (submit happened on the caller
        thread, resolve on the worker — the future's monotonic stamps
        are the span's bounds)."""
        from deequ_tpu.obs.registry import (
            SERVE_LATENCY,
            SERVE_REJECTED,
            SERVE_RESOLVED,
        )

        (SERVE_RESOLVED if ok else SERVE_REJECTED).inc()
        if ok and self.monitor is not None and future._result is not None:
            try:
                self.monitor.observe_verification(
                    future.tenant, future._result
                )
            # deequ-lint: ignore[bare-except] -- monitoring is observation, never outcome: a watch-rule error must not reject a future that already resolved with a good result; the error is counted on MONITOR_STATS
            except Exception:  # noqa: BLE001
                from deequ_tpu.repository.monitor import MONITOR_STATS

                MONITOR_STATS.monitor_errors += 1
        latency = future.latency_seconds
        if latency is None:
            return
        tenant = "?" if future.tenant is None else str(future.tenant)
        SERVE_LATENCY.observe(tenant, latency)
        # the same value the registry histogram observes feeds the
        # brownout ladder's latency signal (consulted only when
        # ServeConfig.brownout_latency_high arms it)
        self._brownout.observe_latency(latency)
        if self._recorder is not None:
            self._recorder.record_span(
                "serve_request",
                future.submitted_at,
                future.resolved_at,
                track=f"tenant/{tenant}",
                tenant=tenant,
                ok=ok,
            )

    # -- worker ----------------------------------------------------------

    def _worker(self) -> None:
        from contextlib import nullcontext

        import jax

        from deequ_tpu.obs.recorder import recording_scope
        from deequ_tpu.parallel.mesh import use_mesh

        with use_mesh(self._mesh), (
            jax.default_device(self._device)
            if self._device is not None
            else nullcontext()
        ), (
            recording_scope(self._recorder)
            if self._recorder is not None
            else nullcontext()
        ):
            while True:
                with self._cv:
                    stall, self._stall_seconds = self._stall_seconds, 0.0
                if stall > 0:
                    # scripted stall (chaos worker seam): heartbeat
                    # freezes for the duration — membership sees a
                    # wedged worker
                    time.sleep(stall)
                self.heartbeat = time.monotonic()
                batch = self._take_batch()
                if batch is None:
                    return
                if not batch:
                    # empty batch = a stall became pending while idle:
                    # loop back so the top-of-loop consumption wedges
                    # the worker now
                    continue
                try:
                    self._serve_batch(batch)
                # deequ-lint: ignore[bare-except] -- worker survival backstop: an unexpected per-batch failure rejects that batch's futures typed and the loop continues; a dead worker would strand every future forever
                except Exception as e:  # noqa: BLE001 — the serving loop
                    # must outlive any one batch: reject what this batch
                    # left unresolved and keep draining the queue
                    wrapped = wrap_if_necessary(e)
                    for req in batch:
                        if not req.future.done():
                            req.future._reject(wrapped)
                finally:
                    with self._cv:
                        self._idle = True
                        self._cv.notify_all()

    def _take_batch(self) -> Optional[List[ServeRequest]]:
        """Pop up to ``max_batch`` requests — class priority then
        weighted tenant fair share (TenantFairQueue) — waiting
        ``coalesce_window`` after the first arrival for co-batchable
        company. Requests whose absolute deadline expired in-queue are
        SHED here, pre-dispatch: collected under the lock, resolved
        typed after releasing it (a future's resolution callback may
        take foreign locks — the fleet ledger's — and must never nest
        inside ``_cv``)."""
        cfg = self.config
        with self._cv:
            while not len(self._queue):
                if not self._running:
                    return None
                if self._stall_seconds:
                    # a stall injected while idle surfaces to the worker
                    # loop (empty batch) so it wedges BEFORE the next
                    # take — a scripted stall must deterministically
                    # freeze whatever is submitted after it, not serve
                    # one last batch first
                    return []
                self._idle = True
                self.heartbeat = time.monotonic()
                # idle ticks walk the brownout ladder back down: the
                # pre-pop update below last saw the FULL backlog, so a
                # queue drained in one wide batch would otherwise park
                # the service at a high level and refuse the first
                # best_effort submissions against an empty queue
                self._brownout.update(0)
                self._cv.notify_all()
                self._cv.wait(0.1)
            self._idle = False
        if cfg.coalesce_window > 0 and cfg.max_batch > 1:
            deadline = time.monotonic() + cfg.coalesce_window
            with self._cv:
                while len(self._queue) < cfg.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._running:
                        break
                    self._cv.wait(left)
        out: List[ServeRequest] = []
        shed: List[ServeRequest] = []
        with self._cv:
            from deequ_tpu.obs.registry import SERVE_QUEUE_DEPTH

            SERVE_QUEUE_DEPTH.set(len(self._queue))
            # drain-side ladder update: levels come back DOWN while the
            # worker empties the queue even if nobody submits
            self._brownout.update(
                len(self._queue),
                cost_frac=self._admission.cost_fraction(self._queued_cost),
            )
            now = time.monotonic()
            while len(self._queue) and len(out) < cfg.max_batch:
                req = self._queue.pop(now, shed.append)
                if req is None:
                    break
                out.append(req)
            # the ledger tracks QUEUED cost only: both a pop (about to
            # serve) and a shed (about to resolve typed) leave the queue
            for req in out:
                self._queued_cost -= float(
                    getattr(req, "predicted_cost", 0.0) or 0.0
                )
            for req in shed:
                self._queued_cost -= float(
                    getattr(req, "predicted_cost", 0.0) or 0.0
                )
            # an empty queue pins the ledger to exactly zero (float
            # subtraction drift must not accumulate across batches)
            if not len(self._queue) or self._queued_cost < 0.0:
                self._queued_cost = 0.0
            # post-pop update: this batch may have taken the whole
            # backlog, and the level should reflect what REMAINS
            self._brownout.update(
                len(self._queue),
                cost_frac=self._admission.cost_fraction(self._queued_cost),
            )
        for req in shed:
            self._shed_expired(req)
        return out

    def _shed_expired(self, req: ServeRequest) -> None:
        """Resolve one deadline-expired request typed, exactly once, on
        its original future (a shed IS a resolution — chaos oracle 9
        counts it), charging the tenant's budget kind ``deadline_shed``
        (exhaustion swallowed: the shed is already the terminal
        outcome). Called OUTSIDE the queue lock."""
        from deequ_tpu.obs.registry import SERVE_SHED_BY_CLASS
        from deequ_tpu.ops.scan_engine import SCAN_STATS
        from deequ_tpu.resilience.governance import try_charge

        waited = time.monotonic() - req.future.submitted_at
        SCAN_STATS.record_degradation(
            "deadline_shed", tenant=req.tenant, slo_class=req.slo.cls,
            deadline_ms=req.slo.deadline_ms, waited_s=round(waited, 4),
        )
        SERVE_SHED_BY_CLASS[req.slo.cls].inc()
        budget = (
            req.run_policy.arm() if req.run_policy is not None else None
        )
        try_charge(budget, "deadline_shed", tenant=req.tenant)
        # NOT a tenant failure for quarantine accounting: the tenant's
        # data never ran, so health stays untouched either way
        req.future._reject(DeadlineExceededException(
            f"request for tenant {req.tenant!r} expired in-queue: waited "
            f"{waited * 1000:.1f} ms past its {req.slo.cls!r} SLO "
            f"deadline of {req.slo.deadline_ms:g} ms — shed pre-dispatch",
            tenant=req.tenant, slo_class=req.slo.cls,
            deadline_ms=req.slo.deadline_ms, waited_s=waited,
            retry_after_s=self._admission.retry_after(self.pending_count()),
        ))

    # -- execution -------------------------------------------------------

    def _serve_batch(self, batch: List[ServeRequest]) -> None:
        alive: List[ServeRequest] = []
        for req in batch:
            if req.future._claim():
                alive.append(req)
        if not alive:
            return
        batch_t0 = time.monotonic()
        groups: Dict[Any, List[ServeRequest]] = {}
        serial: List[ServeRequest] = []
        for req in alive:
            try:
                self._admit(req)
            # deequ-lint: ignore[bare-except] -- admission failure becomes this one future's typed rejection, never silence
            except Exception as e:  # noqa: BLE001 — admission failure is
                # this request's outcome, not the batch's
                req.future._reject(wrap_if_necessary(e))
                continue
            if req.coalescable and not self.tenant_health.is_quarantined(
                req.tenant
            ):
                groups.setdefault(req.key, []).append(req)
            else:
                serial.append(req)
        for key, members in groups.items():
            try:
                self._serve_coalesced(members)
            # deequ-lint: ignore[bare-except] -- group isolation: an unexpected failure (bad run_policy, plan-build bug) rejects THIS group's futures typed; sibling groups of the batch still serve
            except Exception as e:  # noqa: BLE001 — one group's failure
                # must not strand its siblings' futures
                wrapped = wrap_if_necessary(e)
                for req in members:
                    if not req.future.done():
                        req.future._reject(wrapped)
        for req in serial:
            try:
                self._serve_serial(req)
            # deequ-lint: ignore[bare-except] -- per-request isolation: _serve_serial handles engine failures itself; this catches pre-engine failures (e.g. a run_policy without arm()) as the request's typed rejection
            except Exception as e:  # noqa: BLE001
                if not req.future.done():
                    req.future._reject(wrap_if_necessary(e))
        self.batches_served += 1
        self.suites_served += len(alive)
        # the drain-rate feed behind retry_after: refused callers are
        # told when the queue will plausibly have drained at this rate.
        # The summed predicted cost turns that into a COST-drain rate,
        # so a heavy backlog schedules later retries than a shallow one
        # of the same depth (ops/plan_cost.py)
        self._admission.note_served(
            len(alive), time.monotonic() - batch_t0,
            cost=sum(
                float(getattr(r, "predicted_cost", 0.0) or 0.0)
                for r in alive
            ),
        )

    def _admit(self, req: ServeRequest) -> None:
        """Fingerprint the request and decide coalescability (schema +
        analyzer + layout + row-count key; see plan_cache).

        The needed-column set and the predicate MARK set (columns an
        exact-compare predicate routes onto the wide plane) are
        properties of the ANALYZER SET alone, discovered by building the
        ops once — cached per analyzer signature (``_families``) so
        every later request fingerprints without an op build. Marks are
        re-applied to each member's columns before layout derivation, so
        key computation is one deterministic function of (analyzers,
        schema, data ranges) for first and repeat tenants alike."""
        from deequ_tpu.ops.scan_engine import _ChunkPacker
        from deequ_tpu.serve.plan_cache import (
            PlanKey,
            build_serve_plan,
            layout_signature,
            schema_signature,
        )
        from deequ_tpu.verification import _dedup_analyzers

        analyzers = list(req.required_analyzers)
        for check in req.checks:
            analyzers.extend(check.required_analyzers())
        req.analyzers = tuple(_dedup_analyzers(analyzers))

        req.coalescable = False
        if self._mesh is not None:
            return  # sharded view: serial path preserves mesh numerics
        data = req.data
        if getattr(data, "is_streaming", False):
            return
        try:
            n_rows = int(data.num_rows or 0)
        except (AttributeError, TypeError):
            return
        if n_rows <= 0 or not req.analyzers:
            return

        family = self._families.get(req.analyzers)
        if family is None:
            # first sight of this analyzer set: build its plan from this
            # request's table — the op build discovers the needed
            # columns and applies the predicate marks we record. Only
            # TABLE-INDEPENDENT facts may enter the family cache: a
            # CLASS-level serial verdict (grouping analyzers,
            # dictionary-baked predicates) is cached, but a verdict this
            # particular table caused (missing column -> op/precondition
            # failures, empty/oversized table) must not poison later
            # tenants' admissions, and a plan carrying THIS table's
            # failure records must never be replayed for healthy repeat
            # tenants — such requests serve serially and the family
            # waits for a healthy first sighting.
            plan = build_serve_plan(data, req.analyzers)
            if plan.serial_class:
                self._families.put(req.analyzers, ((), (), False))
                return
            if (
                not plan.coalescable
                or plan.op_failures
                or plan.precondition_failures
            ):
                return  # table-level degeneracy: serial, no family yet
            marks = tuple(
                n for n in plan.needed
                if getattr(data[n], "_exact_compare", False)
            )
            self._families.put(req.analyzers, (plan.needed, marks, True))
            plan.key = PlanKey(
                schema_sig=schema_signature(data, plan.needed),
                analyzer_sig=req.analyzers,
                layout_sig=layout_signature(plan.layout),
                chunk=n_rows,
            )
            self.plan_cache.put(plan)
            req.key = plan.key
            req.coalescable = True
            return

        needed, marks, scan_only = family
        if not scan_only:
            return
        if any(n not in data for n in needed):
            return  # missing columns: the serial path's precondition
            # machinery reports them as failure metrics
        for n in marks:
            data[n]._exact_compare = True
        try:
            packer = _ChunkPacker(
                {n: data[n] for n in needed},
                max(n_rows, 1),
                encode_ingest=self._encode,
            )
        # deequ-lint: ignore[bare-except] -- fingerprint probe only: an unpackable column routes the suite to the serial path, which re-raises/reports typed
        except Exception:  # noqa: BLE001 — unpackable columns: serial path
            return
        req.key = PlanKey(
            schema_sig=schema_signature(data, needed),
            analyzer_sig=req.analyzers,
            layout_sig=layout_signature(packer.layout()),
            chunk=n_rows,
        )
        req.packer = packer
        req.coalescable = True

    def _plan_for(self, req: ServeRequest):
        from deequ_tpu.serve.plan_cache import build_serve_plan

        plan = self.plan_cache.get(req.key)
        if plan is not None:
            return plan
        plan = build_serve_plan(req.data, req.analyzers, key_hint=req.key)
        self.plan_cache.put(plan)
        return plan

    def _serve_coalesced(self, members: List[ServeRequest]) -> None:
        """One PlanKey group: plan lookup, coalesced execution with
        tenant-axis bisection on device faults, per-member finalize."""
        plan = self._plan_for(members[0])
        if not plan.coalescable:
            for req in members:
                self._serve_serial(req)
            return
        budgets = {
            id(req): (req.run_policy.arm() if req.run_policy is not None
                      else None)
            for req in members
        }
        self._dispatch_slice(plan, members, budgets)

    def _dispatch_slice(
        self,
        plan,
        members: List[ServeRequest],
        budgets: Dict[int, Any],
        depth: int = 0,
    ) -> None:
        """Run one tenant-axis slice coalesced; on a device fault, charge
        every member's budget and BISECT (isolation in O(log K));
        singletons that still fault fall to the serial ladder."""
        from deequ_tpu.ops.scan_engine import SCAN_STATS
        from deequ_tpu.serve.executor import run_coalesced

        try:
            results = run_coalesced(
                plan,
                [req.data for req in members],
                [str(req.tenant) for req in members],
                plan_lint=self._lint_mode,
                attempt=depth,
                packers=[req.packer for req in members],
            )
        except PlanLintError as e:
            # a static contract violation rejects the PROGRAM — every
            # member of the packed plan shares it, so each future gets
            # the typed error (the error-mode contract: raise, never
            # masquerade as data)
            for req in members:
                req.future._reject(e)
            return
        except DeviceException as e:
            survivors: List[ServeRequest] = []
            for req in members:
                budget = budgets.get(id(req))
                if budget is None:
                    survivors.append(req)
                    continue
                try:
                    budget.charge("coalesce_retry", tenant=req.tenant)
                    survivors.append(req)
                except RunBudgetExhaustedException as exhausted:
                    # THIS member's budget is spent: degrade its slice
                    # only — the rest of the batch retries without it
                    self._finalize_budget_exhausted(req, exhausted, budget)
            if len(survivors) == 0:
                return
            if len(survivors) == 1:
                self._serve_serial(
                    survivors[0], budget=budgets.get(id(survivors[0])),
                    after_fault=e,
                )
                return
            SCAN_STATS.record_degradation(
                "coalesce_bisect",
                members=len(survivors), depth=depth, error=str(e),
            )
            mid = len(survivors) // 2
            self._dispatch_slice(plan, survivors[:mid], budgets, depth + 1)
            self._dispatch_slice(plan, survivors[mid:], budgets, depth + 1)
            return
        # deequ-lint: ignore[bare-except] -- shared-scan failure becomes failure METRICS for every member (the runner's failure-as-data rule); device faults were already caught typed above
        except Exception as e:  # noqa: BLE001 — a shared-scan failure maps
            # onto every member's analyzers (the runner's rule)
            wrapped = wrap_if_necessary(e)
            for req in members:
                self._finalize_scan_failure(req, wrapped)
            return
        for req, result_row in zip(members, results):
            self._finalize_member(
                req, plan, result_row, budgets.get(id(req))
            )

    # -- finalization ----------------------------------------------------

    def _finalize_member(self, req, plan, scan_results, budget) -> None:
        """Scan results -> states -> metrics -> check evaluation ->
        resolved future (the per-tenant host tail the coalesced dispatch
        cannot share)."""
        from deequ_tpu.analyzers.runner import AnalysisRunner, AnalyzerContext
        from deequ_tpu.verification import VerificationSuite

        try:
            ctx = AnalyzerContext.empty()
            for a, exc in plan.precondition_failures.items():
                ctx.metric_map[a] = a.to_failure_metric(exc)
            for a, exc in plan.op_failures.items():
                ctx.metric_map[a] = a.to_failure_metric(exc)
            ctx = AnalysisRunner._finalize_scanning_analyzers(
                ctx, plan.scannable, plan.extract_plan, scan_results,
            )
            result = VerificationSuite._evaluate(req.checks, ctx)
            result.scan_stats = {"coalesced": True, "device_fetches": 1}
            if budget is not None:
                result.run_budget = budget.snapshot()
            self.tenant_health.record_success(req.tenant)
            req.future._resolve(result)
        # deequ-lint: ignore[bare-except] -- finalize failure becomes this member's typed rejection, never silence
        except Exception as e:  # noqa: BLE001 — finalize failure is this
            # member's outcome
            self._record_tenant_failure(req)
            req.future._reject(wrap_if_necessary(e))

    def _finalize_scan_failure(self, req, wrapped, count_failure=True) -> None:
        """Shared-scan failure -> failure metrics for every analyzer of
        this member (failure-as-data, the runner's shared-scan rule)."""
        from deequ_tpu.analyzers.runner import AnalyzerContext
        from deequ_tpu.verification import VerificationSuite

        if count_failure:
            self._record_tenant_failure(req)
        ctx = AnalyzerContext(
            {a: a.to_failure_metric(wrapped) for a in req.analyzers}
        )
        result = VerificationSuite._evaluate(req.checks, ctx)
        result.scan_stats = {"coalesced": False, "failed": str(wrapped)}
        req.future._resolve(result)

    def _finalize_budget_exhausted(self, req, exhausted, budget) -> None:
        """Budget exhaustion degrades ONLY this member's slice: typed
        failure metrics + the ledger under ``degrade``, a typed
        rejection under ``raise``."""
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        SCAN_STATS.record_degradation(
            "tenant_budget_exhausted", tenant=req.tenant,
            reason=exhausted.reason,
        )
        self._record_tenant_failure(req)
        if not exhausted.degraded:
            req.future._reject(exhausted)
            return
        self._finalize_scan_failure(req, exhausted, count_failure=False)
        # overwrite the generic telemetry with the ledger
        if req.future._result is not None and budget is not None:
            req.future._result.run_budget = budget.snapshot()

    def _record_tenant_failure(self, req) -> None:
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        if self.tenant_health.record_failure(req.tenant):
            SCAN_STATS.record_degradation(
                "tenant_quarantine", tenant=req.tenant,
                consecutive=self.tenant_health.failures.get(req.tenant),
            )

    # -- serial path -----------------------------------------------------

    def _serve_serial(
        self, req: ServeRequest, budget=None, after_fault=None
    ) -> None:
        """The ordinary per-tenant engine path (full fault ladder) under
        this member's budget scope — the coalesced path's singleton
        fallback and the route for non-coalescable / quarantined
        suites."""
        from contextlib import nullcontext

        from deequ_tpu.resilience.governance import run_budget_scope
        from deequ_tpu.verification import VerificationSuite

        if budget is None and req.run_policy is not None:
            budget = req.run_policy.arm()
        try:
            with (
                run_budget_scope(budget) if budget is not None
                else nullcontext()
            ):
                result = VerificationSuite.do_verification_run(
                    req.data,
                    list(req.checks),
                    list(req.required_analyzers),
                    on_device_error=self.config.on_device_error,
                )
            result.scan_stats = dict(result.scan_stats or {})
            result.scan_stats["coalesced"] = False
            if after_fault is not None:
                result.scan_stats["isolated_after"] = str(after_fault)
            if budget is not None:
                result.run_budget = budget.snapshot()
            # a run that completed only by exhausting its budget into a
            # degraded partial result is a tenant FAILURE for health
            # accounting — "resolved" must not heal a quarantine the
            # exhaustion itself would deepen
            if budget is not None and budget.exhausted_reason is not None:
                self._record_tenant_failure(req)
            else:
                self.tenant_health.record_success(req.tenant)
            req.future._resolve(result)
        except RunBudgetExhaustedException as e:
            self._finalize_budget_exhausted(req, e, budget)
        # deequ-lint: ignore[bare-except] -- serial-path failure becomes this request's typed rejection; run_scan already classified device faults inside
        except Exception as e:  # noqa: BLE001 — this request's outcome
            self._record_tenant_failure(req)
            req.future._reject(wrap_if_necessary(e))

    # -- introspection ---------------------------------------------------

    def pending_count(self) -> int:
        with self._cv:
            return len(self._queue)

    def stats(self) -> dict:
        with self._cv:
            pending = len(self._queue)
            by_class = {
                cls: self._queue.class_depth(cls) for cls in SLO_CLASSES
            }
        return {
            "batches_served": self.batches_served,
            "suites_served": self.suites_served,
            "pending": pending,
            "pending_by_class": by_class,
            "brownout_level": self._brownout.level,
            "plan_cache_entries": len(self.plan_cache),
            "quarantined_tenants": sorted(
                map(str, self.tenant_health.quarantined)
            ),
        }
