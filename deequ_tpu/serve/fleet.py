"""VerificationFleet — N serving workers with failover, one quarantine.

PR 10's :class:`~deequ_tpu.serve.service.VerificationService` is one
worker on one device: it dies with its thread, its plan cache dies with
it, and its quarantine ledger is private. This module is the fleet tier
(ROADMAP item 1 — the remaining gap between "a serving layer" and
"serves millions of users"):

- **Placement** — tenants route by consistent hash of the admission-free
  plan fingerprint (:mod:`deequ_tpu.serve.router`): plan-cache locality
  survives worker join/leave, so failover never pays a fleet-wide
  recompilation storm (the Flare locality argument, arXiv:1703.08219).
- **Membership** — heartbeat-driven (:mod:`deequ_tpu.serve.membership`,
  the ``check_peers`` probe seam applied in-process): a worker whose
  service thread dies or stalls past ``stall_timeout`` is declared lost
  (typed :class:`~deequ_tpu.exceptions.WorkerLostException`) by a
  background monitor — no human in the loop.
- **Failover** — the lost worker's accepted-but-unresolved requests
  (queued AND in-flight; the fleet ledger is authoritative) re-dispatch
  onto survivors on their ORIGINAL futures — ``stop(drain=False)`` /
  ``resume`` kill-and-resume semantics lifted from one service to the
  fleet. Plans are deterministic, so a re-dispatched result is
  bit-identical to what the dead worker would have produced; if the
  presumed-dead worker was merely stalled and wakes to resolve late,
  the futures' first-resolution-wins gate drops the duplicate — every
  accepted future resolves exactly once (chaos oracle 8).
- **No free retries** — a tenant's :class:`RunBudget` is armed ONCE at
  fleet submit and FOLLOWS the request: every failover re-dispatch
  charges it (kind ``worker_failover``), so a request cannot ride
  worker deaths to unlimited attempts; exhaustion degrades or rejects
  exactly as the single-service ladder does.
- **Cross-worker quarantine** — all workers share ONE ``_TenantHealth``
  ledger: a poison tenant quarantined by any worker is serial-only
  fleet-wide, and one success anywhere heals it fleet-wide.
- **Warm join** — a (re)joining worker imports the survivors' hot plans
  (the plan cache's LRU recency feed, surfaced through the obs registry
  as ``fleet.hot_plans``) BEFORE it is admitted to the ring, so its
  first requests hit warm state instead of paying trace storms.

Chaos seams: :meth:`kill_worker` (scripted death),
:meth:`stall_worker` (the service's ``inject_stall``), and
:meth:`rejoin_worker` — the ``worker`` seam
``resilience/chaos.py`` scripts under its invariant oracles.
"""

from __future__ import annotations

import threading
import time
import uuid
import weakref
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from deequ_tpu.exceptions import (
    CorruptStateException,
    DeadlineExceededException,
    RunBudgetExhaustedException,
    ServiceClosedException,
    ServiceOverloadedException,
    WorkerLostException,
)
from deequ_tpu.serve.admission import Slo, resolve_slo
from deequ_tpu.serve.membership import FleetMembership
from deequ_tpu.serve.router import ConsistentHashRouter, route_digest
from deequ_tpu.serve.service import (
    ServeConfig,
    ServeRequest,
    VerificationFuture,
    VerificationService,
    _TenantHealth,
)


class _PreArmedPolicy:
    """RunPolicy stand-in whose ``arm()`` returns the SAME armed budget
    every time: the mechanism that makes a tenant's budget FOLLOW its
    request across failover re-dispatch (a fresh worker calling
    ``run_policy.arm()`` must not mint a fresh ledger)."""

    def __init__(self, budget):
        self.budget = budget

    def arm(self):
        return self.budget


@dataclass
class FleetConfig:
    """Fleet knobs. ``n_workers`` / ``heartbeat_interval`` /
    ``failover_retries`` default from DEEQU_TPU_FLEET_WORKERS /
    DEEQU_TPU_HEARTBEAT_INTERVAL / DEEQU_TPU_FAILOVER_RETRIES (envcfg
    registry — typed ``EnvConfigError`` on garbage). ``worker_knobs``
    feed each worker's :class:`ServeConfig`; ``stall_timeout`` defaults
    to ``max(8 * heartbeat_interval, 2s)`` — generous enough that a
    busy batch is not a false positive; ``warm_plans`` bounds the
    hot-plan transfer per donor on worker join."""

    n_workers: Optional[int] = None
    heartbeat_interval: Optional[float] = None
    stall_timeout: Optional[float] = None
    failover_retries: Optional[int] = None
    warm_plans: int = 8
    monitor: bool = True
    #: one :class:`~deequ_tpu.repository.monitor.QualityMonitor` shared
    #: by EVERY worker's resolve seam (``monitor`` above is the
    #: membership heartbeat thread — unrelated): a tenant's resolved
    #: metrics fold into fleet-wide per-series anomaly state no matter
    #: which worker served it. Failover re-dispatch cannot fork the
    #: series because the observation seam hangs off the future's
    #: first-resolution-wins gate (a late resolution from a waking
    #: stalled worker never reaches the monitor) — NOT the monitor's
    #: stale-point gate, which serving observations bypass by design
    #: (they carry no dataset date, so observe_verification assigns
    #: each point a fresh synthetic time).
    quality_monitor: Any = None
    quarantine_after: int = 2
    run_policy: Any = None
    worker_knobs: Optional[Dict[str, Any]] = None
    #: durable request ledger (PR 17, serve/ledger.py): when set, every
    #: fleet acceptance fsyncs a checksummed frame before its future is
    #: returned and every resolution appends a tombstone, so even the
    #: IN-PROCESS fleet recovers orphaned futures after a coordinator
    #: crash — pass the same dir (plus ``resume_futures``) to a fresh
    #: fleet and it replays accepted-minus-tombstoned onto the original
    #: futures. One fleet per ledger dir. Defaults from
    #: DEEQU_TPU_FLEET_LEDGER_DIR (None = no durability).
    ledger_dir: Optional[str] = None
    ledger_mode: str = "recover"
    #: True (production shape) pins worker i to device i — fleet
    #: parallelism across chips, but a failover target pays one
    #: per-device compile for each migrated plan (jit executables are
    #: device-committed; transferred cache entries re-lower). False runs
    #: every worker on the ambient device with a SHARED compile cache —
    #: failover is warm immediately, which is what latency-sensitive
    #: single-chip deployments (and the deterministic chaos scenario,
    #: whose stall timeout must sit BELOW the scripted stall but ABOVE
    #: a steady-state dispatch) want.
    distinct_devices: bool = True

    def __post_init__(self):
        from deequ_tpu.envcfg import env_value

        if self.heartbeat_interval is None:
            self.heartbeat_interval = env_value(
                "DEEQU_TPU_HEARTBEAT_INTERVAL"
            )
        self.heartbeat_interval = float(self.heartbeat_interval)
        if self.heartbeat_interval <= 0:
            raise ValueError("heartbeat_interval must be > 0 seconds")
        if self.failover_retries is None:
            self.failover_retries = env_value("DEEQU_TPU_FAILOVER_RETRIES")
        self.failover_retries = int(self.failover_retries)
        if self.failover_retries < 0:
            raise ValueError("failover_retries must be >= 0")
        if self.n_workers is None:
            self.n_workers = env_value("DEEQU_TPU_FLEET_WORKERS")
        if self.n_workers is not None and int(self.n_workers) < 1:
            raise ValueError("n_workers must be >= 1")
        if self.stall_timeout is None:
            self.stall_timeout = max(8 * self.heartbeat_interval, 2.0)
        self.stall_timeout = float(self.stall_timeout)
        if self.warm_plans < 0:
            raise ValueError("warm_plans must be >= 0")
        self.worker_knobs = dict(self.worker_knobs or {})
        if self.ledger_dir is None:
            self.ledger_dir = env_value("DEEQU_TPU_FLEET_LEDGER_DIR")


class FleetWorker:
    """One fleet member: a :class:`VerificationService` pinned to a
    device, plus liveness state. ``alive=False`` workers stay in the
    table (their id can rejoin) but own no ring arcs."""

    def __init__(self, idx: int, service: VerificationService, device):
        self.idx = idx
        self.service = service
        self.device = device
        self.alive = True

    def queue_depth(self) -> int:
        try:
            return self.service.pending_count()
        except ServiceClosedException:
            return 0


@dataclass
class _Assignment:
    """The fleet's authoritative record of one accepted request — what
    failover re-dispatches when its worker dies (queued or in-flight
    alike; the dead worker's internal queue is NOT consulted)."""

    data: Any
    checks: tuple
    required_analyzers: tuple
    tenant: Any
    budget: Any            # armed RunBudget (None = ungoverned)
    digest: str
    worker: int
    failovers: int = 0
    #: the submission's SLO + its ABSOLUTE deadline, stamped ONCE at
    #: fleet submit: a failover re-dispatch carries both unchanged, and
    #: a victim whose deadline already passed is SHED typed on its
    #: original future instead of replayed stale (round 15)
    slo: Any = None
    deadline_at: Optional[float] = None
    #: this acceptance's durable-ledger frame id (None when the fleet
    #: runs without a ledger_dir)
    accept_id: Optional[str] = None


#: the most recent fleet, for the obs registry's read-through section
_ACTIVE_FLEET: Optional[weakref.ReferenceType] = None


def _fleet_section() -> dict:
    """The obs registry's ``fleet`` collector: workers alive, per-worker
    queue depth, failover count, and the hot-plan feed worker-join
    warmup draws from."""
    from deequ_tpu.obs.registry import FLEET_FAILOVERS

    fleet = _ACTIVE_FLEET() if _ACTIVE_FLEET is not None else None
    if fleet is None:
        return {"workers_alive": 0, "failovers": FLEET_FAILOVERS.value}
    return fleet._section()


class VerificationFleet:
    """The multi-worker serving entry point (see module doc)."""

    def __init__(self, config: Optional[FleetConfig] = None,
                 start: bool = True, trace=None,
                 resume_futures: Optional[Dict[str, Any]] = None,
                 **knobs):
        global _ACTIVE_FLEET
        import jax

        self.config = config if config is not None else FleetConfig(**knobs)
        self._trace = trace
        self._devices = list(jax.devices())
        n = self.config.n_workers
        if n is None:
            n = min(4, max(1, len(self._devices)))
        self.n_workers = int(n)
        # ONE quarantine ledger for the whole fleet (cross-worker
        # quarantine: poison isolated everywhere, healed everywhere)
        self._tenant_health = _TenantHealth(self.config.quarantine_after)
        self._router = ConsistentHashRouter()
        self._workers: Dict[int, FleetWorker] = {}
        self._zombies: List[VerificationService] = []
        self._assignments: Dict[Any, _Assignment] = {}
        self._heat: Dict[str, int] = {}
        self._lock = threading.Lock()
        # serializes loss handling AND submission against it: the
        # membership monitor, kill_worker, and rejoin_worker must not
        # interleave membership mutations — and a submit must fully
        # record its assignment before a loss snapshot runs, or a
        # monitor firing between enqueue and record would orphan the
        # future (its request cleared with the dead queue, its
        # assignment invisible to the victim sweep). Reentrant: a
        # submit that discovers a dead service retires it inline.
        self._failover_lock = threading.RLock()
        self._closed = False
        self.workers_lost = 0
        self.requests_redispatched = 0
        #: durable acceptance record (FleetConfig.ledger_dir): frames
        #: fsync at accept, tombstone at resolve — crash recovery for
        #: the in-process fleet too
        self._ledger = None
        #: accept_id -> future for ledger records replayed at startup
        self.resumed: Dict[str, Any] = {}
        if self.config.ledger_dir:
            from deequ_tpu.serve.ledger import RequestLedger

            self._ledger = RequestLedger(
                self.config.ledger_dir, mode=self.config.ledger_mode
            )
        self.membership = FleetMembership(
            members=self._alive_ids,
            probe_of=self._probe_worker,
            on_loss=self._handle_loss,
            interval=self.config.heartbeat_interval,
            stall_timeout=self.config.stall_timeout,
        )
        for idx in range(self.n_workers):
            service = self._spawn_service(idx)
            self._workers[idx] = FleetWorker(
                idx, service, self._device_for(idx)
            )
            self._router.add_worker(idx)
        _ACTIVE_FLEET = weakref.ref(self)
        from deequ_tpu.obs.registry import REGISTRY

        REGISTRY.register_collector("fleet", _fleet_section)
        self._update_alive_gauge()
        self._replay_ledger(resume_futures or {})
        if start and self.config.monitor:
            self.membership.start()

    # -- worker lifecycle ------------------------------------------------

    def _device_for(self, idx: int):
        if not self.config.distinct_devices or not self._devices:
            return None
        return self._devices[idx % len(self._devices)]

    def _spawn_service(self, idx: int) -> VerificationService:
        from deequ_tpu.parallel.mesh import use_mesh

        knobs = dict(self.config.worker_knobs)
        # each worker IS one device: construct under the single-device
        # view (not the caller's ambient mesh) so workers coalesce on
        # their own chip — fleet parallelism comes from placement across
        # workers, not from sharding one suite across chips
        with use_mesh(None):
            return VerificationService(
                config=ServeConfig(**knobs) if knobs else ServeConfig(),
                start=True,
                trace=self._trace,
                device=self._device_for(idx),
                tenant_health=self._tenant_health,
                monitor=self.config.quality_monitor,
            )

    def _alive_ids(self) -> List[int]:
        with self._lock:
            return sorted(i for i, w in self._workers.items() if w.alive)

    def _probe_worker(self, idx: int):
        with self._lock:
            worker = self._workers.get(idx)
        if worker is None:
            return False, 0.0
        thread = worker.service._thread
        return (
            thread is not None and thread.is_alive()
            and not worker.service._closed,
            worker.service.heartbeat,
        )

    def rejoin_worker(self, idx: int) -> Optional[FleetWorker]:
        """Bring a lost worker id back: a FRESH service, warmed from the
        survivors' hot plans BEFORE it owns any ring arc (a cold joiner
        admitted immediately would eat trace storms exactly when the
        fleet is already degraded)."""
        with self._failover_lock:
            with self._lock:
                if self._closed:
                    raise ServiceClosedException("fleet is stopped")
                existing = self._workers.get(idx)
                if existing is not None and existing.alive:
                    return existing
                donors = [
                    w.service for w in self._workers.values() if w.alive
                ]
            service = self._spawn_service(idx)
            self._warm(service, donors)
            worker = FleetWorker(idx, service, self._device_for(idx))
            with self._lock:
                self._workers[idx] = worker
                self._router.add_worker(idx)
            self._update_alive_gauge()
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            SCAN_STATS.record_degradation(
                "worker_rejoin", worker=idx,
                warmed_plans=len(service.plan_cache),
            )
            return worker

    def prewarm(self) -> None:
        """Cross-transfer every alive worker's hot plans to every other
        worker. After a prewarm, ANY survivor already holds a dead
        worker's plans, so failover re-dispatch skips the plan build —
        the fleet analogue of warming a cache tier before admitting
        traffic. (On ``distinct_devices`` fleets the migrated programs
        still re-lower once per new device; see :class:`FleetConfig`.)"""
        with self._lock:
            alive = [w for w in self._workers.values() if w.alive]
        for worker in alive:
            self._warm(
                worker.service,
                [d.service for d in alive if d is not worker],
            )

    def _warm(self, service: VerificationService, donors) -> None:
        """Plan-cache warmup/transfer: import each donor's hottest
        ``warm_plans`` entries (LRU recency — the registry's hot-plan
        feed) plus the analyzer-family admission cache, so repeat
        tenants landing on the joiner go straight to cached programs."""
        for donor in donors:
            try:
                plans, families = donor.warm_state(self.config.warm_plans)
                service.warm_from(plans, families)
            # deequ-lint: ignore[bare-except] -- best-effort warmup over a possibly-concurrently-mutating donor cache: a failed transfer leaves the joiner cold, never broken
            except Exception:  # noqa: BLE001
                continue

    #: heat-ledger bound: past this many distinct routing digests the
    #: coldest half is dropped (the hot-plan feed only ever reads the
    #: top ``warm_plans``; an unbounded dict would leak one entry per
    #: distinct (schema, analyzers, rows) tuple for the fleet's life)
    _HEAT_CAP = 1024

    def _record_heat(self, digest: str) -> None:
        """Caller holds ``self._lock``."""
        self._heat[digest] = self._heat.get(digest, 0) + 1
        if len(self._heat) > self._HEAT_CAP:
            keep = sorted(
                self._heat.items(), key=lambda kv: kv[1], reverse=True
            )[: self._HEAT_CAP // 2]
            self._heat = dict(keep)

    # -- submission ------------------------------------------------------

    def route(self, data, checks: Sequence = (),
              required_analyzers: Sequence = ()) -> Optional[int]:
        """The worker id a submission would land on (tests/bench use
        this to script deterministic deaths)."""
        analyzers = list(required_analyzers)
        for check in checks:
            analyzers.extend(check.required_analyzers())
        return self._router.place(route_digest(data, analyzers))

    def submit(
        self,
        data,
        checks: Sequence = (),
        required_analyzers: Sequence = (),
        tenant=None,
        run_policy=None,
        slo=None,
    ):
        """Enqueue one suite on its placed worker; returns the future.
        The tenant's budget (``run_policy`` or the fleet default) is
        armed HERE — queue wait, execution, and any failover re-dispatch
        all draw on the one ledger. ``slo`` (serve/admission.Slo) is
        resolved here too: its absolute deadline stamps ONCE, at fleet
        acceptance, and follows the request across failover.

        Overload spill: if the placed worker refuses admission typed
        (``ServiceOverloadedException`` family), the submit walks the
        ring clockwise (:meth:`ConsistentHashRouter.walk`) and offers
        the request to each remaining worker once — one hot worker
        (a flood tenant's home) must not turn away traffic the rest of
        the fleet has headroom for. Only when EVERY alive worker
        refuses does the placed worker's typed refusal (carrying its
        ``retry_after_s``) propagate to the caller."""
        analyzers = list(required_analyzers)
        for check in checks:
            analyzers.extend(check.required_analyzers())
        digest = route_digest(data, analyzers)
        policy = (
            run_policy if run_policy is not None
            else self.config.run_policy
        )
        budget = policy.arm() if policy is not None else None
        slo = resolve_slo(slo)
        with self._failover_lock:
            with self._lock:
                if self._closed:
                    raise ServiceClosedException(
                        "submit on a stopped VerificationFleet"
                    )
                self._record_heat(digest)
            future = None
            refusal: Optional[ServiceOverloadedException] = None
            for wid in self._router.walk(digest):
                with self._lock:
                    worker = self._workers.get(wid)
                if worker is None or not worker.alive:
                    continue
                try:
                    future = worker.service.submit(
                        data,
                        checks=checks,
                        required_analyzers=required_analyzers,
                        tenant=tenant,
                        run_policy=(
                            _PreArmedPolicy(budget)
                            if budget is not None else None
                        ),
                        slo=slo,
                    )
                    break
                except ServiceOverloadedException as e:
                    # typed admission refusal: remember the PLACED
                    # worker's refusal (its retry_after reflects where
                    # the tenant's locality lives) and spill clockwise
                    if refusal is None:
                        refusal = e
                    continue
                except ServiceClosedException:
                    # the placed worker's service died between placement
                    # and enqueue (thread crash not yet declared):
                    # retire it — its ring arcs leave with it — and
                    # keep walking the survivors (reentrant lock)
                    self._handle_loss(wid, WorkerLostException(
                        f"worker {wid} service closed at submit",
                        worker_ids=(wid,),
                    ))
            if future is None:
                if refusal is not None:
                    raise refusal
                raise ServiceClosedException(
                    "no alive workers in the fleet (all lost; "
                    "rejoin_worker or restart)"
                )
            asg = _Assignment(
                data=data,
                checks=tuple(checks),
                required_analyzers=tuple(required_analyzers),
                tenant=tenant,
                budget=budget,
                digest=digest,
                worker=worker.idx,
                slo=slo,
                deadline_at=(
                    future.submitted_at + slo.deadline_seconds
                    if slo.deadline_seconds is not None else None
                ),
            )
            with self._lock:
                self._assignments[future] = asg
            if self._ledger is not None:
                # accept-time durability: the frame fsyncs BEFORE the
                # caller ever holds the future, so a coordinator crash
                # at any later instant can still replay this request
                asg.accept_id = uuid.uuid4().hex
                future.accept_id = asg.accept_id
                self._ledger.append_accept(
                    asg.accept_id,
                    tenant=tenant,
                    digest=digest,
                    slo_cls=slo.cls,
                    deadline_ms=slo.deadline_ms,
                    weight=slo.weight,
                    deadline_left_s=(
                        asg.deadline_at - time.monotonic()
                        if asg.deadline_at is not None else None
                    ),
                    work=(data, tuple(checks), tuple(required_analyzers)),
                    quarantine=self._tenant_health.snapshot(),
                )
        self._chain_done(future)
        return future

    def verify(self, data, checks: Sequence = (), **kw):
        """Synchronous convenience: submit + wait."""
        return self.submit(data, checks, **kw).result()

    def _chain_done(self, future) -> None:
        """Wrap the service's observation seam so the fleet ledger drops
        the assignment the moment its future resolves (the service's
        own histogram/trace callback still runs first) — and, when the
        durable ledger is on, appends the resolve tombstone."""
        prev = future._on_done

        def _done(f, ok, _prev=prev):
            if _prev is not None:
                _prev(f, ok)
            self._drop_assignment(f)

        future._on_done = _done
        if future.done():
            # resolved between submit and chaining: the callback already
            # fired on the unwrapped seam — clean the ledger directly
            self._drop_assignment(future)

    def _drop_assignment(self, future) -> None:
        with self._lock:
            popped = self._assignments.pop(future, None)
        if (popped is not None and popped.accept_id is not None
                and self._ledger is not None):
            try:
                self._ledger.append_resolve(popped.accept_id)
            except (OSError, ValueError):
                # a tombstone lost to a closing ledger costs one
                # redundant (first-resolution-gated) replay at resume,
                # never a lost result
                pass

    # -- failover --------------------------------------------------------

    def kill_worker(self, idx: int, reason: str = "scripted death") -> int:
        """Chaos/ops seam: simulate process death of worker ``idx`` and
        fail its accepted requests over. Returns how many requests were
        re-dispatched."""
        return self._handle_loss(
            idx,
            WorkerLostException(
                f"worker {idx} died: {reason}", worker_ids=(idx,)
            ),
        )

    def stall_worker(self, idx: int, seconds: float) -> None:
        """Chaos seam: wedge worker ``idx``'s thread for ``seconds``.
        Past ``stall_timeout`` the membership monitor declares it lost
        and failover runs; if the stall ends first, nothing happens —
        exactly a real transient stall."""
        with self._lock:
            worker = self._workers.get(idx)
        if worker is not None and worker.alive:
            worker.service.inject_stall(seconds)

    def _handle_loss(self, idx: int, cause: WorkerLostException) -> int:
        """Membership's loss callback AND kill_worker's body: retire the
        worker, then replay its unresolved assignments onto survivors
        on their original futures."""
        with self._failover_lock:
            with self._lock:
                worker = self._workers.get(idx)
                if worker is None or not worker.alive or self._closed:
                    return 0
                worker.alive = False
                self._router.remove_worker(idx)
                self.workers_lost += 1
                # keep the zombie service for fleet stop(): a stalled
                # thread may still wake and must be shut down then (its
                # late resolutions are dropped by the futures' gate)
                self._zombies.append(worker.service)
            # halt the service without joining: a stalled/dead thread
            # cannot be joined, and simulated process death must not
            # block failover behind it
            worker.service.stop(drain=False, join=False)
            self._update_alive_gauge()
            from deequ_tpu.obs.registry import FLEET_FAILOVERS
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            FLEET_FAILOVERS.inc()
            with self._lock:
                victims = [
                    (f, a) for f, a in self._assignments.items()
                    if a.worker == idx and not f.done()
                ]
            SCAN_STATS.record_degradation(
                "worker_failover", worker=idx, tenants=len(victims),
                error=str(cause),
            )
            redispatched = 0
            for future, asg in victims:
                redispatched += self._redispatch(future, asg, idx, cause)
            self.requests_redispatched += redispatched
            return redispatched

    def _redispatch(self, future, asg: _Assignment, lost_idx: int,
                    cause: WorkerLostException) -> int:
        """Replay ONE assignment onto a survivor (original future).
        A victim whose absolute deadline already passed is SHED typed
        instead (its caller gave up — replaying would resolve stale and
        burn a survivor's capacity exactly when the fleet is degraded);
        otherwise charges the tenant's budget — no free retries — and
        rejects typed when retries/survivors run out."""
        if (
            asg.deadline_at is not None
            and time.monotonic() >= asg.deadline_at
        ):
            self._shed_expired_victim(future, asg, lost_idx)
            return 0
        asg.failovers += 1
        if asg.budget is not None:
            try:
                asg.budget.charge(
                    "worker_failover", worker=lost_idx, tenant=asg.tenant,
                )
            except RunBudgetExhaustedException as exhausted:
                self._finalize_budget_exhausted(future, asg, exhausted)
                return 0
        with self._lock:
            wid = self._router.place(asg.digest)
            target = self._workers.get(wid) if wid is not None else None
        if target is None or asg.failovers > self.config.failover_retries:
            future._reject(WorkerLostException(
                f"request for tenant {asg.tenant!r} lost worker "
                f"{lost_idx} and "
                + ("no survivor remains"
                   if target is None
                   else f"exhausted failover_retries="
                        f"{self.config.failover_retries}"),
                worker_ids=cause.worker_ids,
            ))
            return 0
        req = ServeRequest(
            data=asg.data,
            checks=asg.checks,
            required_analyzers=asg.required_analyzers,
            tenant=asg.tenant,
            run_policy=(
                _PreArmedPolicy(asg.budget)
                if asg.budget is not None else None
            ),
            future=future,
            # the ORIGINAL deadline rides along: queue wait accrues
            # across the failover instead of resetting, so the adopting
            # worker's fair queue still sheds it if it expires there
            slo=asg.slo,
            deadline_at=asg.deadline_at,
        )
        try:
            target.service.resume([req])
        except ServiceClosedException as e:
            # the survivor died between placement and resume (cascading
            # loss): its own loss handling will replay this assignment
            # again if the monitor catches it first; otherwise reject
            # typed rather than strand the future
            future._reject(WorkerLostException(
                f"failover target worker {target.idx} already closed: {e}",
                worker_ids=(lost_idx, target.idx),
            ))
            return 0
        asg.worker = target.idx
        self._chain_done(future)  # resume() rebound the observation seam
        return 1

    def _shed_expired_victim(self, future, asg: _Assignment,
                             lost_idx: int) -> None:
        """Shed one deadline-expired failover victim typed, exactly
        once, on its original future (a shed IS a resolution — chaos
        oracles 8/9 count it), charging the tenant's ledger kind
        ``deadline_shed`` with exhaustion swallowed (the shed is
        already the terminal outcome)."""
        from deequ_tpu.obs.registry import SERVE_SHED_BY_CLASS
        from deequ_tpu.ops.scan_engine import SCAN_STATS
        from deequ_tpu.resilience.governance import try_charge

        cls = asg.slo.cls if asg.slo is not None else "standard"
        waited = time.monotonic() - future.submitted_at
        SCAN_STATS.record_degradation(
            "deadline_shed", tenant=asg.tenant, slo_class=cls,
            worker=lost_idx, at="failover",
            waited_s=round(waited, 4),
        )
        SERVE_SHED_BY_CLASS[cls].inc()
        try_charge(
            asg.budget, "deadline_shed", tenant=asg.tenant,
            worker=lost_idx,
        )
        future._reject(DeadlineExceededException(
            f"request for tenant {asg.tenant!r} lost worker {lost_idx} "
            f"after its {cls!r} SLO deadline "
            f"({asg.slo.deadline_ms:g} ms) already passed — shed at "
            "failover instead of replayed stale",
            tenant=asg.tenant, slo_class=cls,
            deadline_ms=asg.slo.deadline_ms, waited_s=waited,
        ))

    def _finalize_budget_exhausted(self, future, asg: _Assignment,
                                   exhausted: RunBudgetExhaustedException
                                   ) -> None:
        """A failover charge exhausted the tenant's budget: degrade this
        one request (typed failure metrics + ledger) or reject typed —
        the single-service exhaustion semantics, applied at the fleet
        seam."""
        from deequ_tpu.analyzers.runner import AnalyzerContext
        from deequ_tpu.ops.scan_engine import SCAN_STATS
        from deequ_tpu.verification import VerificationSuite, _dedup_analyzers

        SCAN_STATS.record_degradation(
            "tenant_budget_exhausted", tenant=asg.tenant,
            reason=exhausted.reason,
        )
        if self._tenant_health.record_failure(asg.tenant):
            SCAN_STATS.record_degradation(
                "tenant_quarantine", tenant=asg.tenant,
                consecutive=self._tenant_health.failures.get(asg.tenant),
            )
        if not exhausted.degraded:
            future._reject(exhausted)
            return
        analyzers = list(asg.required_analyzers)
        for check in asg.checks:
            analyzers.extend(check.required_analyzers())
        ctx = AnalyzerContext({
            a: a.to_failure_metric(exhausted)
            for a in _dedup_analyzers(analyzers)
        })
        result = VerificationSuite._evaluate(asg.checks, ctx)
        result.scan_stats = {"coalesced": False, "failed": str(exhausted)}
        result.run_budget = asg.budget.snapshot()
        future._resolve(result)

    # -- coordinator resume ----------------------------------------------

    def _replay_ledger(self, resume_futures: Dict[str, Any]) -> None:
        """Kill-and-resume for the IN-PROCESS fleet: replay every
        accepted-but-untombstoned ledger record (the futures a crashed
        coordinator orphaned) through the workers' ``resume`` seam —
        original futures where the driver survived, fresh ones
        otherwise. Deadlines resume minus the wall-clock spent dead;
        expired victims shed typed instead of replaying stale."""
        if self._ledger is None:
            return
        outstanding = self._ledger.outstanding()
        if not outstanding:
            return
        from deequ_tpu.envcfg import env_value
        from deequ_tpu.ops.scan_engine import SCAN_STATS
        from deequ_tpu.serve.ledger import RequestLedger

        if not env_value("DEEQU_TPU_COORD_RESUME"):
            SCAN_STATS.record_degradation(
                "coord_resume_disabled", outstanding=len(outstanding),
            )
            return
        snap = self._ledger.latest_quarantine()
        if snap is not None:
            self._tenant_health.restore(snap)
        now_wall = time.time()
        with self._failover_lock:
            for accept_id, rec in outstanding.items():
                try:
                    tenant = RequestLedger.load_tenant(rec)
                    data, checks, required = RequestLedger.load_work(rec)
                except CorruptStateException as e:
                    SCAN_STATS.record_degradation(
                        "ledger_undecodable_record", id=accept_id,
                        error=str(e),
                    )
                    continue
                future = resume_futures.get(accept_id)
                if future is None:
                    future = VerificationFuture(tenant)
                future.accept_id = accept_id
                slo_rec = rec.get("slo") or {}
                slo = Slo(
                    deadline_ms=slo_rec.get("deadline_ms"),
                    weight=float(slo_rec.get("weight", 1.0)),
                    cls=str(slo_rec.get("cls", "standard")),
                )
                left = None
                if rec.get("deadline_left_s") is not None:
                    dead_for = now_wall - float(
                        rec.get("accepted_wall", now_wall)
                    )
                    left = float(rec["deadline_left_s"]) - max(
                        dead_for, 0.0
                    )
                analyzers = list(required)
                for check in checks:
                    analyzers.extend(check.required_analyzers())
                digest = rec.get("digest") or route_digest(data, analyzers)
                asg = _Assignment(
                    data=data,
                    checks=tuple(checks),
                    required_analyzers=tuple(required),
                    tenant=tenant,
                    budget=None,
                    digest=digest,
                    worker=-1,
                    slo=slo,
                    deadline_at=(
                        time.monotonic() + left
                        if left is not None else None
                    ),
                    accept_id=accept_id,
                )
                with self._lock:
                    self._assignments[future] = asg
                    self._record_heat(digest)
                self._chain_done(future)
                self.resumed[accept_id] = future
                if left is not None and left <= 0:
                    self._shed_expired_victim(future, asg, -1)
                    continue
                with self._lock:
                    wid = self._router.place(digest)
                    target = (
                        self._workers.get(wid) if wid is not None else None
                    )
                if target is None:
                    future._reject(WorkerLostException(
                        "resume replay found no alive workers",
                        worker_ids=(),
                    ))
                    continue
                req = ServeRequest(
                    data=data,
                    checks=tuple(checks),
                    required_analyzers=tuple(required),
                    tenant=tenant,
                    run_policy=None,
                    future=future,
                    slo=slo,
                    deadline_at=asg.deadline_at,
                )
                asg.worker = target.idx
                try:
                    target.service.resume([req])
                except ServiceClosedException as e:
                    future._reject(WorkerLostException(
                        f"resume replay target worker {target.idx} "
                        f"already closed: {e}",
                        worker_ids=(target.idx,),
                    ))
                    continue
                self._chain_done(future)  # resume() rebound the seam
        SCAN_STATS.record_degradation(
            "coord_resume", replayed=len(self.resumed),
        )

    # -- lifecycle -------------------------------------------------------

    def flush(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            services = [
                w.service for w in self._workers.values() if w.alive
            ]
        for service in services:
            service.flush(timeout)

    def stop(self, drain: bool = True) -> List:
        """Stop the whole fleet. ``drain=True`` serves every queued
        request first; returns the futures still unresolved (empty
        after a drain)."""
        self.membership.stop()
        with self._lock:
            self._closed = True
            services = [
                w.service for w in self._workers.values() if w.alive
            ]
            zombies = list(self._zombies)
        for service in services:
            service.stop(drain=drain)
        for zombie in zombies:
            zombie.stop(drain=False, join=False)
        if self._ledger is not None:
            self._ledger.close()
        self._update_alive_gauge(0)
        with self._lock:
            leftovers = [
                f for f in self._assignments if not f.done()
            ]
        return leftovers

    def __enter__(self) -> "VerificationFleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    # -- introspection ---------------------------------------------------

    def _update_alive_gauge(self, value: Optional[int] = None) -> None:
        from deequ_tpu.obs.registry import FLEET_WORKERS_ALIVE

        FLEET_WORKERS_ALIVE.set(
            value if value is not None else len(self._alive_ids())
        )

    def _section(self) -> dict:
        """The registry's ``fleet`` section (see ``_fleet_section``)."""
        from deequ_tpu.obs.registry import FLEET_FAILOVERS

        with self._lock:
            workers = {
                str(i): {
                    "alive": w.alive,
                    "queue_depth": w.queue_depth() if w.alive else 0,
                    "suites_served": w.service.suites_served,
                    # per-worker ladder level: the global gauge is
                    # last-writer-wins across workers, this is exact
                    "brownout_level": w.service._brownout.level,
                }
                for i, w in self._workers.items()
            }
            hot = sorted(
                self._heat.items(), key=lambda kv: kv[1], reverse=True
            )[:self.config.warm_plans]
            pending = sum(
                1 for f in self._assignments if not f.done()
            )
        return {
            "workers_alive": sum(
                1 for w in workers.values() if w["alive"]
            ),
            "workers_lost": self.workers_lost,
            "failovers": FLEET_FAILOVERS.value,
            "requests_redispatched": self.requests_redispatched,
            "requests_outstanding": pending,
            "workers": workers,
            "hot_plans": [
                {"digest": d[:12], "heat": n} for d, n in hot
            ],
        }

    def stats(self) -> dict:
        return self._section()
