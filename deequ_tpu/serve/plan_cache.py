"""The compiled-plan cache — repeat tenants skip tracing entirely.

A small verification suite costs microseconds of device compute but a
fresh submission pays: ScanOp construction, kernel-variant planning, a
plan-lint jaxpr trace, a program trace + XLA compile, and the dispatch +
fetch round trip. For the config-1 serving shape those fixed costs ARE
the latency. This module caches everything above the dispatch:

- :class:`PlanKey` — the suite fingerprint: needed-column schema
  signature, the DEDUPLICATED analyzer tuple (analyzers are hashable
  value objects whose identity includes their ``where`` predicates — the
  predicate fingerprint rides here), the packer LAYOUT signature (which
  planes each column routes over — data-dependent: the same schema with
  out-of-range values routes differently and must not share a program),
  and the member row count (the packed chunk width is static shape).
- :class:`ServePlan` — one cached entry: the built exec ops + extract
  plan, the shared packer layout, admission verdict (coalescable or the
  reason not), and the traced-program table keyed by (tenant-axis
  bucket, LUT signature) — the LUT signature is the dictionary-derived
  argument shapes, so a batch whose stacked LUTs grew re-traces while
  dictionary CONTENT rides as runtime arguments (the lut_cache design).
- :class:`PlanCache` — bounded LRU over ServePlans.

``ScanStats.plan_cache_hits`` counts suites served from a fully cached
plan — the batch found the traced program for its (tenant bucket, LUT
signature) and ran with zero op builds, zero traces, zero compiles,
zero plan-lint traces; ``plan_cache_misses`` counts suites whose batch
had to build/trace any of it (the executor accounts both,
suite-weighted). The repeat-tenant contract (bench
``measure_serving_load`` + tier-1 ``serve`` suite): a second identical
suite is a hit and adds zero ``plan_lint_traces`` / ``programs_built``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from deequ_tpu.ops.scan_engine import _BoundedLRU


@dataclass(frozen=True)
class PlanKey:
    """Suite fingerprint (see module doc). ``schema_sig`` is
    ((column, dtype), ...) over the NEEDED columns sorted by name;
    ``analyzer_sig`` the deduplicated analyzer tuple in evaluation order
    (value objects — parameters and ``where`` predicates included);
    ``layout_sig`` the packer plane routing; ``chunk`` the member row
    count every coalesced slice of this plan shares."""

    schema_sig: Tuple
    analyzer_sig: Tuple
    layout_sig: Tuple
    chunk: int


@dataclass
class ServePlan:
    """One cached suite plan (built once per PlanKey; see module doc)."""

    key: PlanKey
    #: the dedup'd analyzers in evaluation order (the runner's order)
    analyzers: Tuple
    #: scannable analyzers (op construction succeeded), their exec ops
    #: after kll coalescing, and plan[i] = (exec_idx, extractor|None)
    scannable: Tuple
    exec_ops: Tuple
    extract_plan: Tuple
    #: op-construction failures {analyzer: exception} — deterministic
    #: per plan, replayed as failure metrics for every member
    op_failures: Dict
    #: schema-precondition failures {analyzer: exception} (the runner's
    #: step-2 partition) — schema-determined, so identical for every
    #: member sharing this plan's schema signature
    precondition_failures: Dict = field(default_factory=dict)
    #: the shared packer layout dict every member packs against
    layout: Dict = field(default_factory=dict)
    #: needed column names (sorted)
    needed: Tuple = ()
    #: a metadata-only unpack view (_ChunkPacker.unpack_view) captured at
    #: build time — what the traced program closes over
    unpack_view: Any = None
    #: traced vmapped programs: (k_bucket, lut_sig) -> (vstep, shapes)
    programs: Dict = field(default_factory=dict)
    #: False + reason when members of this plan cannot coalesce (own-pass
    #: or grouping analyzers, dictionary-baked ops, streaming/oversized
    #: tables) — the service then runs them per-tenant on the serial path
    coalescable: bool = True
    why_not: str = ""
    #: True when the REASON is intrinsic to the analyzer set (grouping /
    #: own-pass members, dictionary-baked or uncacheable ops) rather
    #: than to the table it was built from — only class-level verdicts
    #: may be cached per analyzer signature (the service's _families);
    #: a table-level verdict (missing column, empty/oversized table,
    #: op-build failure) must never poison other tenants' admissions
    serial_class: bool = False

    def program_for(self, k_bucket: int, lut_sig: Tuple):
        return self.programs.get((k_bucket, lut_sig))

    def put_program(self, k_bucket: int, lut_sig: Tuple, prog) -> None:
        self.programs[(k_bucket, lut_sig)] = prog


class PlanCache:
    """Bounded LRU of ServePlans (the serve layer's one entry point to
    plan reuse). ``get`` / ``put`` mirror the hit/miss ledger into
    ``ScanStats`` — a hit here is the "skip tracing, compilation and
    plan-lint entirely" fast path ONLY if the program table also has the
    batch's (K, luts) program; the executor accounts that split."""

    def __init__(self, cap: int = 256):
        self._lru = _BoundedLRU(cap)

    def get(self, key: PlanKey) -> Optional[ServePlan]:
        return self._lru.get(key)

    def put(self, plan: ServePlan) -> None:
        self._lru.put(plan.key, plan)

    def __len__(self) -> int:
        return len(self._lru)

    def entries(self) -> List[ServePlan]:
        """The cached plans in LRU order (most recently used LAST) — the
        fleet's hot-plan feed for worker-join warmup."""
        return list(self._lru._d.values())

    def clear(self) -> None:
        self._lru.clear()


@dataclass(frozen=True)
class SubPlanKey:
    """Cross-suite SUB-PLAN identity (round 19, the plan optimizer):
    the traced packed program's identity BELOW the exact :class:`PlanKey`.

    Two tenants whose analyzer sets are permutations (or whose suites
    dedupe to the same op set) get DISTINCT PlanKeys — ``analyzer_sig``
    preserves submission order, which the result path needs — but trace
    to the same program once ops are put in canonical order. This key
    names that shared program: the canonical (sorted by op identity)
    exec-op tuple, the schema/layout signatures, the chunk width, the
    tenant bucket + LUT signature (the traced shapes), and every kernel
    variant that steers codegen. ``lint.plan_lint.check_subplan_key``
    (the ``plan-fusion-refetch`` rule's sharing half) rejects any key
    that drops an identity component."""

    ops_sig: Tuple
    schema_sig: Tuple
    layout_sig: Tuple
    chunk: int
    k_bucket: int
    lut_sig: Tuple
    variant: str
    hist_variant: str
    ingest_variant: str


class SubPlanCache:
    """Bounded LRU of traced packed programs keyed by
    :class:`SubPlanKey` — lock-serialized (the serving workers share the
    process singleton, like the PR-14 census counters). Stored entries
    are (single_flat, vstep, shapes, recipes) in CANONICAL op order;
    each borrowing plan keeps its own exec-order permutation alongside
    its ``ServePlan.programs`` entry."""

    def __init__(self, cap: int = 128):
        self._lru = _BoundedLRU(cap)
        self._lock = threading.Lock()

    def get(self, key: SubPlanKey):
        with self._lock:
            return self._lru.get(key)

    def put(self, key: SubPlanKey, prog) -> None:
        with self._lock:
            self._lru.put(key, prog)

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()


#: the process-wide cross-suite program cache (serve/executor.py reads
#: it on every exact-PlanKey program miss before paying a trace)
SUBPLAN_CACHE = SubPlanCache()


def canonical_op_order(exec_ops: Tuple) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """The canonical op ordering shared programs are traced in: exec-op
    indices sorted by the op's cache-key identity (analyzers are value
    objects; their string form is a stable total order for any one op
    set). Returns ``(canon, perm)`` — ``canon[pos]`` is the exec index
    at canonical position ``pos``, and ``perm[exec_idx]`` the canonical
    position of exec op ``exec_idx`` (the result-path inverse)."""
    canon = tuple(
        sorted(range(len(exec_ops)), key=lambda i: str(exec_ops[i].cache_key))
    )
    perm = [0] * len(canon)
    for pos, i in enumerate(canon):
        perm[i] = pos
    return canon, tuple(perm)


def subplan_key(
    plan: ServePlan,
    canon: Tuple[int, ...],
    k_bucket: int,
    lut_sig: Tuple,
    variant: str,
    hist_variant: str,
    ingest_variant: str,
) -> SubPlanKey:
    """Build the :class:`SubPlanKey` for ``plan``'s packed program at
    this (bucket, LUT) shape. ``ops_sig`` carries the analyzer value
    objects themselves (full identity: parameters and ``where``
    predicates included), in canonical order."""
    return SubPlanKey(
        ops_sig=tuple(plan.exec_ops[i].cache_key for i in canon),
        schema_sig=plan.key.schema_sig,
        layout_sig=layout_signature(plan.layout),
        chunk=plan.key.chunk,
        k_bucket=k_bucket,
        lut_sig=lut_sig,
        variant=variant,
        hist_variant=hist_variant,
        ingest_variant=ingest_variant,
    )


def schema_signature(table, needed) -> Tuple:
    """((column, dtype), ...) over ``needed`` (sorted) — the schema half
    of the plan fingerprint."""
    return tuple((n, table[n].dtype) for n in needed)


def layout_signature(layout: Dict) -> Tuple:
    return tuple(sorted((k, tuple(v)) for k, v in layout.items()))


def build_serve_plan(table, analyzers: List, key_hint=None) -> ServePlan:
    """Build the ServePlan for ``analyzers`` over ``table``'s shape — op
    construction (failure-isolated per analyzer, the runner's rule), kll
    coalescing, layout derivation, and coalescability admission. The
    hit/miss ledger is accounted by the executor (program granularity),
    not here."""
    from deequ_tpu.analyzers.base import (
        ScanShareableAnalyzer,
        find_first_failing,
    )
    from deequ_tpu.analyzers.runner import AnalysisRunner, _is_grouping_shared
    from deequ_tpu.ops.scan_engine import _ChunkPacker, _auto_chunk_rows

    analyzers = tuple(analyzers)
    # precondition partition first (the runner's step 2): schema
    # violations become failure metrics per member, never scan ops
    precondition_failures: Dict = {}
    passed = []
    for a in analyzers:
        exc = find_first_failing(table.schema, a.preconditions())
        if exc is None:
            passed.append(a)
        else:
            precondition_failures[a] = exc
    scanning = [
        a for a in passed
        if isinstance(a, ScanShareableAnalyzer) and not _is_grouping_shared(a)
    ]
    non_scan = [a for a in passed if a not in scanning]

    coalescable = True
    why = ""
    serial_class = False
    if non_scan:
        # grouping/own-pass members need their own passes (frequency
        # folds, spill budgets) — the standard runner handles them; a
        # suite containing any is served per-tenant. CLASS-level: true
        # for every table this analyzer set ever meets
        coalescable = False
        serial_class = True
        why = f"non-scan-shareable analyzers: {[str(a) for a in non_scan]}"

    ops, scannable, op_failures = AnalysisRunner._build_scan_ops(
        table, scanning
    )
    exec_ops: Tuple = ()
    extract_plan: Tuple = ()
    layout: Dict = {}
    needed: Tuple = ()
    view = None
    if scannable:
        exec_list, plan_list = AnalysisRunner._coalesce_scan_ops(ops)
        exec_ops = tuple(exec_list)
        extract_plan = tuple(plan_list)
        if any(op.dictionary_baked for op in exec_ops):
            # trace-time dictionary constants bake the FIRST table's
            # values into the program — never reusable across tenants
            # (class-level: the predicate, not the table, is baked)
            coalescable = False
            serial_class = True
            why = why or "dictionary-baked ops (trace-time constants)"
        if any(op.cache_key is None for op in exec_ops):
            coalescable = False
            serial_class = True
            why = why or "uncacheable ops (no program identity)"
        needed = tuple(sorted({c for op in exec_ops for c in op.columns}))
        cols = {n: table[n] for n in needed}
        n_rows = int(table.num_rows)
        if n_rows == 0:
            coalescable = False
            why = why or "empty table"
        elif n_rows > _auto_chunk_rows(cols):
            # multi-chunk members would change the serial path's
            # reduction association (the group path's single-chunk
            # guard) — big tables go through the ordinary engine
            coalescable = False
            why = why or "table exceeds the single-chunk coalesce bound"
        if n_rows > 0:
            # same encode routing as the serial baseline (run_scan
            # resolves the same switch): an encoded member must ride the
            # code plane coalesced exactly as it would serially, or the
            # bit-identity contract compares different compute paths
            from deequ_tpu.ops.scan_plan import encoded_ingest_enabled

            packer = _ChunkPacker(
                cols, max(n_rows, 1),
                encode_ingest=encoded_ingest_enabled(None),
            )
            layout = packer.layout()
            view = packer.unpack_view()
    elif scanning:
        # every scan op failed to build: nothing to coalesce
        coalescable = False
        why = why or "no scannable ops"

    return ServePlan(
        key=key_hint,
        analyzers=analyzers,
        scannable=tuple(scannable),
        exec_ops=exec_ops,
        extract_plan=extract_plan,
        op_failures=dict(op_failures),
        precondition_failures=precondition_failures,
        layout=layout,
        needed=needed,
        unpack_view=view,
        coalescable=coalescable and bool(scannable),
        why_not=why,
        serial_class=serial_class,
    )
