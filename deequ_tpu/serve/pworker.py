"""Process-fleet worker: one VerificationService behind a frame
transport (``python -m deequ_tpu.serve.pworker --fd N --idx I``).

The protocol loop here is the ONLY worker implementation: the
coordinator (:mod:`deequ_tpu.serve.pfleet`) runs it in a spawned
process over a :class:`~deequ_tpu.serve.transport.SocketTransport`
(production shape: process isolation, one host/chip per worker) or in
a thread over a :class:`~deequ_tpu.serve.transport.LoopbackTransport`
(deterministic tests, single-process deployments) — the frames, acks,
typed refusals, and quarantine merges are identical in both.

Protocol (coordinator -> worker):

- ``submit`` — one suite: ``work_blob`` carries (data, checks,
  required_analyzers); ``slo`` the class/deadline/weight; an optional
  ``quarantine_blob`` merges the coordinator's fleet-wide quarantine
  view in BEFORE admission, so a tenant poisoned on another worker is
  serial-only here too. Answered by ``accept`` or a typed ``refuse``.
- ``warm`` — plan FINGERPRINTS (schema + row count + pickled
  analyzers). Traced programs don't serialize; the joiner replays each
  fingerprint through ``build_serve_plan`` over a synthetic table of
  the same shape, so its own cache traces once — warm join without
  shipping compiled artifacts.
- ``ping`` -> ``pong`` (service-thread heartbeat age + queue depth +
  quarantine snapshot): the membership probe's transport leg.
- ``stop`` — drain (or not) and exit the loop.

Worker -> coordinator: ``hello`` at ready, ``accept``/``refuse`` per
submit, ``result`` per resolution (success or typed failure, plus the
worker's quarantine snapshot so verdicts flow back), ``pong``,
``warm_ack``, ``stopped``.

Backpressure stays TYPED across the wire: a
:class:`~deequ_tpu.exceptions.ServiceOverloadedException` family
refusal serializes its structured fields (``retry_after_s``,
``queue_depth``, ``slo_class``, admission ``reason``) — not a pickled
exception — and the coordinator reconstructs the same type, so the
PR-15 admission semantics survive serialization byte-for-byte where it
matters: in the fields callers schedule retries from.
"""

from __future__ import annotations

import argparse
import os
import socket
import time
from typing import Any, Dict, Optional

from deequ_tpu.exceptions import (
    ServiceClosedException,
    ServiceOverloadedException,
    StaleEpochException,
)
from deequ_tpu.serve.transport import (
    Transport,
    TransportClosedError,
    dump_blob,
    load_blob,
)


def _column_facts(col) -> "tuple[bool, bool]":
    """(has_nulls, fits_i32) — the VALUE facts the chunk packer routes
    on (null-free columns ship no mask row; narrow integrals ride the
    i32 buffer). The fingerprint must carry them or the warm replay's
    synthetic table lands in a different layout group and mints a key
    no real tenant ever matches."""
    import numpy as np

    codes = getattr(col, "codes", None)
    if codes is not None:
        # deequ-lint: ignore[host-fetch] -- fingerprinting reads the Column's host numpy codes, never a device array
        return bool((np.asarray(codes) < 0).any()), True
    mask = getattr(col, "mask", None)
    # deequ-lint: ignore[host-fetch] -- the Column's validity mask is a host numpy array by construction
    has_nulls = mask is not None and not bool(np.asarray(mask).all())
    fits_i32 = True
    values = getattr(col, "values", None)
    if values is not None:
        # deequ-lint: ignore[host-fetch] -- Column.values is the host-side staging array, never a device array
        arr = np.asarray(values)
        if arr.size and np.issubdtype(arr.dtype, np.number):
            finite = arr[np.isfinite(arr)]
            if finite.size:
                fits_i32 = bool(np.abs(finite).max() < 2**31 - 1)
    return has_nulls, fits_i32


def plan_fingerprint(data, analyzers) -> Optional[dict]:
    """The shippable identity of a plan: schema (with the
    layout-routing value facts) + rows + analyzers. None for sources
    that don't expose a columnar schema (count-less streams serve on
    the serial path — nothing to warm)."""
    try:
        schema = []
        for name in data.column_names:
            col = data[name]
            has_nulls, fits_i32 = _column_facts(col)
            schema.append([name, col.dtype.name, has_nulls, fits_i32])
        rows = int(data.num_rows or 0)
    except (AttributeError, TypeError):
        return None
    if rows <= 0:
        return None
    return {
        "schema": schema,
        "rows": rows,
        "analyzers_blob": dump_blob(tuple(analyzers)),
    }


def _synthetic_table(schema, rows: int):
    """A table matching a fingerprint's shape AND layout routing —
    what the warm replay builds its plan (and first trace) against.
    Values are inert placeholders except for the two packer-visible
    facts: a single null when the real column had any, and a value
    outside int32 when the real column's did not fit."""
    import numpy as np

    from deequ_tpu.data.table import Column, ColumnarTable, DType

    columns = []
    for entry in schema:
        name, dtype_name = entry[0], entry[1]
        has_nulls = bool(entry[2]) if len(entry) > 2 else False
        fits_i32 = bool(entry[3]) if len(entry) > 3 else True
        dtype = DType[dtype_name]
        if dtype == DType.STRING:
            codes = np.zeros(rows, dtype=np.int32)
            if has_nulls:
                codes[0] = -1
            # deequ-lint: ignore[host-fetch] -- builds a fresh host numpy dictionary for the synthetic table
            dictionary = np.asarray(["a"], dtype=object)
            columns.append(Column(
                name, dtype, codes=codes, dictionary=dictionary,
            ))
        else:
            values = np.zeros(rows)
            if not fits_i32:
                values[:] = float(2**33)
            mask = np.ones(rows, dtype=bool)
            if has_nulls:
                mask[0] = False
            columns.append(Column(
                name, dtype, values=values, mask=mask,
            ))
    return ColumnarTable(columns)


def replay_fingerprints(service, plans) -> int:
    """Warm a service's plan cache from shipped fingerprints: build
    each plan over a synthetic same-shape table and mint the same
    :class:`~deequ_tpu.serve.plan_cache.PlanKey` the service would
    (the PlanKey replay — this cache traces once, on arrival, instead
    of per first tenant). Best-effort per entry: a fingerprint that no
    longer builds (or is serial-class) leaves the joiner cold for that
    one plan, never broken."""
    from deequ_tpu.serve.plan_cache import (
        PlanKey,
        build_serve_plan,
        layout_signature,
        schema_signature,
    )

    warmed = 0
    for fp in plans:
        try:
            analyzers = load_blob(fp["analyzers_blob"], "warm fingerprint")
            rows = int(fp["rows"])
            table = _synthetic_table(fp["schema"], rows)
            plan = build_serve_plan(table, list(analyzers))
            if (
                not plan.coalescable
                or plan.serial_class
                or plan.op_failures
                or plan.precondition_failures
            ):
                continue  # serial-path plans have no cache identity
            plan.key = PlanKey(
                schema_sig=schema_signature(table, plan.needed),
                analyzer_sig=tuple(analyzers),
                layout_sig=layout_signature(plan.layout),
                chunk=rows,
            )
            service.plan_cache.put(plan)
            warmed += 1
        # deequ-lint: ignore[bare-except] -- best-effort warm replay: a stale/undecodable fingerprint leaves the joiner cold for that one plan, never broken
        except Exception:  # noqa: BLE001
            continue
    return warmed


def _refusal_fields(e) -> dict:
    """Decompose a typed refusal (the ServiceOverloadedException family
    OR a fencing StaleEpochException) into structured wire fields — the
    coordinator reconstructs the same type from them."""
    return {
        "cls": type(e).__name__,
        "message": str(e),
        "queue_depth": getattr(e, "queue_depth", None),
        "retry_after_s": getattr(e, "retry_after_s", None),
        "slo_class": getattr(e, "slo_class", None),
        "reason": getattr(e, "reason", None),
        "stale_epoch": getattr(e, "stale_epoch", None),
        "current_epoch": getattr(e, "current_epoch", None),
        "holder": getattr(e, "holder", None),
    }


class WorkerLoop:
    """The protocol loop over one transport endpoint (see module doc)."""

    def __init__(self, transport: Transport, idx: int = 0,
                 worker_knobs: Optional[Dict[str, Any]] = None,
                 service=None):
        from deequ_tpu.parallel.mesh import use_mesh
        from deequ_tpu.serve.service import ServeConfig, VerificationService

        self.transport = transport
        self.idx = int(idx)
        if service is not None:
            self.service = service
        else:
            knobs = dict(worker_knobs or {})
            # the worker IS one host/chip: construct under the
            # single-device view (the fleet's _spawn_service rule)
            with use_mesh(None):
                self.service = VerificationService(
                    config=ServeConfig(**knobs) if knobs else ServeConfig(),
                    start=True,
                )
        self._stopping = False
        #: epoch fencing (serve/lease.py): the highest coordinator
        #: epoch this worker has witnessed; dispatches stamped older
        #: are refused typed before ANY side effect. 0 = unfenced.
        self._highest_epoch = 0
        #: accept_id -> the epoch that dispatched it, echoed on results
        #: so a resumed coordinator can spot zombie-epoch result frames
        self._accept_epochs: Dict[str, int] = {}

    # -- frame handlers --------------------------------------------------

    def _send(self, msg: dict) -> bool:
        try:
            self.transport.send(msg)
            return True
        except TransportClosedError:
            # the coordinator is gone: a worker with no coordinator has
            # nobody to resolve to — finish quietly, the durable ledger
            # on the coordinator side owns recovery
            self._stopping = True
            return False

    def _quarantine_blob(self) -> str:
        return dump_blob(self.service.tenant_health.snapshot())

    def _send_result(self, accept_id: str, future) -> None:
        ok = future._error is None and not future.cancelled()
        payload = future._result if ok else future._error
        self._send({
            "t": "result",
            "id": accept_id,
            "ok": bool(ok),
            "epoch": self._accept_epochs.pop(
                accept_id, self._highest_epoch
            ),
            "payload_blob": dump_blob(payload),
            "quarantine_blob": self._quarantine_blob(),
        })

    def _on_submit(self, msg: dict) -> None:
        from deequ_tpu.serve.admission import Slo

        accept_id = str(msg["id"])
        epoch = int(msg.get("epoch") or 0)
        if epoch and epoch < self._highest_epoch:
            # a fenced-out (zombie) coordinator's dispatch: refuse it
            # typed BEFORE any side effect — no quarantine restore, no
            # blob decode, no admission
            exc = StaleEpochException(
                f"dispatch from stale epoch {epoch} refused: worker "
                f"{self.idx} has seen epoch {self._highest_epoch}",
                stale_epoch=epoch,
                current_epoch=self._highest_epoch,
            )
            self._send({"t": "refuse", "id": accept_id,
                        **_refusal_fields(exc)})
            return
        if epoch:
            self._highest_epoch = epoch
            self._accept_epochs[accept_id] = epoch
        snap_blob = msg.get("quarantine_blob")
        if snap_blob:
            self.service.tenant_health.restore(
                load_blob(snap_blob, "submit quarantine snapshot")
            )
        data, checks, required_analyzers = load_blob(
            msg["work_blob"], "submit work"
        )
        tenant = load_blob(msg["tenant_blob"], "submit tenant")
        slo_raw = msg.get("slo") or {}
        deadline_left = msg.get("deadline_left_s")
        slo = Slo(
            deadline_ms=(
                max(float(deadline_left), 1e-3) * 1000.0
                if deadline_left is not None else None
            ),
            weight=float(slo_raw.get("weight", 1.0)),
            cls=str(slo_raw.get("cls", "standard")),
        )
        try:
            future = self.service.submit(
                data,
                checks=checks,
                required_analyzers=required_analyzers,
                tenant=tenant,
                slo=slo,
            )
        except ServiceOverloadedException as e:
            # typed backpressure, serialized structurally (module doc)
            self._send({"t": "refuse", "id": accept_id,
                        **_refusal_fields(e)})
            return
        except ServiceClosedException as e:
            self._send({
                "t": "refuse", "id": accept_id,
                "cls": "ServiceClosedException", "message": str(e),
            })
            return
        prev = future._on_done

        def _done(f, ok, _prev=prev, _id=accept_id):
            if _prev is not None:
                _prev(f, ok)
            self._send_result(_id, f)

        future._on_done = _done
        self._send({"t": "accept", "id": accept_id})
        if future.done():
            # resolved between submit and chaining: the service's own
            # seam already ran on the unwrapped callback — ship the
            # result directly (never re-run the observation seam)
            self._send_result(accept_id, future)

    def _on_ping(self, msg: dict) -> None:
        self._send({
            "t": "pong",
            "seq": msg.get("seq"),
            "heartbeat_age_s": max(
                time.monotonic() - self.service.heartbeat, 0.0
            ),
            "queue_depth": self.service.pending_count(),
            "quarantine_blob": self._quarantine_blob(),
        })

    def _on_warm(self, msg: dict) -> None:
        warmed = replay_fingerprints(self.service, msg.get("plans") or ())
        self._send({"t": "warm_ack", "warmed": warmed})

    def _on_stop(self, msg: dict) -> None:
        self._stopping = True
        pending = self.service.stop(drain=bool(msg.get("drain", True)))
        self._send({
            "t": "stopped",
            "pending": len(pending),
            "quarantine_blob": dump_blob(
                pending.tenant_health or
                self.service.tenant_health.snapshot()
            ),
        })

    # -- the loop --------------------------------------------------------

    def run(self) -> None:
        self._send({"t": "hello", "pid": os.getpid(), "idx": self.idx})
        handlers = {
            "submit": self._on_submit,
            "ping": self._on_ping,
            "warm": self._on_warm,
            "stop": self._on_stop,
        }
        while not self._stopping:
            try:
                msg = self.transport.recv(timeout=0.25)
            except TransportClosedError:
                # coordinator death: stop serving. Accepted-but-unsent
                # work dies with this worker BY DESIGN — the durable
                # ledger on the coordinator side replays it at resume
                break
            if msg is None:
                continue
            handler = handlers.get(str(msg.get("t")))
            if handler is None:
                self._send({
                    "t": "error",
                    "message": f"unknown frame type {msg.get('t')!r}",
                })
                continue
            handler(msg)
        if not self.service._closed:
            self.service.stop(drain=False, join=False)
        self.transport.close()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="deequ-tpu process-fleet worker (spawned by "
                    "serve/pfleet.py; not a user entry point)"
    )
    parser.add_argument("--fd", type=int, required=True,
                        help="inherited socketpair fd to the coordinator")
    parser.add_argument("--idx", type=int, default=0)
    parser.add_argument("--knobs", type=str, default=None,
                        help="JSON ServeConfig overrides")
    args = parser.parse_args(argv)
    import json

    from deequ_tpu.serve.transport import SocketTransport

    knobs = json.loads(args.knobs) if args.knobs else None
    sock = socket.socket(fileno=args.fd)
    WorkerLoop(SocketTransport(sock), idx=args.idx,
               worker_knobs=knobs).run()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
