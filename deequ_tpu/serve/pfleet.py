"""ProcessFleet — the multi-HOST fleet: process-isolated workers, a
durable request ledger, and coordinator kill-and-resume.

:class:`~deequ_tpu.serve.fleet.VerificationFleet` (PR 14) runs its
workers as threads of one process: a wedged C extension, a heap
corruption, or an OOM kill takes the whole fleet — and every accepted
future — down at once. This module is the same fleet control plane
re-seated on PROCESS boundaries (the production multi-host shape, one
worker process per host/chip):

- **Workers are processes** — each spawned as ``python -m
  deequ_tpu.serve.pworker`` over one end of a ``socketpair`` (or, with
  ``transport="loopback"``, as a thread over an in-process queue pair
  running the IDENTICAL protocol loop — same frames, acks, refusals).
  ``kill -9`` on a worker is a real SIGKILL; its loss surfaces as
  transport EOF, exactly like host death in a real fleet.
- **Membership on the check_peers seam** — the same
  :class:`~deequ_tpu.serve.membership.FleetMembership` monitor, with a
  ping/pong probe over the transport: each pong carries the worker's
  own service-thread heartbeat age, so a process that is alive but
  WEDGED mid-batch is declared lost just like a dead one.
- **Plan warmup ships FINGERPRINTS, not programs** — traced/compiled
  executables do not serialize across processes. Submits record each
  routing digest's plan fingerprint (schema + rows + analyzers);
  prewarm/rejoin ship the hottest fingerprints and the worker REPLAYS
  the PlanKey (:func:`deequ_tpu.serve.pworker.replay_fingerprints`),
  tracing once on arrival instead of per first tenant.
- **Typed backpressure crosses the wire** — a worker's
  ``ServiceOverloadedException`` family refusal travels as structured
  fields and is RECONSTRUCTED as the same type coordinator-side, so
  ring-walk spill and caller retry schedules work unchanged.
- **The durable ledger** (:mod:`deequ_tpu.serve.ledger`) — every
  acceptance is fsynced as a checksummed frame BEFORE its submit
  returns, every resolution appends a tombstone. SIGKILL the
  coordinator and a fresh ``ProcessFleet(ledger_dir=...,
  resume_futures=...)`` replays accepted-minus-tombstoned onto the
  ORIGINAL futures — the ``stop(drain=False)``/``resume``
  kill-and-resume contract extended across coordinator death, with the
  futures' first-resolution-wins gate keeping exactly-once (chaos
  oracle 8 across the process boundary). Deadlines resume HONESTLY: a
  record's remaining budget is its accept-time remainder minus the
  wall-clock the coordinator spent dead; an expired victim is shed
  typed, never replayed stale.

- **Epoch fencing** (:mod:`deequ_tpu.serve.lease`) — resume assumed the
  old coordinator was DEAD; fencing makes a merely-stalled one
  harmless. When fencing is on (default whenever a ``ledger_dir`` is
  configured), the coordinator acquires a durable lease whose epoch
  strictly exceeds everything the ledger has witnessed, stamps every
  submit frame, ledger record, and reaccept with it, and checks the
  lease on every submit: a zombie that wakes after a takeover raises
  :class:`~deequ_tpu.exceptions.StaleEpochException` on its next
  submit, permanently, and IGNORES result frames once fenced (counted
  on ``zombie_results_ignored``). Workers refuse stale-epoch dispatches
  typed before any side effect; ledger replay reconciles cross-epoch
  duplicates by epoch precedence — exactly-once stays the futures'
  first-resolution-wins gate, now with the zombie unable to add new
  effects at all.

Chaos seams: :meth:`kill_worker` (real SIGKILL),
:meth:`rejoin_worker`, ledger-backed resume, and the zombie-coordinator
``partition`` seam — scripted by ``resilience/chaos.py``'s ``kill9`` /
``coord_kill9`` / ``partition`` events under the fleet oracles.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import uuid
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from deequ_tpu.exceptions import (
    AdmissionRejectedException,
    CorruptStateException,
    DeadlineExceededException,
    ServiceClosedException,
    ServiceOverloadedException,
    StaleEpochException,
    WorkerLostException,
)
from deequ_tpu.serve.admission import Slo, resolve_slo
from deequ_tpu.serve.lease import CoordinatorLease
from deequ_tpu.serve.ledger import RequestLedger
from deequ_tpu.serve.membership import FleetMembership
from deequ_tpu.serve.router import ConsistentHashRouter, route_digest
from deequ_tpu.serve.service import VerificationFuture, _TenantHealth
from deequ_tpu.serve.transport import (
    LoopbackTransport,
    SocketTransport,
    Transport,
    TransportClosedError,
    dump_blob,
    load_blob,
)


@dataclass
class ProcessFleetConfig:
    """ProcessFleet knobs. ``transport`` / ``ledger_dir`` default from
    DEEQU_TPU_FLEET_TRANSPORT / DEEQU_TPU_FLEET_LEDGER_DIR; the shared
    fleet knobs default from the same envcfg vars the in-process fleet
    reads. ``ack_timeout`` bounds how long a submit waits for a
    worker's accept/refuse before declaring it lost (a worker that
    cannot even ack is not serving); ``spawn_timeout`` bounds worker
    startup (process spawn + import + hello)."""

    n_workers: Optional[int] = None
    transport: Optional[str] = None
    ledger_dir: Optional[str] = None
    ledger_mode: str = "recover"
    heartbeat_interval: Optional[float] = None
    stall_timeout: Optional[float] = None
    failover_retries: Optional[int] = None
    warm_plans: int = 8
    monitor: bool = True
    quarantine_after: int = 2
    worker_knobs: Optional[Dict[str, Any]] = None
    ack_timeout: float = 10.0
    spawn_timeout: float = 60.0
    lease_dir: Optional[str] = None
    lease_ttl: Optional[float] = None
    fencing: Optional[bool] = None

    def __post_init__(self):
        from deequ_tpu.envcfg import env_value

        if self.transport is None:
            self.transport = env_value("DEEQU_TPU_FLEET_TRANSPORT")
        if self.transport not in ("proc", "loopback"):
            raise ValueError(
                f"transport must be 'proc' or 'loopback', "
                f"got {self.transport!r}"
            )
        if self.ledger_dir is None:
            self.ledger_dir = env_value("DEEQU_TPU_FLEET_LEDGER_DIR")
        if self.heartbeat_interval is None:
            self.heartbeat_interval = env_value(
                "DEEQU_TPU_HEARTBEAT_INTERVAL"
            )
        self.heartbeat_interval = float(self.heartbeat_interval)
        if self.heartbeat_interval <= 0:
            raise ValueError("heartbeat_interval must be > 0 seconds")
        if self.failover_retries is None:
            self.failover_retries = env_value("DEEQU_TPU_FAILOVER_RETRIES")
        self.failover_retries = int(self.failover_retries)
        if self.failover_retries < 0:
            raise ValueError("failover_retries must be >= 0")
        if self.n_workers is None:
            self.n_workers = env_value("DEEQU_TPU_FLEET_WORKERS")
        if self.n_workers is not None and int(self.n_workers) < 1:
            raise ValueError("n_workers must be >= 1")
        if self.stall_timeout is None:
            # the ping/pong heartbeat lags one monitor tick behind the
            # worker's actual state; keep the stall verdict comfortably
            # past that lag
            self.stall_timeout = max(8 * self.heartbeat_interval, 2.0)
        self.stall_timeout = float(self.stall_timeout)
        if self.warm_plans < 0:
            raise ValueError("warm_plans must be >= 0")
        if self.ack_timeout <= 0:
            raise ValueError("ack_timeout must be > 0 seconds")
        self.worker_knobs = dict(self.worker_knobs or {})
        if self.lease_dir is None:
            self.lease_dir = env_value("DEEQU_TPU_LEASE_DIR")
        if self.lease_dir is None:
            # the natural home: the lease fences the same durable state
            # the ledger holds
            self.lease_dir = self.ledger_dir
        if self.lease_ttl is None:
            self.lease_ttl = env_value("DEEQU_TPU_LEASE_TTL")
        self.lease_ttl = float(self.lease_ttl)
        if self.lease_ttl <= 0:
            raise ValueError("lease_ttl must be > 0 seconds")
        if self.fencing is None:
            self.fencing = env_value("DEEQU_TPU_FENCING")
        if self.fencing is None:
            # default ON exactly when there is durable state to fence
            self.fencing = (
                self.ledger_dir is not None and self.lease_dir is not None
            )
        self.fencing = bool(self.fencing)
        if self.fencing and not self.lease_dir:
            raise ValueError(
                "fencing requires a lease_dir (or a ledger_dir to "
                "default it from)"
            )


class _Ack:
    """One in-flight submit offer's accept/refuse rendezvous. The
    receiver thread (or a loss handler) fills ``status``/``fields``
    exactly once and sets the event."""

    __slots__ = ("event", "status", "fields", "worker")

    def __init__(self, worker: int):
        self.event = threading.Event()
        self.status: Optional[str] = None
        self.fields: Optional[dict] = None
        self.worker = worker


@dataclass
class _PAssignment:
    """The coordinator's authoritative record of one accepted request —
    the in-RAM twin of its durable ledger frame. Blobs are pickled once
    at submit (a failover re-offer must not re-serialize a mutated
    table)."""

    accept_id: str
    future: Any
    tenant: Any
    digest: str
    work_blob: str
    tenant_blob: str
    slo: Any
    deadline_at: Optional[float]
    worker: int = -1
    failovers: int = 0


class _PWorker:
    """One process-fleet member: a transport endpoint plus the process
    (or loopback thread) behind it and its liveness state."""

    def __init__(self, idx: int, transport: Transport,
                 proc: Optional[subprocess.Popen] = None,
                 thread: Optional[threading.Thread] = None,
                 peer: Optional[Transport] = None):
        self.idx = idx
        self.transport = transport
        self.proc = proc
        self.thread = thread
        #: the worker-side loopback endpoint (None for processes) — the
        #: kill seam closes IT so the worker loop dies from its own side
        self.peer = peer
        self.pid: Optional[int] = None
        self.alive = True
        self.ready = threading.Event()
        self.warm_ack = threading.Event()
        self.stopped = threading.Event()
        self.last_pong = time.monotonic()
        self.queue_depth = 0
        self.receiver: Optional[threading.Thread] = None

    def process_alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return self.thread is not None and self.thread.is_alive()


#: the most recent process fleet, for the obs registry section
_ACTIVE_PFLEET: Optional[weakref.ReferenceType] = None


def _pfleet_section() -> dict:
    from deequ_tpu.obs.registry import (
        FENCING_REJECTIONS,
        LEDGER_APPENDS,
        PFLEET_REDISPATCHES,
        ZOMBIE_RESULTS_IGNORED,
    )

    fleet = _ACTIVE_PFLEET() if _ACTIVE_PFLEET is not None else None
    if fleet is None:
        return {
            "workers_alive": 0,
            "redispatches": PFLEET_REDISPATCHES.value,
            "ledger_appends": LEDGER_APPENDS.value,
            "fencing_rejections": FENCING_REJECTIONS.value,
            "zombie_results_ignored": ZOMBIE_RESULTS_IGNORED.value,
        }
    return fleet._section()


class ProcessFleet:
    """The process-isolated serving fleet (see module doc).

    ``resume_futures`` maps ledger accept ids to the ORIGINAL
    :class:`VerificationFuture` objects when the driver survived the
    coordinator (same-process resume); absent entries get fresh
    futures, exposed via :attr:`resumed`."""

    def __init__(self, config: Optional[ProcessFleetConfig] = None,
                 start: bool = True,
                 resume_futures: Optional[Dict[str, Any]] = None,
                 **knobs):
        global _ACTIVE_PFLEET

        self.config = (
            config if config is not None else ProcessFleetConfig(**knobs)
        )
        n = self.config.n_workers
        self.n_workers = int(n) if n is not None else 4
        self._tenant_health = _TenantHealth(self.config.quarantine_after)
        self._router = ConsistentHashRouter()
        self._workers: Dict[int, _PWorker] = {}
        self._assignments: Dict[str, _PAssignment] = {}
        self._acks: Dict[str, _Ack] = {}
        self._fingerprints: Dict[str, dict] = {}
        self._heat: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._ack_lock = threading.Lock()
        # same discipline as the in-process fleet: loss handling and
        # submission serialize against each other (reentrant — an offer
        # that discovers a dead transport retires the worker inline)
        self._failover_lock = threading.RLock()
        self._closed = False
        self._ping_seq = 0
        self.workers_lost = 0
        self.requests_redispatched = 0
        #: accept_id -> future for ledger records replayed at startup
        self.resumed: Dict[str, Any] = {}
        self._ledger: Optional[RequestLedger] = None
        if self.config.ledger_dir:
            self._ledger = RequestLedger(
                self.config.ledger_dir, mode=self.config.ledger_mode
            )
        #: epoch fencing (serve/lease.py): acquire strictly above both
        #: the stored lease AND everything the ledger has witnessed, so
        #: a takeover outranks the previous holder even if the lease
        #: file itself was destroyed. 0 = fencing off.
        self._lease: Optional[CoordinatorLease] = None
        self._fenced: Optional[StaleEpochException] = None
        self.epoch = 0
        if self.config.fencing and self.config.lease_dir:
            self._lease = CoordinatorLease(
                self.config.lease_dir, ttl=self.config.lease_ttl
            )
            self.epoch = self._lease.acquire(
                min_epoch=(
                    self._ledger.max_epoch()
                    if self._ledger is not None else 0
                )
            )
        self.membership = FleetMembership(
            members=self._alive_ids,
            probe_of=self._probe_worker,
            on_loss=self._handle_loss,
            interval=self.config.heartbeat_interval,
            stall_timeout=self.config.stall_timeout,
        )
        for idx in range(self.n_workers):
            worker = self._spawn(idx)
            self._workers[idx] = worker
            self._router.add_worker(idx)
        _ACTIVE_PFLEET = weakref.ref(self)
        from deequ_tpu.obs.registry import REGISTRY

        REGISTRY.register_collector("pfleet", _pfleet_section)
        self._update_alive_gauge()
        self._replay_ledger(resume_futures or {})
        if start and self.config.monitor:
            self.membership.start()

    # -- spawning --------------------------------------------------------

    def _spawn(self, idx: int) -> _PWorker:
        if self.config.transport == "loopback":
            worker = self._spawn_loopback(idx)
        else:
            worker = self._spawn_proc(idx)
        worker.receiver = threading.Thread(
            target=self._receive_loop, args=(worker,), daemon=True,
            name=f"deequ-tpu-pfleet-rx-{idx}",
        )
        worker.receiver.start()
        if not worker.ready.wait(self.config.spawn_timeout):
            self._retire_endpoint(worker)
            raise WorkerLostException(
                f"worker {idx} did not say hello within "
                f"{self.config.spawn_timeout:g}s of spawn",
                worker_ids=(idx,),
            )
        return worker

    def _spawn_proc(self, idx: int) -> _PWorker:
        import json
        import socket as socket_mod

        parent, child = socket_mod.socketpair()
        argv = [
            sys.executable, "-m", "deequ_tpu.serve.pworker",
            "--fd", str(child.fileno()), "--idx", str(idx),
        ]
        if self.config.worker_knobs:
            argv += ["--knobs", json.dumps(self.config.worker_knobs)]
        proc = subprocess.Popen(argv, pass_fds=(child.fileno(),))
        child.close()
        return _PWorker(idx, SocketTransport(parent), proc=proc)

    def _spawn_loopback(self, idx: int) -> _PWorker:
        coord_end, worker_end = LoopbackTransport.pair()
        knobs = dict(self.config.worker_knobs)

        def _run():
            from deequ_tpu.serve.pworker import WorkerLoop

            WorkerLoop(worker_end, idx=idx, worker_knobs=knobs).run()

        thread = threading.Thread(
            target=_run, daemon=True, name=f"deequ-tpu-pworker-{idx}"
        )
        thread.start()
        return _PWorker(idx, coord_end, thread=thread, peer=worker_end)

    def _retire_endpoint(self, worker: _PWorker) -> None:
        """Tear down one worker's transport/process without failover
        bookkeeping (spawn failure, final stop)."""
        worker.transport.close()
        if worker.proc is not None and worker.proc.poll() is None:
            worker.proc.terminate()
            try:
                worker.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                worker.proc.kill()
                worker.proc.wait(timeout=5.0)

    # -- the receiver ----------------------------------------------------

    def _receive_loop(self, worker: _PWorker) -> None:
        """One thread per worker: drains its transport and dispatches
        frames. Transport death (EOF, ECONNRESET — what SIGKILL looks
        like from here) or a torn frame retires the worker through the
        normal loss path."""
        while True:
            try:
                msg = worker.transport.recv(timeout=0.25)
            except TransportClosedError:
                break
            except CorruptStateException as e:
                # a torn mid-stream frame means the channel can never
                # re-synchronize (frames are sequential): worker loss,
                # recorded as such
                from deequ_tpu.ops.scan_engine import SCAN_STATS

                SCAN_STATS.record_degradation(
                    "pfleet_torn_frame", worker=worker.idx, error=str(e),
                )
                break
            if msg is None:
                continue
            self._dispatch_frame(worker, msg)
        worker.stopped.set()
        if worker.alive and not self._closed:
            self._handle_loss(worker.idx, WorkerLostException(
                f"worker {worker.idx} transport died "
                "(process killed or channel torn)",
                worker_ids=(worker.idx,),
            ), expected=worker)

    def _dispatch_frame(self, worker: _PWorker, msg: dict) -> None:
        kind = str(msg.get("t"))
        if kind == "hello":
            worker.pid = msg.get("pid")
            worker.last_pong = time.monotonic()
            worker.ready.set()
        elif kind in ("accept", "refuse"):
            with self._ack_lock:
                ack = self._acks.get(str(msg.get("id")))
                if ack is not None and not ack.event.is_set():
                    ack.status = kind
                    ack.fields = msg
                    ack.event.set()
        elif kind == "result":
            self._on_result(msg)
        elif kind == "pong":
            age = float(msg.get("heartbeat_age_s") or 0.0)
            worker.last_pong = time.monotonic() - age
            worker.queue_depth = int(msg.get("queue_depth") or 0)
            self._merge_quarantine(msg.get("quarantine_blob"))
        elif kind == "warm_ack":
            worker.warm_ack.set()
        elif kind == "stopped":
            self._merge_quarantine(msg.get("quarantine_blob"))
            worker.stopped.set()

    def _merge_quarantine(self, blob: Optional[str]) -> None:
        if not blob:
            return
        try:
            self._tenant_health.restore(
                load_blob(blob, "worker quarantine snapshot")
            )
        except CorruptStateException:
            # a quarantine snapshot that cannot decode merges nothing —
            # the next pong carries a fresh one
            pass

    def _on_result(self, msg: dict) -> None:
        accept_id = str(msg.get("id"))
        frame_epoch = int(msg.get("epoch") or 0)
        if self._lease is not None and (
            self._fenced is not None
            or (frame_epoch and frame_epoch < self.epoch)
        ):
            # a fenced-out coordinator must add NO effects — its
            # successor re-dispatched this work and owns its resolution
            # (the futures' gate would keep exactly-once regardless;
            # ignoring keeps the zombie's effect count at zero) — and a
            # result stamped with a predecessor's epoch is a zombie
            # worker's late echo
            from deequ_tpu.obs.registry import ZOMBIE_RESULTS_IGNORED
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            ZOMBIE_RESULTS_IGNORED.inc()
            SCAN_STATS.record_degradation(
                "zombie_result_ignored", id=accept_id,
                frame_epoch=frame_epoch, epoch=self.epoch,
                fenced=self._fenced is not None,
            )
            return
        with self._lock:
            asg = self._assignments.get(accept_id)
        if asg is None:
            # late duplicate (the request was already resolved, shed,
            # or failed over and resolved elsewhere): the future's gate
            # would drop it anyway; the ledger already has its tombstone
            return
        self._merge_quarantine(msg.get("quarantine_blob"))
        payload = load_blob(msg["payload_blob"], "result payload")
        if msg.get("ok"):
            asg.future._resolve(payload)
        else:
            asg.future._reject(
                payload if isinstance(payload, BaseException)
                else WorkerLostException(
                    f"worker {asg.worker} reported a non-exception "
                    f"failure payload: {payload!r}",
                    worker_ids=(asg.worker,),
                )
            )

    # -- bookkeeping -----------------------------------------------------

    def _make_done(self, accept_id: str):
        """The future's coordinator-side resolution hook: drop the
        assignment and tombstone the ledger — wherever the resolution
        came from (worker result, failover shed, typed reject)."""

        def _done(f, ok):
            with self._lock:
                popped = self._assignments.pop(accept_id, None)
            if popped is not None and self._ledger is not None:
                try:
                    self._ledger.append_resolve(accept_id,
                                                epoch=self.epoch)
                except (OSError, ValueError):
                    # a tombstone lost to a closing/full ledger costs
                    # one redundant (gated) replay at resume, never a
                    # lost result
                    pass

        return _done

    _HEAT_CAP = 1024

    def _record_heat(self, digest: str, data, analyzers) -> None:
        """Caller holds ``self._lock``. Tracks digest heat AND the plan
        fingerprint warmup ships (programs don't serialize; shapes
        do)."""
        self._heat[digest] = self._heat.get(digest, 0) + 1
        if digest not in self._fingerprints:
            from deequ_tpu.serve.pworker import plan_fingerprint

            fp = plan_fingerprint(data, analyzers)
            if fp is not None:
                self._fingerprints[digest] = fp
        if len(self._heat) > self._HEAT_CAP:
            keep = dict(sorted(
                self._heat.items(), key=lambda kv: kv[1], reverse=True
            )[: self._HEAT_CAP // 2])
            self._heat = keep
            self._fingerprints = {
                d: fp for d, fp in self._fingerprints.items() if d in keep
            }

    def _alive_ids(self) -> List[int]:
        with self._lock:
            return sorted(i for i, w in self._workers.items() if w.alive)

    # -- membership probe ------------------------------------------------

    def _probe_worker(self, idx: int):
        """The FleetMembership probe leg: (process alive AND channel
        open, last heartbeat on the coordinator clock). Each probe also
        launches the next ping — the pong lands asynchronously via the
        receiver, so freshness lags one tick (stall_timeout covers
        that)."""
        with self._lock:
            worker = self._workers.get(idx)
        if worker is None or not worker.alive:
            return False, 0.0
        self._ping_seq += 1
        try:
            worker.transport.send({"t": "ping", "seq": self._ping_seq})
        except TransportClosedError:
            return False, 0.0
        return worker.process_alive(), worker.last_pong

    # -- fencing ---------------------------------------------------------

    def _fence(self, cause: StaleEpochException) -> None:
        """Fence PERMANENTLY: a coordinator that has been outranked once
        stays outranked (un-fencing would re-open split brain). Every
        subsequent submit re-raises typed from the stored cause."""
        if self._fenced is not None:
            return
        self._fenced = cause
        from deequ_tpu.obs.registry import FENCING_REJECTIONS
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        FENCING_REJECTIONS.inc()
        SCAN_STATS.record_degradation(
            "stale_epoch_fenced", epoch=self.epoch,
            current_epoch=cause.current_epoch, holder=cause.holder,
        )

    def _check_fence(self) -> None:
        """The per-submit fencing guard: re-read the lease (cheap next
        to the fsync every durable accept pays) and refuse typed when a
        successor outranks us. No-op when fencing is off."""
        if self._lease is None:
            return
        if self._fenced is not None:
            from deequ_tpu.obs.registry import FENCING_REJECTIONS

            FENCING_REJECTIONS.inc()
            raise StaleEpochException(
                str(self._fenced),
                stale_epoch=self._fenced.stale_epoch,
                current_epoch=self._fenced.current_epoch,
                holder=self._fenced.holder,
            )
        try:
            self._lease.check()
        except StaleEpochException as e:
            self._fence(e)
            raise

    # -- submission ------------------------------------------------------

    def route(self, data, checks: Sequence = (),
              required_analyzers: Sequence = ()) -> Optional[int]:
        """The worker id a submission would land on (tests/bench script
        deterministic deaths against this)."""
        analyzers = list(required_analyzers)
        for check in checks:
            analyzers.extend(check.required_analyzers())
        return self._router.place(route_digest(data, analyzers))

    def submit(self, data, checks: Sequence = (),
               required_analyzers: Sequence = (), tenant=None, slo=None):
        """Enqueue one suite on its placed worker process; returns the
        future. Acceptance is DURABLE before this returns: the ledger
        frame fsyncs before the submit offer ships, so a coordinator
        killed at any later instant still owes (and can replay) exactly
        this request. Overload spill walks the ring exactly like the
        in-process fleet — every refusal is the worker's own typed
        backpressure, reconstructed from the wire. When fencing is on,
        a fenced-out (zombie) coordinator refuses here typed
        (:class:`StaleEpochException`) before any side effect."""
        self._check_fence()
        analyzers = list(required_analyzers)
        for check in checks:
            analyzers.extend(check.required_analyzers())
        digest = route_digest(data, analyzers)
        slo = resolve_slo(slo)
        with self._failover_lock:
            with self._lock:
                if self._closed:
                    raise ServiceClosedException(
                        "submit on a stopped ProcessFleet"
                    )
                self._record_heat(digest, data, analyzers)
            future = VerificationFuture(tenant)
            deadline_at = (
                future.submitted_at + slo.deadline_seconds
                if slo.deadline_seconds is not None else None
            )
            asg = _PAssignment(
                accept_id=uuid.uuid4().hex,
                future=future,
                tenant=tenant,
                digest=digest,
                work_blob=dump_blob(
                    (data, tuple(checks), tuple(required_analyzers))
                ),
                tenant_blob=dump_blob(tenant),
                slo=slo,
                deadline_at=deadline_at,
            )
            # record + chain BEFORE any frame ships: a worker fast
            # enough to answer with the result mid-submit must find the
            # assignment already registered
            future.accept_id = asg.accept_id
            future._on_done = self._make_done(asg.accept_id)
            with self._lock:
                self._assignments[asg.accept_id] = asg
            if self._ledger is not None:
                self._ledger.append_accept(
                    asg.accept_id,
                    tenant=tenant,
                    digest=digest,
                    slo_cls=slo.cls,
                    deadline_ms=slo.deadline_ms,
                    weight=slo.weight,
                    deadline_left_s=(
                        deadline_at - time.monotonic()
                        if deadline_at is not None else None
                    ),
                    work=(data, tuple(checks),
                          tuple(required_analyzers)),
                    quarantine=self._tenant_health.snapshot(),
                    epoch=self.epoch,
                )
            status, outcome = self._offer_walk(asg)
            if status == "accepted":
                return future
            # nobody took it: the acceptance is void — tombstone it and
            # surface the placed worker's typed refusal (or fleet death)
            with self._lock:
                self._assignments.pop(asg.accept_id, None)
            if self._ledger is not None:
                self._ledger.append_resolve(asg.accept_id,
                                            epoch=self.epoch)
            if status == "refused":
                raise outcome
            raise ServiceClosedException(
                "no alive workers in the process fleet "
                "(all lost; rejoin_worker or restart)"
            )

    def verify(self, data, checks: Sequence = (), **kw):
        """Synchronous convenience: submit + wait."""
        return self.submit(data, checks, **kw).result()

    def _offer_walk(self, asg: _PAssignment):
        """Offer one assignment around the ring from its digest, each
        alive worker once. Returns ``("accepted", wid)``, ``("refused",
        exc)`` (the FIRST — placed — worker's typed refusal), or
        ``("dead", None)``. Caller holds the failover lock."""
        refusal: Optional[ServiceOverloadedException] = None
        with self._lock:
            order = list(self._router.walk(asg.digest))
        for wid in order:
            with self._lock:
                worker = self._workers.get(wid)
            if worker is None or not worker.alive:
                continue
            outcome = self._offer(worker, asg)
            if outcome == "accept":
                asg.worker = wid
                return "accepted", wid
            if isinstance(outcome, StaleEpochException):
                # a WORKER fenced us: our epoch is stale for every
                # worker, not just this one — stop the walk, fence
                # permanently
                self._fence(outcome)
                return "refused", outcome
            if isinstance(outcome, ServiceOverloadedException):
                if refusal is None:
                    refusal = outcome
                continue
            # None / ServiceClosed: the worker was retired mid-offer —
            # keep walking the survivors
        if refusal is not None:
            return "refused", refusal
        return "dead", None

    def _offer(self, worker: _PWorker, asg: _PAssignment):
        """Ship one submit frame and wait for its accept/refuse.
        Returns ``"accept"``, a reconstructed typed refusal, or None
        when the worker died mid-offer (retired inline — caller holds
        the failover lock)."""
        frame = {
            "t": "submit",
            "id": asg.accept_id,
            "epoch": self.epoch,
            "work_blob": asg.work_blob,
            "tenant_blob": asg.tenant_blob,
            "slo": {"cls": asg.slo.cls, "weight": asg.slo.weight,
                    "deadline_ms": asg.slo.deadline_ms},
            "deadline_left_s": (
                max(asg.deadline_at - time.monotonic(), 1e-3)
                if asg.deadline_at is not None else None
            ),
            "quarantine_blob": dump_blob(self._tenant_health.snapshot()),
        }
        ack = _Ack(worker.idx)
        with self._ack_lock:
            self._acks[asg.accept_id] = ack
        try:
            try:
                worker.transport.send(frame)
            except TransportClosedError as e:
                self._handle_loss(worker.idx, WorkerLostException(
                    f"worker {worker.idx} channel died at offer: {e}",
                    worker_ids=(worker.idx,),
                ), skip=asg.accept_id)
                return None
            if not ack.event.wait(self.config.ack_timeout):
                # a worker that cannot even ACK within the window is
                # not serving: retire it (its other victims fail over;
                # THIS assignment continues its walk in the caller)
                self._handle_loss(worker.idx, WorkerLostException(
                    f"worker {worker.idx} did not ack within "
                    f"{self.config.ack_timeout:g}s",
                    worker_ids=(worker.idx,),
                ), skip=asg.accept_id)
                return None
        finally:
            with self._ack_lock:
                self._acks.pop(asg.accept_id, None)
        if ack.status == "accept":
            return "accept"
        if ack.status == "lost":
            return None
        return self._rebuild_refusal(ack.fields or {})

    @staticmethod
    def _rebuild_refusal(fields: dict):
        """Typed backpressure off the wire: same exception type, same
        structured retry fields, as if the worker's service had raised
        in-process."""
        cls = fields.get("cls")
        message = fields.get("message") or "worker refused admission"
        if cls == "ServiceClosedException":
            return ServiceClosedException(message)
        if cls == "StaleEpochException":
            return StaleEpochException(
                message,
                stale_epoch=fields.get("stale_epoch"),
                current_epoch=fields.get("current_epoch"),
                holder=fields.get("holder"),
            )
        kw = dict(
            queue_depth=fields.get("queue_depth"),
            retry_after_s=fields.get("retry_after_s"),
            slo_class=fields.get("slo_class"),
        )
        if cls == "AdmissionRejectedException":
            return AdmissionRejectedException(
                message, reason=fields.get("reason") or "class_budget",
                **kw,
            )
        return ServiceOverloadedException(message, **kw)

    # -- failover --------------------------------------------------------

    def kill_worker(self, idx: int, reason: str = "scripted kill -9"
                    ) -> int:
        """Chaos/ops seam — REAL process death: SIGKILL the worker
        process (loopback: sever its endpoint) and fail its accepted
        requests over. Returns how many were re-dispatched."""
        with self._lock:
            worker = self._workers.get(idx)
        if worker is None or not worker.alive:
            return 0
        if worker.proc is not None:
            if worker.proc.poll() is None:
                os.kill(worker.proc.pid, signal.SIGKILL)
                try:
                    worker.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    pass
        elif worker.peer is not None:
            worker.peer.close()
        return self._handle_loss(idx, WorkerLostException(
            f"worker {idx} died: {reason}", worker_ids=(idx,)
        ))

    def _abort_acks_for(self, idx: int) -> None:
        """Wake any offer waiting on a now-dead worker BEFORE the loss
        handler queues on the failover lock — the offering thread HOLDS
        that lock while it waits."""
        with self._ack_lock:
            for ack in self._acks.values():
                if ack.worker == idx and not ack.event.is_set():
                    ack.status = "lost"
                    ack.event.set()

    def _handle_loss(self, idx: int, cause: WorkerLostException,
                     skip: Optional[str] = None,
                     expected: Optional[_PWorker] = None) -> int:
        """Retire a dead worker and replay its unresolved assignments
        onto survivors on their ORIGINAL futures. ``skip`` names an
        assignment the caller is already walking (it must not be
        replayed underneath its own offer); ``expected`` guards a
        receiver thread's loss report against racing a rejoin under the
        same id."""
        self._abort_acks_for(idx)
        with self._failover_lock:
            with self._lock:
                worker = self._workers.get(idx)
                if (worker is None or not worker.alive or self._closed
                        or (expected is not None
                            and worker is not expected)):
                    return 0
                worker.alive = False
                self._router.remove_worker(idx)
                self.workers_lost += 1
            self._retire_endpoint(worker)
            self._update_alive_gauge()
            from deequ_tpu.obs.registry import FLEET_FAILOVERS
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            FLEET_FAILOVERS.inc()
            with self._lock:
                victims = [
                    a for a in self._assignments.values()
                    if a.worker == idx and a.accept_id != skip
                    and not a.future.done()
                ]
            SCAN_STATS.record_degradation(
                "pworker_failover", worker=idx, tenants=len(victims),
                error=str(cause),
            )
            redispatched = 0
            for asg in victims:
                redispatched += self._redispatch(asg, idx, cause)
            self.requests_redispatched += redispatched
            return redispatched

    def _redispatch(self, asg: _PAssignment, lost_idx: int,
                    cause: WorkerLostException) -> int:
        """Replay ONE assignment onto a survivor. Deadline-expired
        victims shed typed on their original futures (never replayed
        stale); retries past ``failover_retries`` reject typed. Caller
        holds the failover lock."""
        from deequ_tpu.obs.registry import PFLEET_REDISPATCHES

        if (asg.deadline_at is not None
                and time.monotonic() >= asg.deadline_at):
            self._shed_expired_victim(asg, lost_idx)
            return 0
        asg.failovers += 1
        if asg.failovers > self.config.failover_retries:
            asg.future._reject(WorkerLostException(
                f"request for tenant {asg.tenant!r} lost worker "
                f"{lost_idx} and exhausted failover_retries="
                f"{self.config.failover_retries}",
                worker_ids=cause.worker_ids,
            ))
            return 0
        status, outcome = self._offer_walk(asg)
        if status == "accepted":
            PFLEET_REDISPATCHES.inc()
            return 1
        if status == "refused":
            asg.future._reject(outcome)
            return 0
        asg.future._reject(WorkerLostException(
            f"request for tenant {asg.tenant!r} lost worker {lost_idx} "
            "and no survivor remains",
            worker_ids=cause.worker_ids,
        ))
        return 0

    def _shed_expired_victim(self, asg: _PAssignment, lost_idx: int
                             ) -> None:
        from deequ_tpu.obs.registry import SERVE_SHED_BY_CLASS
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        cls = asg.slo.cls if asg.slo is not None else "standard"
        waited = time.monotonic() - asg.future.submitted_at
        SCAN_STATS.record_degradation(
            "deadline_shed", tenant=asg.tenant, slo_class=cls,
            worker=lost_idx, at="pfleet_failover",
            waited_s=round(waited, 4),
        )
        SERVE_SHED_BY_CLASS[cls].inc()
        asg.future._reject(DeadlineExceededException(
            f"request for tenant {asg.tenant!r} lost worker {lost_idx} "
            f"after its {cls!r} SLO deadline already passed — shed at "
            "failover instead of replayed stale",
            tenant=asg.tenant, slo_class=cls,
            deadline_ms=(asg.slo.deadline_ms if asg.slo else None),
            waited_s=waited,
        ))

    # -- warmup ----------------------------------------------------------

    def _hot_fingerprints(self) -> List[dict]:
        with self._lock:
            hot = sorted(
                self._heat.items(), key=lambda kv: kv[1], reverse=True
            )
            return [
                self._fingerprints[d] for d, _ in hot
                if d in self._fingerprints
            ][: self.config.warm_plans]

    def _warm_worker(self, worker: _PWorker, plans: List[dict]) -> None:
        if not plans:
            return
        worker.warm_ack.clear()
        try:
            worker.transport.send({"t": "warm", "plans": plans})
        except TransportClosedError:
            return
        # best-effort: a joiner that never acks is caught by membership
        worker.warm_ack.wait(self.config.ack_timeout)

    def prewarm(self) -> None:
        """Ship every alive worker the fleet's hottest plan
        fingerprints; each replays the PlanKeys into its own cache.
        After a prewarm any survivor serves a dead worker's tenants
        without a first-request trace storm."""
        plans = self._hot_fingerprints()
        with self._lock:
            alive = [w for w in self._workers.values() if w.alive]
        for worker in alive:
            self._warm_worker(worker, plans)

    def rejoin_worker(self, idx: int) -> Optional[_PWorker]:
        """Bring a lost worker id back as a FRESH process, warmed from
        the coordinator's hot-fingerprint feed BEFORE it owns any ring
        arc."""
        with self._failover_lock:
            with self._lock:
                if self._closed:
                    raise ServiceClosedException("process fleet is stopped")
                existing = self._workers.get(idx)
                if existing is not None and existing.alive:
                    return existing
            worker = self._spawn(idx)
            self._warm_worker(worker, self._hot_fingerprints())
            with self._lock:
                self._workers[idx] = worker
                self._router.add_worker(idx)
            self._update_alive_gauge()
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            SCAN_STATS.record_degradation(
                "pworker_rejoin", worker=idx, pid=worker.pid,
            )
            return worker

    # -- coordinator resume ----------------------------------------------

    def _replay_ledger(self, resume_futures: Dict[str, Any]) -> None:
        """Kill-and-resume: re-dispatch every accepted-but-untombstoned
        ledger record (the work a dead coordinator still owed) onto
        this fleet's workers — original futures where the driver
        survived, fresh ones otherwise. Exactly-once rides the futures'
        first-resolution-wins gate; deadlines resume minus the
        wall-clock spent dead."""
        if self._ledger is None:
            return
        outstanding = self._ledger.outstanding()
        if not outstanding:
            return
        from deequ_tpu.envcfg import env_value

        if not env_value("DEEQU_TPU_COORD_RESUME"):
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            SCAN_STATS.record_degradation(
                "coord_resume_disabled", outstanding=len(outstanding),
            )
            return
        from deequ_tpu.obs.registry import PFLEET_RESUMED
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        snap = self._ledger.latest_quarantine()
        if snap is not None:
            self._tenant_health.restore(snap)
        now_wall = time.time()
        with self._failover_lock:
            for accept_id, rec in outstanding.items():
                try:
                    tenant = RequestLedger.load_tenant(rec)
                    data, checks, required = RequestLedger.load_work(rec)
                except CorruptStateException as e:
                    # checksum passed but the pickle no longer decodes
                    # (e.g. a class renamed between incarnations):
                    # surface typed per-record, keep replaying the rest
                    SCAN_STATS.record_degradation(
                        "ledger_undecodable_record", id=accept_id,
                        error=str(e),
                    )
                    continue
                future = resume_futures.get(accept_id)
                if future is None:
                    future = VerificationFuture(tenant)
                slo_rec = rec.get("slo") or {}
                slo = Slo(
                    deadline_ms=slo_rec.get("deadline_ms"),
                    weight=float(slo_rec.get("weight", 1.0)),
                    cls=str(slo_rec.get("cls", "standard")),
                )
                left = None
                if rec.get("deadline_left_s") is not None:
                    dead_for = now_wall - float(
                        rec.get("accepted_wall", now_wall)
                    )
                    left = float(rec["deadline_left_s"]) - max(
                        dead_for, 0.0
                    )
                analyzers = list(required)
                for check in checks:
                    analyzers.extend(check.required_analyzers())
                asg = _PAssignment(
                    accept_id=accept_id,
                    future=future,
                    tenant=tenant,
                    digest=rec.get("digest")
                    or route_digest(data, analyzers),
                    work_blob=rec["work_blob"],
                    tenant_blob=rec["tenant_blob"],
                    slo=slo,
                    deadline_at=(
                        time.monotonic() + left
                        if left is not None else None
                    ),
                )
                future.accept_id = accept_id
                future._on_done = self._make_done(accept_id)
                with self._lock:
                    self._assignments[accept_id] = asg
                    self._record_heat(asg.digest, data, analyzers)
                if self.epoch and (
                    RequestLedger._epoch_of(rec) < self.epoch
                ):
                    # durable ownership claim BEFORE re-dispatch: the
                    # record's effective epoch becomes ours, so the
                    # zombie that accepted it loses every epoch-
                    # precedence comparison from here on
                    self._ledger.append_reaccept(accept_id, self.epoch)
                PFLEET_RESUMED.inc()
                self.resumed[accept_id] = future
                if left is not None and left <= 0:
                    self._shed_expired_victim(asg, -1)
                    continue
                status, outcome = self._offer_walk(asg)
                if status == "refused":
                    future._reject(outcome)
                elif status == "dead":
                    future._reject(WorkerLostException(
                        "resume replay found no alive workers",
                        worker_ids=(),
                    ))
        SCAN_STATS.record_degradation(
            "coord_resume", replayed=len(self.resumed),
        )

    # -- lifecycle -------------------------------------------------------

    def abandon(self) -> None:
        """Chaos/ops seam — simulated coordinator ``kill -9``, scoped to
        this object: freeze the bookkeeping (no drains, no tombstones,
        no failovers), sever every worker channel, drop the ledger
        handle. This is exactly what the OS does to a SIGKILLed
        coordinator's threads, sockets, and file handles — accepted
        futures stay unresolved, and only the durable ledger knows what
        was owed. A fresh ``ProcessFleet(ledger_dir=...,
        resume_futures=...)`` is the recovery path."""
        self.membership.stop()
        with self._lock:
            self._closed = True
            workers = list(self._workers.values())
        for worker in workers:
            self._retire_endpoint(worker)
            if worker.receiver is not None:
                worker.receiver.join(timeout=5.0)
        if self._ledger is not None:
            self._ledger.close()
        self._update_alive_gauge(0)

    def stop(self, drain: bool = True) -> List:
        """Stop the whole fleet: drain (or not) every worker, reap the
        processes, close the ledger. Returns the futures still
        unresolved."""
        self.membership.stop()
        with self._lock:
            if self._closed:
                return []
            self._closed = True
            workers = [w for w in self._workers.values() if w.alive]
        for worker in workers:
            worker.stopped.clear()
            try:
                worker.transport.send({"t": "stop", "drain": drain})
            except TransportClosedError:
                worker.stopped.set()
        deadline = time.monotonic() + (60.0 if drain else 10.0)
        for worker in workers:
            worker.stopped.wait(max(deadline - time.monotonic(), 0.1))
            self._retire_endpoint(worker)
            if worker.receiver is not None:
                worker.receiver.join(timeout=5.0)
        if self._ledger is not None:
            self._ledger.close()
        self._update_alive_gauge(0)
        with self._lock:
            return [
                a.future for a in self._assignments.values()
                if not a.future.done()
            ]

    def __enter__(self) -> "ProcessFleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop(drain=exc_type is None)

    # -- introspection ---------------------------------------------------

    def _update_alive_gauge(self, value: Optional[int] = None) -> None:
        from deequ_tpu.obs.registry import PFLEET_WORKERS_ALIVE

        PFLEET_WORKERS_ALIVE.set(
            value if value is not None else len(self._alive_ids())
        )

    def _section(self) -> dict:
        from deequ_tpu.obs.registry import (
            FENCING_REJECTIONS,
            LEDGER_APPENDS,
            ZOMBIE_RESULTS_IGNORED,
        )

        with self._lock:
            workers = {
                str(i): {
                    "alive": w.alive,
                    "pid": w.pid,
                    "transport": (
                        "proc" if w.proc is not None else "loopback"
                    ),
                    "queue_depth": w.queue_depth if w.alive else 0,
                }
                for i, w in self._workers.items()
            }
            pending = sum(
                1 for a in self._assignments.values()
                if not a.future.done()
            )
        return {
            "workers_alive": sum(
                1 for w in workers.values() if w["alive"]
            ),
            "workers_lost": self.workers_lost,
            "redispatches": self.requests_redispatched,
            "requests_outstanding": pending,
            "resumed": len(self.resumed),
            "epoch": self.epoch,
            "fencing": self._lease is not None,
            "fenced": self._fenced is not None,
            "fencing_rejections": FENCING_REJECTIONS.value,
            "zombie_results_ignored": ZOMBIE_RESULTS_IGNORED.value,
            "ledger_appends": LEDGER_APPENDS.value,
            "ledger_path": (
                self._ledger.path if self._ledger is not None else None
            ),
            "workers": workers,
        }

    def stats(self) -> dict:
        return self._section()
