"""Durable coordinator lease with a monotonically increasing EPOCH —
the fencing token that makes split brain safe (PR 18).

PR 17 made coordinator death survivable: a fresh
:class:`~deequ_tpu.serve.pfleet.ProcessFleet` on the same ``ledger_dir``
replays outstanding accepts onto the original futures. But "dead" was an
assumption — a coordinator that merely STALLED (GC pause, partition,
stopped container) through a takeover wakes up as a zombie writing to
the same ledger and re-dispatching the same work. This module is the
standard fencing answer: a durable lease file whose ``epoch`` only ever
increases. Every acquisition (including a resume takeover) bumps the
epoch; every frame, ledger record, and result the coordinator writes
carries it; anything stamped with an older epoch is refused typed
(:class:`~deequ_tpu.exceptions.StaleEpochException`) or ignored.

The lease file is itself durable state under the same discipline it
protects: written via the atomic temp+fsync+rename helper inside the
checksummed ``DQX1`` envelope, with torn-lease reads surfacing typed
(or quarantining to a counter-suffixed ``.corrupt`` sidecar in recover
mode). A torn lease can therefore never silently REGRESS the epoch:
callers that must not move backwards pass the request ledger's
``max_epoch()`` as ``min_epoch`` at acquire, so the fencing floor
survives even a destroyed lease file.

The TTL is a liveness knob, not the safety mechanism: ``check()`` (the
coordinator's hot-path guard) re-reads the lease from disk on every
call — cheap against the fsync every durable accept already pays — and
re-asserts/renews it at half-TTL cadence. Safety is the epoch ordering
alone; two coordinators that both believe they hold the lease still
cannot double-resolve, because the lower epoch loses every comparison.
"""

from __future__ import annotations

import json
import os
import socket
import time
from dataclasses import dataclass
from typing import Optional

from deequ_tpu.data.fs import FileSystem, LocalFileSystem
from deequ_tpu.exceptions import CorruptStateException, StaleEpochException
from deequ_tpu.resilience.atomic import (
    atomic_write_bytes,
    quarantine_path,
    unwrap_checksum,
    wrap_checksum,
)

#: the one lease file inside a fleet's lease_dir
LEASE_FILENAME = "coordinator.lease"


@dataclass(frozen=True)
class LeaseState:
    """One decoded lease file: the cluster's current fencing state."""

    epoch: int
    holder: str
    acquired_wall: float
    renewed_wall: float
    ttl_s: float

    def age_s(self, now: Optional[float] = None) -> float:
        return (now if now is not None else time.time()) - self.renewed_wall

    def expired(self, now: Optional[float] = None) -> bool:
        """Past its TTL — a takeover is POLITE now (the holder stopped
        renewing), though epoch fencing keeps any takeover safe."""
        return self.age_s(now) > self.ttl_s


class CoordinatorLease:
    """The durable epoch-fenced lease (see module doc).

    ``fs`` is injectable (tests run the full protocol over an
    :class:`~deequ_tpu.data.fs.InMemoryFileSystem`); production uses
    local disk, where the atomic-rename write gives lease updates
    all-or-nothing visibility."""

    def __init__(self, lease_dir: str, ttl: Optional[float] = None,
                 holder: Optional[str] = None,
                 fs: Optional[FileSystem] = None):
        from deequ_tpu.envcfg import env_value

        self._fs = fs if fs is not None else LocalFileSystem()
        self.lease_dir = lease_dir
        self._fs.makedirs(lease_dir)
        self.path = self._fs.join(lease_dir, LEASE_FILENAME)
        self.ttl = float(
            ttl if ttl is not None else env_value("DEEQU_TPU_LEASE_TTL")
        )
        if self.ttl <= 0:
            raise ValueError("lease ttl must be > 0 seconds")
        self.holder = holder or f"{socket.gethostname()}:pid{os.getpid()}"
        #: this holder's epoch; 0 = not acquired
        self.epoch = 0
        self._last_renew = 0.0  # monotonic stamp of our last disk write
        self._acquired_wall = 0.0

    # -- disk format -----------------------------------------------------

    def read(self, recover: bool = False) -> Optional[LeaseState]:
        """Decode the on-disk lease. None when no lease exists; typed
        :class:`CorruptStateException` on a torn/damaged lease file —
        unless ``recover`` is set, which quarantines the damaged bytes
        to a counter-suffixed ``.corrupt`` sidecar (forensic evidence;
        a second recovery never overwrites the first) and returns None
        so the caller re-acquires. The epoch floor against regression
        after a destroyed lease is the caller's ``min_epoch``."""
        if not self._fs.exists(self.path):
            return None
        with self._fs.open(self.path, "rb") as f:
            raw = f.read()
        try:
            payload = unwrap_checksum(raw, "coordinator lease")
            state = json.loads(payload.decode("utf-8"))
            return LeaseState(
                epoch=int(state["epoch"]),
                holder=str(state.get("holder", "")),
                acquired_wall=float(state.get("acquired_wall", 0.0)),
                renewed_wall=float(state.get("renewed_wall", 0.0)),
                ttl_s=float(state.get("ttl_s", self.ttl)),
            )
        except CorruptStateException as e:
            if not recover:
                raise
            self._quarantine(raw, str(e))
            return None
        except (KeyError, TypeError, ValueError, UnicodeDecodeError) as e:
            # checksum passed (or legacy passthrough) but the payload
            # does not decode as a lease: same damage classification
            damage = CorruptStateException(
                "coordinator lease", f"undecodable lease payload: {e}"
            )
            if not recover:
                raise damage from e
            self._quarantine(raw, str(damage))
            return None

    def _quarantine(self, raw: bytes, error: str) -> None:
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        sidecar = quarantine_path(self._fs, self.path)
        with self._fs.open(sidecar, "wb") as f:  # deequ-lint: ignore[durable-write] -- quarantine sidecar: forensic copy of already-damaged bytes, not durable state (no reader validates it)
            f.write(raw)
        self._fs.delete(self.path)
        SCAN_STATS.record_degradation(
            "lease_torn", path=self.path, sidecar=sidecar, error=error,
        )

    def _write(self, epoch: int, acquired_wall: float) -> None:
        now = time.time()
        payload = json.dumps({
            "epoch": epoch,
            "holder": self.holder,
            "acquired_wall": acquired_wall,
            "renewed_wall": now,
            "ttl_s": self.ttl,
        }, sort_keys=True).encode("utf-8")
        atomic_write_bytes(
            self._fs, self.path, wrap_checksum(payload),
            what="coordinator lease",
        )
        self._acquired_wall = acquired_wall
        self._last_renew = time.monotonic()

    # -- the protocol ----------------------------------------------------

    def acquire(self, min_epoch: int = 0) -> int:
        """Take (over) the lease: the new epoch strictly exceeds every
        epoch ever observed — the stored lease's, ``min_epoch`` (pass
        the request ledger's ``max_epoch()`` so a torn/lost lease file
        cannot regress the fence), and our own. Acquisition does not
        wait for expiry: the caller decided a takeover is warranted
        (resume after coordinator death); epoch ordering keeps the
        stalled previous holder harmless."""
        current = self.read(recover=True)
        floor = max(
            current.epoch if current is not None else 0,
            int(min_epoch), self.epoch,
        )
        self.epoch = floor + 1
        self._write(self.epoch, acquired_wall=time.time())
        return self.epoch

    def check(self) -> int:
        """The hot-path fencing guard (every fenced submit): raise
        :class:`StaleEpochException` when the on-disk lease outranks our
        epoch — a successor took over while we stalled. Re-asserts the
        lease when the file is missing/damaged (our epoch stands until
        someone outranks it) and renews it at half-TTL cadence."""
        if self.epoch <= 0:
            raise ValueError("check() before acquire()")
        current = self.read(recover=True)
        if current is not None and current.epoch > self.epoch:
            raise StaleEpochException(
                f"lease epoch {self.epoch} fenced out: "
                f"{current.holder!r} holds epoch {current.epoch}",
                stale_epoch=self.epoch,
                current_epoch=current.epoch,
                holder=current.holder,
            )
        if current is None or current.epoch < self.epoch:
            # lost/damaged/regressed lease file: re-assert ours
            self._write(self.epoch, acquired_wall=self._acquired_wall)
        elif time.monotonic() - self._last_renew > self.ttl / 2.0:
            self.renew()
        return self.epoch

    def renew(self) -> None:
        """Refresh ``renewed_wall`` (the TTL heartbeat). Fenced holders
        must not renew over their successor: re-checks the disk epoch
        first."""
        if self.epoch <= 0:
            raise ValueError("renew() before acquire()")
        current = self.read(recover=True)
        if current is not None and current.epoch > self.epoch:
            raise StaleEpochException(
                f"renew refused: lease epoch {self.epoch} fenced out by "
                f"{current.holder!r} at epoch {current.epoch}",
                stale_epoch=self.epoch,
                current_epoch=current.epoch,
                holder=current.holder,
            )
        self._write(self.epoch, acquired_wall=self._acquired_wall)
