"""deequ_tpu.serve — the long-lived multi-tenant verification service.

The millions-of-users shape (BENCHMARKS config 1) is many SMALL suites
arriving concurrently, not one giant scan — and per submitted run the
engine pays fixed costs that dwarf the compute at small row counts: a
trace+compile for any fresh plan, a plan-lint trace, and a dispatch +
fetch round trip (~4 fixed-latency tunnel round trips per run). Flare's
thesis (arXiv:1703.08219) is that native whole-query compilation only
wins when its cost is amortized across repeated executions; this package
is that amortization for deequ-tpu (ROADMAP item 2, closing item 5's
plan/executor split another notch):

- :mod:`plan_cache` — the COMPILED-PLAN CACHE: suites are fingerprinted
  by (schema, analyzer set — predicates included, packer layout, row
  count) and a repeat tenant reuses the built ops, the traced+compiled
  vmapped program, and the memoized plan-lint verdict. Observable as
  ``ScanStats.plan_cache_hits`` / ``plan_cache_misses``; the hard
  contract (bench + tier-1) is that a repeat suite adds ZERO traces.
- :mod:`executor` — the REQUEST COALESCER's packed executor: N pending
  tenant tables pack into ONE ``(K, n)`` buffer stack and run as ONE
  vmapped fused dispatch with ONE device->host fetch (per-tenant state
  slices unpacked on the host), so the round-trip cost is paid once per
  BATCH of runs. Members coalesce only on exact (plan, layout, row
  count) agreement — per-slice results are bit-identical to serial
  per-tenant runs (the run_scan_group construction, vmap semantics);
  the tenant axis pads to a pow2 bucket with all-invalid dummy slices
  whose inertness vmap's per-slice independence guarantees. Faults
  bisect the TENANT axis (split, retry halves) so one poison tenant is
  localized in O(log K) and degrades only its own slice.
- :mod:`service` — :class:`VerificationService`: the async
  ``submit(...) -> VerificationFuture`` API, a bounded worker loop with
  a coalescing window, per-tenant run budgets (PR 9 governance; one
  tenant's budget exhaustion never sinks a batch), tenant quarantine
  for repeat offenders, and kill-and-resume of the pending queue.
- :mod:`admission` — the OVERLOAD tier (round 15): per-tenant
  :class:`Slo` classes, typed admission control with ``retry_after_s``,
  a deadline-aware class-tiered tenant-fair queue (expired requests
  shed typed pre-dispatch), and the 3-level brownout ladder — overload
  changes WHICH requests run, never how (completed results stay
  bit-identical to an unloaded serial run).
- :mod:`pfleet` (+ :mod:`transport`, :mod:`ledger`, :mod:`pworker`) —
  the PROCESS fleet (round 17): coordinator + N worker processes
  behind a checksummed frame transport, plan warmup via shipped
  fingerprints (the joiner mints the service's own ``PlanKey``), typed
  backpressure reconstructed from wire fields, a durable accept-time
  request ledger with torn-tail recovery, real-SIGKILL worker
  failover, and coordinator kill-and-resume onto original futures.

See docs/serving.md for cache-key semantics, coalescing/padding rules,
and the isolation ladder.
"""

from deequ_tpu.serve.admission import (
    AdmissionController,
    BrownoutController,
    Slo,
    TenantFairQueue,
)
from deequ_tpu.serve.fleet import FleetConfig, VerificationFleet
from deequ_tpu.serve.ledger import RequestLedger
from deequ_tpu.serve.membership import FleetMembership, WorkerLossReport
from deequ_tpu.serve.pfleet import ProcessFleet, ProcessFleetConfig
from deequ_tpu.serve.plan_cache import PlanCache, PlanKey, ServePlan
from deequ_tpu.serve.router import ConsistentHashRouter, route_digest
from deequ_tpu.serve.service import (
    PendingWork,
    ServeConfig,
    VerificationFuture,
    VerificationService,
)

__all__ = [
    "AdmissionController",
    "BrownoutController",
    "ConsistentHashRouter",
    "FleetConfig",
    "FleetMembership",
    "PendingWork",
    "PlanCache",
    "PlanKey",
    "ProcessFleet",
    "ProcessFleetConfig",
    "RequestLedger",
    "route_digest",
    "ServePlan",
    "ServeConfig",
    "Slo",
    "TenantFairQueue",
    "VerificationFleet",
    "VerificationFuture",
    "VerificationService",
    "WorkerLossReport",
]
