"""Durable request ledger — the fleet's accepted-work record, persisted
at ACCEPT time through the checksummed frame format.

ROADMAP item 1's durability hole: the fleet's assignment ledger and the
futures it resolves live in coordinator RAM, so host death orphans
every accepted request — ``PendingWork`` only covers a graceful
``stop(drain=False)``. This module closes it. Every acceptance appends
one checksummed frame (:func:`deequ_tpu.serve.transport.encode_frame` —
the wire envelope and the durable envelope are the SAME bytes) to an
append-only file, fsynced before the submit returns its future; every
resolution appends a tombstone. Recovery replays the file: accepted
minus tombstoned is exactly the work a dead coordinator still owed, and
a fresh coordinator re-dispatches it (onto the original futures when
the driver survived, fresh ones when it did not — the future's
first-resolution-wins gate keeps exactly-once either way).

Torn-write recovery mirrors the metrics repository's torn-SEGMENT
semantics (repository/columnar.py) at frame granularity: a record that
tears mid-append (crash between ``write`` and a complete frame) makes
the file's TAIL unreadable, never its head. ``mode="recover"`` (the
coordinator-resume default) quarantines ONLY that torn tail — the
damaged bytes move to a ``.corrupt`` sidecar (kept for forensics), the
file truncates to the last whole frame, and every prior record loads;
``mode="raise"`` surfaces the typed
:class:`~deequ_tpu.exceptions.CorruptStateException` instead. Damage
is never silently skipped: frames are sequential, so nothing after the
first tear is trusted.

The quarantine ledger rides along: each accept frame carries the
fleet's merged per-tenant quarantine snapshot, so a resumed coordinator
restores WHO was quarantined along with what was queued (the
``PendingWork`` contract, made durable).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.serve.transport import (
    dump_blob,
    encode_frame,
    load_blob,
    read_frame,
)

#: the one append-only ledger file inside a fleet's ledger_dir
LEDGER_FILENAME = "requests.dql"
#: torn tails recovered past are preserved here, never deleted
CORRUPT_SUFFIX = ".corrupt"


class RequestLedger:
    """Append-only checksummed record of fleet-accepted work (see
    module doc). Thread-safe appends (submit and resolve race from
    different threads); recovery runs once, at open."""

    def __init__(self, ledger_dir: str, mode: str = "recover"):
        if mode not in ("recover", "raise"):
            raise ValueError(
                f"mode must be 'recover' or 'raise', got {mode!r}"
            )
        self.ledger_dir = ledger_dir
        self.path = os.path.join(ledger_dir, LEDGER_FILENAME)
        self.mode = mode
        self._lock = threading.Lock()
        self.records: List[dict] = []
        self.torn_tail_bytes = 0
        os.makedirs(ledger_dir, exist_ok=True)
        self._recover()
        self._handle = open(self.path, "ab")

    # -- recovery --------------------------------------------------------

    def _recover(self) -> None:
        """Replay every whole frame; classify the first damage as a
        torn tail (quarantine or raise per ``mode``). The scan stops at
        the first tear — frames are sequential, nothing past it is
        trusted."""
        if not os.path.exists(self.path):
            return
        records: List[dict] = []
        good_end = 0
        error: Optional[CorruptStateException] = None
        with open(self.path, "rb") as f:
            while True:
                try:
                    msg = read_frame(
                        f, f"request ledger {LEDGER_FILENAME}"
                    )
                except CorruptStateException as e:
                    error = e
                    break
                if msg is None:
                    break
                records.append(msg)
                good_end = f.tell()
        self.records = records
        if error is None:
            return
        if self.mode == "raise":
            raise error
        # quarantine ONLY the torn tail: damaged bytes to the sidecar,
        # the ledger truncated to its last whole frame — every prior
        # record stays live (the repository torn-segment rule at frame
        # granularity)
        size = os.path.getsize(self.path)
        self.torn_tail_bytes = size - good_end
        with open(self.path, "rb") as f:
            f.seek(good_end)
            tail = f.read()
        with open(self.path + CORRUPT_SUFFIX, "ab") as sidecar:
            sidecar.write(tail)
            sidecar.flush()
            os.fsync(sidecar.fileno())
        with open(self.path, "ab") as f:
            f.truncate(good_end)
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        SCAN_STATS.record_degradation(
            "ledger_torn_tail", path=self.path,
            dropped_bytes=self.torn_tail_bytes, error=str(error),
        )

    # -- appends ---------------------------------------------------------

    def _append(self, record: dict) -> None:
        frame = encode_frame(record)
        with self._lock:
            self._handle.write(frame)
            self._handle.flush()
            os.fsync(self._handle.fileno())
            self.records.append(record)
        from deequ_tpu.obs.registry import LEDGER_APPENDS

        LEDGER_APPENDS.inc()

    def append_accept(
        self,
        accept_id: str,
        *,
        tenant: Any,
        digest: str,
        slo_cls: str,
        deadline_ms: Optional[float],
        weight: float,
        deadline_left_s: Optional[float],
        work: Any,
        quarantine: Optional[dict] = None,
    ) -> None:
        """One accepted request, durable BEFORE its future is returned:
        ``work`` is the (data, checks, required_analyzers) tuple —
        everything a fresh coordinator needs to re-dispatch.
        ``deadline_left_s`` is the deadline budget remaining at accept
        (an absolute monotonic stamp would be meaningless in the
        resuming process); ``accepted_wall`` (wall-clock, stamped here)
        lets resume subtract the dead time so a request does not get
        its deadline back just because the coordinator died."""
        self._append({
            "kind": "accept",
            "id": accept_id,
            "accepted_wall": time.time(),
            "tenant_blob": dump_blob(tenant),
            "digest": digest,
            "slo": {
                "cls": slo_cls,
                "deadline_ms": deadline_ms,
                "weight": weight,
            },
            "deadline_left_s": deadline_left_s,
            "work_blob": dump_blob(work),
            "quarantine_blob": (
                dump_blob(quarantine) if quarantine is not None else None
            ),
        })

    def append_resolve(self, accept_id: str) -> None:
        """The tombstone: this accepted request resolved (result OR
        typed rejection — either way the coordinator owes nothing)."""
        self._append({"kind": "resolve", "id": accept_id})

    # -- replay ----------------------------------------------------------

    def outstanding(self) -> Dict[str, dict]:
        """Accepted minus tombstoned, in accept order — the work a dead
        coordinator still owed."""
        out: Dict[str, dict] = {}
        for rec in self.records:
            if rec.get("kind") == "accept":
                out[rec["id"]] = rec
            elif rec.get("kind") == "resolve":
                out.pop(rec.get("id"), None)
        return out

    def latest_quarantine(self) -> Optional[dict]:
        """The most recent persisted quarantine snapshot (rides accept
        frames), for restore at resume."""
        snap = None
        for rec in self.records:
            blob = rec.get("quarantine_blob")
            if rec.get("kind") == "accept" and blob is not None:
                snap = blob
        return load_blob(snap, "ledger quarantine") if snap else None

    @staticmethod
    def load_work(rec: dict) -> Tuple[Any, tuple, tuple]:
        """Decode one accept record's (data, checks, required_analyzers)."""
        return load_blob(rec["work_blob"], "ledger work record")

    @staticmethod
    def load_tenant(rec: dict) -> Any:
        return load_blob(rec["tenant_blob"], "ledger tenant field")

    def close(self) -> None:
        with self._lock:
            try:
                self._handle.close()
            except OSError:
                pass
