"""Durable request ledger — the fleet's accepted-work record, persisted
at ACCEPT time through the checksummed frame format.

ROADMAP item 1's durability hole: the fleet's assignment ledger and the
futures it resolves live in coordinator RAM, so host death orphans
every accepted request — ``PendingWork`` only covers a graceful
``stop(drain=False)``. This module closes it. Every acceptance appends
one checksummed frame (:func:`deequ_tpu.serve.transport.encode_frame` —
the wire envelope and the durable envelope are the SAME bytes) to an
append-only file, fsynced before the submit returns its future; every
resolution appends a tombstone. Recovery replays the file: accepted
minus tombstoned is exactly the work a dead coordinator still owed, and
a fresh coordinator re-dispatches it (onto the original futures when
the driver survived, fresh ones when it did not — the future's
first-resolution-wins gate keeps exactly-once either way).

Torn-write recovery mirrors the metrics repository's torn-SEGMENT
semantics (repository/columnar.py) at frame granularity: a record that
tears mid-append (crash between ``write`` and a complete frame) makes
the file's TAIL unreadable, never its head. ``mode="recover"`` (the
coordinator-resume default) quarantines ONLY that torn tail — the
damaged bytes move to a counter-suffixed ``.corrupt`` sidecar (kept
for forensics; a second recovery never overwrites the first sidecar's
evidence), the file truncates to the last whole frame, and every prior
record loads; ``mode="raise"`` surfaces the typed
:class:`~deequ_tpu.exceptions.CorruptStateException` instead. Damage
is never silently skipped: frames are sequential, so nothing after the
first tear is trusted.

Epoch fencing (PR 18) rides every record: accepts, tombstones, and the
lightweight ``reaccept`` records a resuming coordinator appends all
carry the writer's lease epoch (:mod:`deequ_tpu.serve.lease`).
``outstanding()`` reconciles cross-epoch duplicates by epoch
precedence — when the same accept id appears under two epochs, the
HIGHEST epoch's record owns it — and counts stale-epoch tombstones, so
a zombie coordinator's late writes are visible in forensics but can
never resurrect or re-dispatch work its successor already owns.
``max_epoch()`` is the fencing floor a fresh coordinator feeds the
lease at acquire: even a destroyed lease file cannot regress the epoch
below what the ledger has witnessed.

The quarantine ledger rides along: each accept frame carries the
fleet's merged per-tenant quarantine snapshot, so a resumed coordinator
restores WHO was quarantined along with what was queued (the
``PendingWork`` contract, made durable).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.resilience.atomic import quarantine_path
from deequ_tpu.serve.transport import (
    dump_blob,
    encode_frame,
    load_blob,
    read_frame,
)

#: the one append-only ledger file inside a fleet's ledger_dir
LEDGER_FILENAME = "requests.dql"
#: torn tails recovered past are preserved here, never deleted
CORRUPT_SUFFIX = ".corrupt"


class RequestLedger:
    """Append-only checksummed record of fleet-accepted work (see
    module doc). Thread-safe appends (submit and resolve race from
    different threads); recovery runs once, at open."""

    def __init__(self, ledger_dir: str, mode: str = "recover"):
        if mode not in ("recover", "raise"):
            raise ValueError(
                f"mode must be 'recover' or 'raise', got {mode!r}"
            )
        self.ledger_dir = ledger_dir
        self.path = os.path.join(ledger_dir, LEDGER_FILENAME)
        self.mode = mode
        self._lock = threading.Lock()
        self.records: List[dict] = []
        self.torn_tail_bytes = 0
        #: reconciliation forensics, recomputed by each outstanding()
        self.cross_epoch_duplicates = 0
        self.cross_epoch_reaccepts = 0
        self.stale_tombstones = 0
        os.makedirs(ledger_dir, exist_ok=True)
        self._recover()
        # unbuffered: each frame goes down in ONE O_APPEND write(2), so
        # two live writers (a fenced zombie's last tombstones racing the
        # resumed coordinator's accepts, the partition seam) interleave
        # at frame granularity, never mid-frame
        self._handle = open(self.path, "ab", buffering=0)

    # -- recovery --------------------------------------------------------

    def _recover(self) -> None:
        """Replay every whole frame; classify the first damage as a
        torn tail (quarantine or raise per ``mode``). The scan stops at
        the first tear — frames are sequential, nothing past it is
        trusted."""
        if not os.path.exists(self.path):
            return
        records: List[dict] = []
        good_end = 0
        error: Optional[CorruptStateException] = None
        with open(self.path, "rb") as f:
            while True:
                try:
                    msg = read_frame(
                        f, f"request ledger {LEDGER_FILENAME}"
                    )
                except CorruptStateException as e:
                    error = e
                    break
                if msg is None:
                    break
                records.append(msg)
                good_end = f.tell()
        self.records = records
        if error is None:
            return
        if self.mode == "raise":
            raise error
        # quarantine ONLY the torn tail: damaged bytes to the sidecar,
        # the ledger truncated to its last whole frame — every prior
        # record stays live (the repository torn-segment rule at frame
        # granularity). The sidecar name is counter-suffixed: a second
        # torn-tail recovery must not overwrite the first's evidence
        size = os.path.getsize(self.path)
        self.torn_tail_bytes = size - good_end
        with open(self.path, "rb") as f:
            f.seek(good_end)
            tail = f.read()
        sidecar_path = quarantine_path(None, self.path, CORRUPT_SUFFIX)
        # deequ-lint: ignore[durable-write] -- quarantine sidecar: forensic copy of already-damaged bytes at a fresh (counter-suffixed) name, not reader-validated durable state
        with open(sidecar_path, "wb") as sidecar:
            sidecar.write(tail)
            sidecar.flush()
            os.fsync(sidecar.fileno())  # deequ-lint: ignore[durable-write] -- part of the annotated sidecar write above; the sidecar has no previous version to preserve, so temp+rename buys nothing
        with open(self.path, "ab") as f:
            f.truncate(good_end)
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        SCAN_STATS.record_degradation(
            "ledger_torn_tail", path=self.path,
            dropped_bytes=self.torn_tail_bytes, error=str(error),
        )

    # -- appends ---------------------------------------------------------

    def _append(self, record: dict) -> None:
        frame = encode_frame(record)
        with self._lock:
            self._handle.write(frame)
            self._handle.flush()
            os.fsync(self._handle.fileno())  # deequ-lint: ignore[durable-write] -- the ledger is APPEND-ONLY by protocol: fsync-per-frame with torn-tail recovery; routing each record through temp+rename would rewrite the whole file per accept (O(N) per append)
            self.records.append(record)
        from deequ_tpu.obs.registry import LEDGER_APPENDS

        LEDGER_APPENDS.inc()

    def append_accept(
        self,
        accept_id: str,
        *,
        tenant: Any,
        digest: str,
        slo_cls: str,
        deadline_ms: Optional[float],
        weight: float,
        deadline_left_s: Optional[float],
        work: Any,
        quarantine: Optional[dict] = None,
        epoch: int = 0,
    ) -> None:
        """One accepted request, durable BEFORE its future is returned:
        ``work`` is the (data, checks, required_analyzers) tuple —
        everything a fresh coordinator needs to re-dispatch.
        ``deadline_left_s`` is the deadline budget remaining at accept
        (an absolute monotonic stamp would be meaningless in the
        resuming process); ``accepted_wall`` (wall-clock, stamped here)
        lets resume subtract the dead time so a request does not get
        its deadline back just because the coordinator died. ``epoch``
        is the writer's lease epoch (0 = unfenced)."""
        self._append({
            "kind": "accept",
            "id": accept_id,
            "epoch": int(epoch),
            "accepted_wall": time.time(),
            "tenant_blob": dump_blob(tenant),
            "digest": digest,
            "slo": {
                "cls": slo_cls,
                "deadline_ms": deadline_ms,
                "weight": weight,
            },
            "deadline_left_s": deadline_left_s,
            "work_blob": dump_blob(work),
            "quarantine_blob": (
                dump_blob(quarantine) if quarantine is not None else None
            ),
        })

    def append_resolve(self, accept_id: str, epoch: int = 0) -> None:
        """The tombstone: this accepted request resolved (result OR
        typed rejection — either way the coordinator owes nothing).
        ``epoch`` stamps the resolving writer; a stale-epoch tombstone
        still tombstones (the future's first-resolution-wins gate
        already fired — the work IS done) but is counted so forensics
        can see a zombie's late writes."""
        self._append({
            "kind": "resolve", "id": accept_id, "epoch": int(epoch),
        })

    def append_reaccept(self, accept_id: str, epoch: int) -> None:
        """A resuming coordinator's lightweight ownership claim over one
        replayed accept: re-stamps the record's effective epoch WITHOUT
        re-pickling its blobs, so a third coordinator resuming after
        this one sees who owned the request last — and the zombie that
        originally accepted it loses the epoch-precedence comparison."""
        self._append({
            "kind": "reaccept", "id": accept_id, "epoch": int(epoch),
        })

    # -- replay ----------------------------------------------------------

    @staticmethod
    def _epoch_of(rec: Optional[dict]) -> int:
        return int((rec or {}).get("epoch") or 0)

    def outstanding(self) -> Dict[str, dict]:
        """Accepted minus tombstoned, in accept order — the work a dead
        coordinator still owed. Cross-epoch reconciliation: a duplicate
        accept under two epochs resolves to the HIGHEST epoch's record
        (the zombie's copy is forensics, not work); a ``reaccept``
        re-stamps the stored record's effective epoch; tombstones pop
        regardless of writer epoch — the resolution gate already fired,
        so the request is settled however stale its tombstoner — with
        stale-epoch tombstones counted on ``stale_tombstones``."""
        out: Dict[str, dict] = {}
        self.cross_epoch_duplicates = 0
        self.cross_epoch_reaccepts = 0
        self.stale_tombstones = 0
        for rec in self.records:
            kind = rec.get("kind")
            if kind == "accept":
                prev = out.get(rec["id"])
                if prev is not None:
                    self.cross_epoch_duplicates += 1
                    if self._epoch_of(rec) < self._epoch_of(prev):
                        continue  # the stale duplicate loses
                out[rec["id"]] = rec
            elif kind == "reaccept":
                prev = out.get(rec.get("id"))
                if prev is not None and (
                    self._epoch_of(rec) > self._epoch_of(prev)
                ):
                    merged = dict(prev)
                    merged["epoch"] = self._epoch_of(rec)
                    out[rec["id"]] = merged
                    self.cross_epoch_reaccepts += 1
            elif kind == "resolve":
                popped = out.pop(rec.get("id"), None)
                if popped is not None and (
                    self._epoch_of(rec) < self._epoch_of(popped)
                ):
                    self.stale_tombstones += 1
        return out

    def max_epoch(self) -> int:
        """The highest epoch any record has witnessed — the fencing
        floor a fresh coordinator feeds ``CoordinatorLease.acquire``
        (a destroyed lease file must never regress the epoch)."""
        return max(
            (self._epoch_of(r) for r in self.records), default=0,
        )

    def latest_quarantine(self) -> Optional[dict]:
        """The most recent persisted quarantine snapshot (rides accept
        frames), for restore at resume."""
        snap = None
        for rec in self.records:
            blob = rec.get("quarantine_blob")
            if rec.get("kind") == "accept" and blob is not None:
                snap = blob
        return load_blob(snap, "ledger quarantine") if snap else None

    @staticmethod
    def load_work(rec: dict) -> Tuple[Any, tuple, tuple]:
        """Decode one accept record's (data, checks, required_analyzers)."""
        return load_blob(rec["work_blob"], "ledger work record")

    @staticmethod
    def load_tenant(rec: dict) -> Any:
        return load_blob(rec["tenant_blob"], "ledger tenant field")

    def close(self) -> None:
        with self._lock:
            try:
                self._handle.close()
            except OSError:
                pass
