"""Frame transport for the process fleet — length-prefixed checksummed
messages over sockets, pipes, or an in-process loopback.

The coordinator (:mod:`deequ_tpu.serve.pfleet`) and its worker
processes (:mod:`deequ_tpu.serve.pworker`) speak a frame protocol whose
envelope is the SAME checksummed format the resilience tier persists
with (:mod:`deequ_tpu.resilience.atomic`): ``DQX1 | crc32(u32 LE) |
length(i64 LE) | payload``. One format serves both the wire and the
durable request ledger (:mod:`deequ_tpu.serve.ledger`) — a frame read
off a socket and a frame replayed off disk validate through the
identical ``unwrap_checksum`` path, and a torn read on either surfaces
the same typed :class:`~deequ_tpu.exceptions.CorruptStateException`.

Message payloads are JSON objects (the control fields stay greppable on
the wire and in the ledger: ids, tenants, SLO class, ``retry_after_s``,
queue depths). Python values JSON cannot carry — tables, checks,
analyzers, results, typed exceptions, quarantine snapshots — ride as
``blob`` fields: base64 text over pickle. That is a deliberate trust
decision scoped to this transport's deployment shape (coordinator and
workers are the SAME code on the SAME machine under one uid, exactly
like multiprocessing's own pickle pipes); the transport never accepts
frames from a network listener.

Transports are INJECTABLE (the ``check_peers`` probe discipline applied
to the data plane): :class:`SocketTransport` wraps a real socketpair fd
shared with a spawned worker process, :class:`LoopbackTransport` wraps
a pair of in-process queues so the identical protocol loop runs
deterministically in a thread — tests and single-process deployments
exercise the same frames, acks, refusals, and quarantine merges without
paying process spawn.
"""

from __future__ import annotations

import base64
import io
import json
import pickle
import queue
import select
import socket
import struct
import threading
from typing import Any, Optional

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.resilience.atomic import (
    CHECKSUM_MAGIC,
    unwrap_checksum,
    wrap_checksum,
)

#: envelope header size: magic(4) + crc32(4) + length(8)
FRAME_HEADER_BYTES = 16

_i64 = struct.Struct("<q")

#: refuse frames whose declared payload length is absurd — a corrupted
#: length field must surface typed, not as a multi-GB allocation
MAX_FRAME_BYTES = 1 << 30


# -- python-object blob fields ------------------------------------------------


try:
    # constraint assertions are closures/lambdas: stdlib pickle cannot
    # ship a Check across the process boundary, cloudpickle can (it
    # serializes the code object; the result still LOADS through plain
    # ``pickle.loads``). Fall back to stdlib pickle where cloudpickle
    # is absent — picklable payloads (tables, results, exceptions,
    # quarantine snapshots) keep working; lambda-bearing checks then
    # surface a normal PicklingError at submit.
    import cloudpickle as _blob_pickler
except ImportError:  # pragma: no cover - cloudpickle ships with jax stacks
    _blob_pickler = pickle


def dump_blob(obj: Any) -> str:
    """Python object -> base64 text for a JSON ``blob`` field."""
    return base64.b64encode(
        _blob_pickler.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    ).decode("ascii")


def load_blob(text: str, what: str = "transport blob") -> Any:
    """Base64 ``blob`` field -> Python object; typed
    CorruptStateException on undecodable bytes (damage is a state
    fault, not a code fault)."""
    try:
        return pickle.loads(base64.b64decode(text.encode("ascii")))
    except (ValueError, pickle.UnpicklingError, EOFError,
            AttributeError, ImportError) as e:
        raise CorruptStateException(what, f"undecodable blob: {e}") from e


# -- frame codec --------------------------------------------------------------


def encode_frame(msg: dict) -> bytes:
    """Message dict -> one checksummed wire/ledger frame."""
    return wrap_checksum(
        json.dumps(msg, sort_keys=True, separators=(",", ":")).encode(
            "utf-8"
        )
    )


def decode_frame(frame: bytes, what: str = "transport frame") -> dict:
    """One complete frame -> message dict; typed on any damage."""
    payload = unwrap_checksum(frame, what)
    try:
        msg = json.loads(payload.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise CorruptStateException(
            what, f"checksum passed but payload is not JSON: {e}"
        ) from e
    if not isinstance(msg, dict):
        raise CorruptStateException(
            what, f"frame payload is {type(msg).__name__}, not an object"
        )
    return msg


def read_frame(stream: io.RawIOBase, what: str = "transport frame"
               ) -> Optional[dict]:
    """Read one frame off a blocking byte stream. Returns None on clean
    EOF at a frame boundary; raises typed CorruptStateException on a
    torn frame (EOF mid-header or mid-payload, bad magic, bad length,
    crc mismatch)."""
    header = _read_exact(stream, FRAME_HEADER_BYTES)
    if header is None:
        return None
    if len(header) < FRAME_HEADER_BYTES:
        raise CorruptStateException(
            what, f"torn frame: EOF after {len(header)} header bytes"
        )
    if header[:4] != CHECKSUM_MAGIC:
        raise CorruptStateException(what, "bad frame magic")
    (length,) = _i64.unpack_from(header, 8)
    if not 0 <= length <= MAX_FRAME_BYTES:
        raise CorruptStateException(
            what, f"implausible frame length {length}"
        )
    body = _read_exact(stream, length) if length else b""
    if body is None or len(body) < length:
        got = 0 if body is None else len(body)
        raise CorruptStateException(
            what, f"torn frame: EOF after {got} of {length} payload bytes"
        )
    return decode_frame(header + body, what)


def _read_exact(stream, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes; None on immediate EOF, short bytes on
    EOF mid-read (the caller classifies torn vs clean)."""
    chunks = []
    got = 0
    while got < n:
        chunk = stream.read(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    if not chunks:
        return None
    return b"".join(chunks)


# -- transports ---------------------------------------------------------------


class TransportClosedError(ConnectionError):
    """The peer endpoint is gone (clean close or process death). A
    ConnectionError subtype on purpose: the coordinator's receive loop
    treats it exactly like a died socket — worker loss, not state
    corruption."""


class Transport:
    """One endpoint of a bidirectional frame channel. ``send`` is
    thread-safe (a worker's service thread resolves results while its
    protocol thread acks submissions); ``recv`` is single-consumer."""

    def send(self, msg: dict) -> None:
        raise NotImplementedError

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        """Next message, or None when ``timeout`` elapses with nothing
        to read. Raises TransportClosedError once the peer is gone and
        everything already received has been drained."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError


class SocketTransport(Transport):
    """Frames over a connected stream socket (a ``socketpair`` whose
    other fd was inherited by the worker process). SIGKILLing the peer
    surfaces here as EOF/ECONNRESET -> TransportClosedError — the
    process fleet's loss signal."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._sock.setblocking(True)
        self._send_lock = threading.Lock()
        self._closed = False

    def fileno(self) -> int:
        return self._sock.fileno()

    def send(self, msg: dict) -> None:
        frame = encode_frame(msg)
        with self._send_lock:
            if self._closed:
                raise TransportClosedError("transport is closed")
            try:
                self._sock.sendall(frame)
            except OSError as e:
                raise TransportClosedError(
                    f"peer gone during send: {e}"
                ) from e

    def _recv_exact(self, n: int) -> Optional[bytes]:
        """Exactly ``n`` bytes off the socket; None on EOF before the
        first byte, short bytes on EOF mid-read."""
        chunks = []
        got = 0
        while got < n:
            try:
                chunk = self._sock.recv(n - got)
            except OSError as e:
                raise TransportClosedError(f"peer gone: {e}") from e
            if not chunk:
                break
            chunks.append(chunk)
            got += len(chunk)
        if not chunks:
            return None
        return b"".join(chunks)

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        if self._closed:
            raise TransportClosedError("transport is closed")
        # the timeout gates frame ARRIVAL only: once the first byte of
        # a frame is in, the read blocks to the frame boundary — a poll
        # timeout must never tear a frame in half
        if timeout is not None:
            try:
                ready, _, _ = select.select([self._sock], [], [], timeout)
            except OSError as e:
                raise TransportClosedError(f"peer gone: {e}") from e
            if not ready:
                return None
        what = "socket frame"
        header = self._recv_exact(FRAME_HEADER_BYTES)
        if header is None:
            raise TransportClosedError("peer closed the channel")
        if len(header) < FRAME_HEADER_BYTES:
            raise CorruptStateException(
                what, f"torn frame: EOF after {len(header)} header bytes"
            )
        if header[:4] != CHECKSUM_MAGIC:
            raise CorruptStateException(what, "bad frame magic")
        (length,) = _i64.unpack_from(header, 8)
        if not 0 <= length <= MAX_FRAME_BYTES:
            raise CorruptStateException(
                what, f"implausible frame length {length}"
            )
        body = self._recv_exact(length) if length else b""
        if body is None or len(body) < length:
            got = 0 if body is None else len(body)
            raise CorruptStateException(
                what,
                f"torn frame: EOF after {got} of {length} payload bytes",
            )
        return decode_frame(header + body, what)

    def close(self) -> None:
        with self._send_lock:
            self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class LoopbackTransport(Transport):
    """In-process frame channel: a pair of queues carrying ENCODED
    frames (encode/decode run for real, so a loopback test exercises
    the same serialization the socket path does — a table that cannot
    pickle fails identically on both)."""

    def __init__(self, inbox: "queue.Queue", outbox: "queue.Queue"):
        self._inbox = inbox
        self._outbox = outbox
        self._closed = threading.Event()
        self._peer_closed = threading.Event()

    @staticmethod
    def pair() -> "tuple[LoopbackTransport, LoopbackTransport]":
        a_to_b: "queue.Queue" = queue.Queue()
        b_to_a: "queue.Queue" = queue.Queue()
        a = LoopbackTransport(inbox=b_to_a, outbox=a_to_b)
        b = LoopbackTransport(inbox=a_to_b, outbox=b_to_a)
        a._peer = b  # type: ignore[attr-defined]
        b._peer = a  # type: ignore[attr-defined]
        return a, b

    def send(self, msg: dict) -> None:
        if self._closed.is_set() or self._peer_closed.is_set():
            raise TransportClosedError("loopback peer is closed")
        self._outbox.put(encode_frame(msg))

    def recv(self, timeout: Optional[float] = None) -> Optional[dict]:
        if self._closed.is_set():
            raise TransportClosedError("transport is closed")
        try:
            frame = self._inbox.get(
                timeout=timeout if timeout is not None else None
            )
        except queue.Empty:
            if self._peer_closed.is_set():
                raise TransportClosedError("peer closed the channel")
            return None
        if frame is None:  # the peer's close sentinel
            self._peer_closed.set()
            raise TransportClosedError("peer closed the channel")
        return decode_frame(frame, "loopback frame")

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        peer = getattr(self, "_peer", None)
        if peer is not None:
            peer._peer_closed.set()
        self._outbox.put(None)
