"""Consistent-hash tenant placement — plan-cache locality that survives
worker join/leave.

The fleet's whole performance story is per-worker plan-cache locality: a
tenant whose suite fingerprint keeps landing on the same worker reuses
that worker's built ops, traced programs, and lint verdicts forever
(the Flare amortization, arXiv:1703.08219 — compilation only wins when
its cost is amortized across repeated executions). A modulo router
would reshuffle EVERY tenant on any membership change and pay a fleet-
wide recompilation storm at exactly the worst moment (a worker just
died). Consistent hashing bounds the blast radius: each worker owns
``VNODES`` pseudo-random arcs of a hash ring, a key maps to the first
vnode clockwise, and removing a worker moves ONLY the keys that worker
owned — every other tenant keeps its warm cache.

The routing key (:func:`route_digest`) is the admission-free prefix of
the plan fingerprint — (schema, analyzer set, row count) — hashable
before any op build, so placement costs one SHA1 over a repr. It is
deliberately coarser than :class:`~deequ_tpu.serve.plan_cache.PlanKey`
(no layout signature: layouts are data-dependent and unknowable pre-
admission); two suites that share a digest but split into distinct
PlanKeys still both benefit — they land on one worker and each warm
their own cache entry there.

Hashes are ``hashlib`` digests, NOT Python ``hash()``: placement must be
stable across processes and runs (PYTHONHASHSEED randomizes ``hash()``),
or a restarted fleet would scatter every tenant's locality.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Any, List, Optional, Sequence, Tuple

#: ring arcs per worker — enough that 4-16 workers split keys within a
#: few percent of even, few enough that membership ops stay trivial
VNODES = 64


def _hash64(text: str) -> int:
    return int.from_bytes(
        hashlib.sha1(text.encode()).digest()[:8], "little"
    )


def route_digest(data, analyzers: Sequence) -> str:
    """The fleet routing key for one submission: a stable digest of
    (column schema, analyzer set, row count). Analyzers contribute their
    ``str`` form (parameters included); streaming/count-less sources
    contribute row count 0 — they route consistently even though they
    will serve on the serial path."""
    try:
        schema = tuple(sorted(
            (name, str(data[name].dtype)) for name in data.column_names
        ))
    except (AttributeError, TypeError):
        schema = ()
    try:
        rows = int(data.num_rows or 0)
    except (AttributeError, TypeError):
        rows = 0
    payload = repr((schema, tuple(str(a) for a in analyzers), rows))
    return hashlib.sha1(payload.encode()).hexdigest()


class ConsistentHashRouter:
    """The fleet's placement function (see module doc). Lock-serialized:
    membership changes (monitor thread) race submissions (caller
    threads)."""

    def __init__(self, vnodes: int = VNODES):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = vnodes
        self._lock = threading.Lock()
        self._points: List[int] = []      # sorted vnode positions
        self._owner: dict = {}            # position -> worker id

    def add_worker(self, worker_id: Any) -> None:
        with self._lock:
            for v in range(self.vnodes):
                pos = _hash64(f"{worker_id}#{v}")
                # a (vanishingly unlikely) collision keeps the earlier
                # owner: deterministic, and the later worker still owns
                # its other vnodes
                if pos in self._owner:
                    continue
                self._owner[pos] = worker_id
                bisect.insort(self._points, pos)

    def remove_worker(self, worker_id: Any) -> None:
        with self._lock:
            dead = [p for p, w in self._owner.items() if w == worker_id]
            for pos in dead:
                del self._owner[pos]
            if dead:
                gone = set(dead)
                self._points = [p for p in self._points if p not in gone]

    def workers(self) -> Tuple[Any, ...]:
        with self._lock:
            return tuple(sorted(set(self._owner.values()), key=repr))

    def place(self, digest: str) -> Optional[Any]:
        """The worker owning ``digest``'s ring position (first vnode
        clockwise, wrapping); None when the ring is empty."""
        point = _hash64(digest)
        with self._lock:
            if not self._points:
                return None
            i = bisect.bisect_right(self._points, point)
            if i == len(self._points):
                i = 0
            return self._owner[self._points[i]]

    def walk(self, digest: str) -> List[Any]:
        """Every distinct worker, in clockwise ring order starting at
        ``digest``'s position — ``walk(d)[0] == place(d)``. The fleet's
        OVERLOAD SPILL order (round 15): when the placed worker refuses
        admission typed (queue full, class budget, brownout), spilling
        to the next worker clockwise trades that request's plan-cache
        locality for goodput — the same deterministic fallback order a
        failed-over key would take, so a spilled repeat tenant lands
        where its plans will migrate anyway. Empty when the ring is."""
        point = _hash64(digest)
        with self._lock:
            if not self._points:
                return []
            i = bisect.bisect_right(self._points, point)
            out: List[Any] = []
            seen = set()
            for k in range(len(self._points)):
                owner = self._owner[
                    self._points[(i + k) % len(self._points)]
                ]
                if owner not in seen:
                    seen.add(owner)
                    out.append(owner)
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(set(self._owner.values()))
