"""Heartbeat-driven fleet membership — the ``check_peers`` seam applied
in-process.

The multi-host tier already solved worker liveness once: ``check_peers``
(parallel/distributed.py) runs an INJECTABLE probe — ``probe(timeout) ->
responsive member ids`` — attributes the losses, and either raises typed
or returns a degradation report. The fleet reuses that exact seam
(:func:`~deequ_tpu.parallel.distributed.probe_liveness` is the factored-
out attribution step) with a different default probe: instead of
barriers over the jax.distributed KV store, each worker's liveness is
its service thread being alive AND its ``heartbeat`` (bumped every
worker-loop iteration) being fresher than ``stall_timeout``. A worker
wedged inside a dispatch looks exactly like a dead one — which is the
point: both stop serving their queue, both need failover.

Losses surface as typed
:class:`~deequ_tpu.exceptions.WorkerLostException` (the fleet analogue
of ``PeerLostException``, same ``DeviceException`` taxonomy) or, with
``on_worker_loss="degrade"``, as a :class:`WorkerLossReport` the fleet's
failover path consumes. A background monitor thread polls every
``interval`` seconds (``DEEQU_TPU_HEARTBEAT_INTERVAL``) and invokes the
fleet's loss callback — heartbeat-driven membership, no human in the
loop.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

from deequ_tpu.exceptions import WorkerLostException
from deequ_tpu.parallel.distributed import (
    run_liveness_check,
    validate_loss_mode,
)


@dataclass
class WorkerLossReport:
    """The outcome of one fleet liveness check (mirrors
    ``PeerLossReport``): ``lost`` names the worker ids that stopped
    responding; ``surviving`` the rest."""

    n_workers: int
    surviving: List[int] = field(default_factory=list)
    lost: List[int] = field(default_factory=list)

    @property
    def degraded(self) -> bool:
        return bool(self.lost)


class FleetMembership:
    """Liveness tracking over one fleet's workers (see module doc).

    ``members()`` yields the worker ids currently expected alive;
    ``probe_of(worker_id)`` returns (thread_alive, heartbeat_monotonic)
    for one of them; ``on_loss(worker_id, exc)`` is the fleet's failover
    callback, invoked by the monitor once per newly-lost worker."""

    def __init__(
        self,
        members: Callable[[], Sequence[int]],
        probe_of: Callable[[int], tuple],
        on_loss: Callable[[int, WorkerLostException], None],
        interval: float = 0.25,
        stall_timeout: float = 2.0,
    ):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        if stall_timeout <= 0:
            raise ValueError(
                f"stall_timeout must be > 0, got {stall_timeout}"
            )
        self._members = members
        self._probe_of = probe_of
        self._on_loss = on_loss
        self.interval = float(interval)
        self.stall_timeout = float(stall_timeout)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the probe (the check_peers seam's in-process default) -----------

    def _default_probe(self, timeout: float) -> List[int]:
        """Responsive worker ids: service thread alive and heartbeat
        fresher than ``stall_timeout``. Same contract as the multi-host
        KV-store probe — a plain callable tests (and the chaos engine)
        can replace."""
        now = time.monotonic()
        alive = []
        for wid in self._members():
            thread_alive, heartbeat = self._probe_of(wid)
            if thread_alive and (now - heartbeat) <= self.stall_timeout:
                alive.append(wid)
        return alive

    # -- one check (check_peers semantics) -------------------------------

    def check_workers(
        self,
        timeout: Optional[float] = None,
        on_worker_loss: str = "fail",
        probe: Optional[Callable[[float], Sequence[int]]] = None,
    ) -> WorkerLossReport:
        """Verify every expected worker is responsive — the fleet twin
        of ``check_peers``. ``"fail"`` raises typed
        ``WorkerLostException`` naming the lost workers; ``"degrade"``
        returns the report for the caller's failover path."""
        validate_loss_mode(on_worker_loss, "on_worker_loss")
        expected = sorted(self._members())
        report = WorkerLossReport(n_workers=len(expected))
        if not expected:
            return report
        probe = probe or self._default_probe
        # unattributable stall: every worker is suspect — even
        # "degrade" cannot pick a failover target, so the shared core
        # raises typed (the check_peers rule, one implementation)
        alive, lost = run_liveness_check(
            expected,
            timeout if timeout is not None else self.stall_timeout,
            probe,
            lambda e: WorkerLostException(
                f"fleet liveness probe timed out unattributably: {e}",
                worker_ids=tuple(expected),
            ),
        )
        report.surviving = alive
        report.lost = lost
        if lost and on_worker_loss == "fail":
            raise WorkerLostException(
                f"lost contact with fleet worker(s) {lost} "
                f"(surviving: {alive}); their accepted requests need "
                "failover re-dispatch",
                worker_ids=tuple(lost),
            )
        return report

    # -- the monitor -----------------------------------------------------

    def poll(self) -> WorkerLossReport:
        """One monitor tick: check liveness, fire ``on_loss`` for every
        newly-lost worker (degrade mode — the fleet fails over instead
        of aborting)."""
        report = self.check_workers(on_worker_loss="degrade")
        for wid in report.lost:
            self._on_loss(
                wid,
                WorkerLostException(
                    f"worker {wid} stopped heartbeating "
                    f"(stall_timeout={self.stall_timeout:g}s)",
                    worker_ids=(wid,),
                ),
            )
        return report

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._monitor, daemon=True, name="deequ-tpu-fleet-hb"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def _monitor(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll()
            except WorkerLostException:
                # unattributable probe timeout: nothing to fail over TO
                # with confidence; keep monitoring — the next tick
                # usually attributes (a genuinely dead fleet surfaces
                # typed on the next submit)
                continue
            # deequ-lint: ignore[bare-except] -- monitor survival backstop: on_loss runs failover over tenant-influenced state (budget finalize evaluates the tenant's own checks); one bad tenant must not kill liveness detection fleet-wide — the error lands in the degradation ledger and the next tick retries
            except Exception as e:  # noqa: BLE001
                try:
                    from deequ_tpu.ops.scan_engine import SCAN_STATS

                    SCAN_STATS.record_degradation(
                        "fleet_monitor_error", error=str(e),
                        kind_of_error=type(e).__name__,
                    )
                except ImportError:
                    pass
                continue
