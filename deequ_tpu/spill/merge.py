"""K-way merge of sorted frequency runs — the finalize path of the spill
engine.

Classic external merge with vectorized slicing instead of a per-row heap:
each source buffers ONE block; every iteration picks the smallest
last-key among the buffers (the *boundary*), slices the ``<= boundary``
prefix off every buffer (a vectorized prefix mask — buffers are sorted),
merge-adds the prefixes (codes + lexsort + reduceat, the monoid merge),
and emits the result. Any future row of any run is strictly greater than
its buffer's last key, hence greater than the boundary, so emitted keys
are final — exactly the argument behind a loser-tree merge, paid per
block instead of per row. At least one buffer empties per iteration, so
memory stays O(sources x block_bytes) and progress is guaranteed.

Fan-in is bounded: merging more runs than the memory budget can buffer
blocks for goes through intermediate merge passes (merge fanin runs ->
one wider run on disk, repeat), the textbook external-sort cascade.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.spill.order import (
    compare_keys,
    key_at,
    leq_boundary,
    merge_add_sorted,
)
from deequ_tpu.spill.runs import Block, RunReader, write_run


def _cast_column(values: np.ndarray, nulls: np.ndarray, target) -> np.ndarray:
    """Cast one key column to the store's promoted dtype. String targets
    skip casting (numpy compares unicode across widths natively); an
    all-null column of the wrong kind (a legacy placeholder) is replaced
    by zeros of the target, mirroring FrequenciesAndNumRows.sum."""
    if target is None or values.dtype == target:
        return values
    if target.kind == "U" or values.dtype.kind == "U":
        if values.dtype.kind != target.kind:
            if bool(nulls.all()):
                return np.zeros(len(values), dtype=target)
            raise ValueError(
                f"cannot merge spill blocks with mismatched key kinds "
                f"({values.dtype} vs {target})"
            )
        return values
    return values.astype(target)


class _Source:
    """One merge input: an iterator of sorted blocks + its buffer."""

    def __init__(self, blocks: Iterator[Block], dtypes):
        self._blocks = blocks
        self._dtypes = dtypes
        self.kv: Optional[Tuple[np.ndarray, ...]] = None
        self.kn: Optional[Tuple[np.ndarray, ...]] = None
        self.counts: Optional[np.ndarray] = None
        self.refill()

    def refill(self) -> bool:
        """Pull the next non-empty block; False when exhausted."""
        for kv, kn, counts in self._blocks:
            if len(counts) == 0:
                continue
            if self._dtypes is not None:
                kv = tuple(
                    _cast_column(v, m, t)
                    for v, m, t in zip(kv, kn, self._dtypes)
                )
            self.kv, self.kn, self.counts = tuple(kv), tuple(kn), counts
            return True
        self.kv = self.kn = self.counts = None
        return False

    @property
    def last_key(self):
        return key_at(self.kv, self.kn, len(self.counts) - 1)

    def take_prefix(self, boundary) -> Optional[Block]:
        """Slice off (and return) the ``<= boundary`` prefix; refills the
        buffer when fully consumed."""
        mask = leq_boundary(self.kv, self.kn, boundary)
        k = int(mask.sum())
        if k == 0:
            return None
        part = (
            tuple(v[:k] for v in self.kv),
            tuple(m[:k] for m in self.kn),
            self.counts[:k],
        )
        if k == len(self.counts):
            self.refill()
        else:
            self.kv = tuple(v[k:] for v in self.kv)
            self.kn = tuple(m[k:] for m in self.kn)
            self.counts = self.counts[k:]
        return part


def merge_block_streams(
    streams: Sequence[Iterator[Block]],
    dtypes=None,
    out_groups: int = 1 << 20,
) -> Iterator[Block]:
    """Merge canonically sorted, per-stream-unique block streams into one
    sorted, globally-unique block stream (blocks re-chunked to at most
    ``out_groups`` groups)."""
    sources = [_Source(s, dtypes) for s in streams]
    sources = [s for s in sources if s.counts is not None]
    while sources:
        if len(sources) == 1:
            # sole remaining source: its keys cannot collide with anything
            src = sources[0]
            while src.counts is not None:
                kv, kn, counts = src.kv, src.kn, src.counts
                src.refill()
                for start in range(0, len(counts), out_groups):
                    end = start + out_groups
                    yield (
                        tuple(v[start:end] for v in kv),
                        tuple(m[start:end] for m in kn),
                        counts[start:end],
                    )
            return
        boundary = sources[0].last_key
        for src in sources[1:]:
            if compare_keys(src.last_key, boundary) < 0:
                boundary = src.last_key
        parts = []
        for src in sources:
            part = src.take_prefix(boundary)
            if part is not None:
                parts.append(part)
        sources = [s for s in sources if s.counts is not None]
        if not parts:  # defensive: boundary owner always contributes
            continue
        if len(parts) == 1:
            kv, kn, counts = parts[0]
        else:
            kv, kn, counts = merge_add_sorted(parts)
        for start in range(0, len(counts), out_groups):
            end = start + out_groups
            yield (
                tuple(v[start:end] for v in kv),
                tuple(m[start:end] for m in kn),
                counts[start:end],
            )


def collapse_runs(
    paths: Sequence[str],
    n_cols: int,
    dtypes=None,
    out_groups: int = 1 << 20,
    max_fanin: int = 16,
    scratch_dir: Optional[str] = None,
) -> List[str]:
    """Cascade merge passes until at most ``max_fanin`` runs remain (the
    textbook external sort: merge fanin runs -> one wider run on disk,
    repeat). Consumed input runs are unlinked; the returned collapsed run
    set is durable, so a caller that streams the final merge repeatedly
    (count stats, Histogram top-N, MI's two passes, serde encode) pays
    the cascade's disk I/O ONCE and only the in-memory final merge per
    pass afterwards."""
    from deequ_tpu.ops.scan_engine import SCAN_STATS

    paths = list(paths)
    pass_idx = 0
    while len(paths) > max_fanin:
        SCAN_STATS.spill_merge_passes += 1
        next_paths: List[str] = []
        for i in range(0, len(paths), max_fanin):
            chunk = paths[i:i + max_fanin]
            if len(chunk) == 1:
                next_paths.append(chunk[0])
                continue
            base = scratch_dir or os.path.dirname(chunk[0])
            out = os.path.join(
                base, f"merge_p{pass_idx}_{i // max_fanin:04d}.run"
            )
            readers = [RunReader(p) for p in chunk]
            writer = write_run(
                out,
                merge_block_streams(
                    [r.blocks() for r in readers], dtypes, out_groups
                ),
                n_cols,
            )
            SCAN_STATS.spill_bytes_written += writer.bytes_written
            SCAN_STATS.spill_bytes_read += sum(
                r.bytes_read for r in readers
            )
            for p in chunk:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            next_paths.append(out)
        paths = next_paths
        pass_idx += 1
    return paths


def merge_runs(
    paths: Sequence[str],
    n_cols: int,
    dtypes=None,
    out_groups: int = 1 << 20,
    max_fanin: int = 16,
    scratch_dir: Optional[str] = None,
) -> Iterator[Block]:
    """Stream the merged blocks of a run set. More runs than ``max_fanin``
    first collapse through disk passes (see collapse_runs — NOTE: that
    consumes the input runs; callers that re-stream should call
    collapse_runs themselves and keep the returned set, as
    SpillingFrequencyStore.blocks does), so peak memory stays
    O(max_fanin x block_bytes) no matter how many runs spilled."""
    from deequ_tpu.ops.scan_engine import SCAN_STATS

    paths = collapse_runs(
        paths, n_cols, dtypes=dtypes, out_groups=out_groups,
        max_fanin=max_fanin, scratch_dir=scratch_dir,
    )
    # the final in-memory merge is NOT counted in spill_merge_passes:
    # consumers re-stream it per pass (count stats, Histogram top-N,
    # serde), and counting those would inflate the cascade telemetry
    readers = [RunReader(p) for p in paths]
    try:
        yield from merge_block_streams(
            [r.blocks() for r in readers], dtypes, out_groups
        )
    finally:
        SCAN_STATS.spill_bytes_read += sum(r.bytes_read for r in readers)
