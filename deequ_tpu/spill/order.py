"""Canonical key order for spill runs, and the vectorized comparisons the
k-way merger needs.

The order is fixed by what the existing monoid merge already produces
(``FrequenciesAndNumRows.sum`` lexsorts per-column ``np.unique`` codes with
the FIRST column most significant): per column, null < every value, values
ascend, and float NaN collapses to one key that sorts after every finite
value. Everything here implements that order three ways — a full sort of a
block, a vectorized row-vs-boundary comparison, and a python-level
boundary-vs-boundary comparison — which MUST stay mutually consistent; the
randomized spill-equivalence sweep in tests/test_spill.py exercises all
three against each other.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as np

# a boundary key: one cell per column; None = null. NaN cells compare equal
# to each other and greater than every non-NaN value.
Key = Tuple[object, ...]


def _code_column(values: np.ndarray, nulls: np.ndarray) -> np.ndarray:
    """Dense rank codes in canonical order (0 = null, NaN last) — the same
    factorization FrequenciesAndNumRows._code_columns performs."""
    if values.dtype.kind == "f":
        _, inv = np.unique(values, return_inverse=True, equal_nan=True)
    else:
        _, inv = np.unique(values, return_inverse=True)
    return np.where(nulls, 0, inv.reshape(values.shape) + 1)


def canonical_order(
    key_values: Sequence[np.ndarray], key_nulls: Sequence[np.ndarray]
) -> np.ndarray:
    """Permutation putting rows in canonical key order (first column most
    significant)."""
    codes = [
        _code_column(v, m) for v, m in zip(key_values, key_nulls)
    ]
    return np.lexsort(tuple(reversed(codes)))


def is_nan_cell(cell) -> bool:
    return isinstance(cell, float) and cell != cell


def key_at(
    key_values: Sequence[np.ndarray],
    key_nulls: Sequence[np.ndarray],
    i: int,
) -> Key:
    """The boundary key of row ``i`` as python cells (None = null)."""
    out = []
    for v, m in zip(key_values, key_nulls):
        if bool(m[i]):
            out.append(None)
        else:
            cell = v[i]
            out.append(cell.item() if isinstance(cell, np.generic) else cell)
    return tuple(out)


def _cell_tier(cell) -> int:
    if cell is None:
        return 0
    if is_nan_cell(cell):
        return 2
    return 1


def compare_keys(a: Key, b: Key) -> int:
    """Lexicographic canonical compare of two boundary keys: -1/0/+1."""
    for ca, cb in zip(a, b):
        ta, tb = _cell_tier(ca), _cell_tier(cb)
        if ta != tb:
            return -1 if ta < tb else 1
        if ta != 1:
            continue  # both null, or both NaN — equal at this column
        # numeric cross-kind compares (int vs float, bool vs int) follow
        # python semantics, matching dict-key equality in from_dict
        if ca == cb:
            continue
        return -1 if ca < cb else 1
    return 0


def leq_boundary(
    key_values: Sequence[np.ndarray],
    key_nulls: Sequence[np.ndarray],
    boundary: Key,
) -> np.ndarray:
    """Vectorized ``row_key <= boundary`` under the canonical order.

    Used by the merger to slice the emit-safe prefix off each run buffer;
    because buffers are canonically sorted the result is always a prefix
    mask."""
    n = len(key_values[0]) if key_values else 0
    result = np.zeros(n, dtype=np.int8)  # running trichotomy, 0 = tied
    undecided = np.ones(n, dtype=bool)
    for v, m, cell in zip(key_values, key_nulls, boundary):
        if not undecided.any():
            break
        tier_b = _cell_tier(cell)
        tier_a = np.where(m, 0, 1).astype(np.int8)
        if v.dtype.kind == "f":
            with np.errstate(invalid="ignore"):
                tier_a = np.where(~m & np.isnan(v), 2, tier_a).astype(np.int8)
        cmp = np.sign(tier_a - tier_b).astype(np.int8)
        if tier_b == 1:
            val_rows = undecided & (cmp == 0)
            if val_rows.any():
                with np.errstate(invalid="ignore"):
                    lt = v < cell
                    gt = v > cell
                cmp = np.where(val_rows & lt, -1, cmp).astype(np.int8)
                cmp = np.where(val_rows & gt, 1, cmp).astype(np.int8)
        newly = undecided & (cmp != 0)
        result[newly] = cmp[newly]
        undecided &= cmp == 0
    return result <= 0


def is_strictly_ascending(
    key_values: Sequence[np.ndarray], key_nulls: Sequence[np.ndarray]
) -> bool:
    """Vectorized check that rows are in canonical key order with NO
    duplicate keys — the invariant every spill-run block must satisfy.

    O(G) per column (adjacent-row trichotomy, same tier rules as
    ``leq_boundary``), so producers can VERIFY a canonical claim instead
    of trusting provenance: string columns carry ingest-dictionary codes
    in arbitrary dictionary order, so a delta that looks canonical by
    construction on numeric keys is not on string keys."""
    n = len(key_values[0]) if key_values else 0
    if n < 2:
        return True
    result = np.zeros(n - 1, dtype=np.int8)  # cmp(row i, row i+1)
    undecided = np.ones(n - 1, dtype=bool)
    for v, m in zip(key_values, key_nulls):
        if not undecided.any():
            break
        tier = np.where(m, 0, 1).astype(np.int8)
        if v.dtype.kind == "f":
            with np.errstate(invalid="ignore"):
                tier = np.where(~m & np.isnan(v), 2, tier).astype(np.int8)
        cmp = np.sign(tier[:-1] - tier[1:]).astype(np.int8)
        both_vals = (tier[:-1] == 1) & (tier[1:] == 1)
        if both_vals.any():
            with np.errstate(invalid="ignore"):
                lt = v[:-1] < v[1:]
                gt = v[:-1] > v[1:]
            cmp = np.where(both_vals & (cmp == 0) & lt, -1, cmp).astype(np.int8)
            cmp = np.where(both_vals & (cmp == 0) & gt, 1, cmp).astype(np.int8)
        newly = undecided & (cmp != 0)
        result[newly] = cmp[newly]
        undecided &= cmp == 0
    # any still-undecided pair is a duplicate key -> not strictly ascending
    return bool((result == -1).all()) and not bool(undecided.any())


def merge_add_sorted(parts) -> Tuple[Tuple[np.ndarray, ...], Tuple[np.ndarray, ...], np.ndarray]:
    """Concatenate frequency parts and merge-add duplicate keys, emitting
    canonical order — the same codes+lexsort+reduceat move as
    ``FrequenciesAndNumRows.sum``, over an arbitrary number of parts.

    The caller guarantees per-column dtypes already agree across parts
    (the store promotes at add time; the merger casts at read time)."""
    kv = tuple(
        np.concatenate([p[0][i] for p in parts])
        for i in range(len(parts[0][0]))
    )
    kn = tuple(
        np.concatenate([p[1][i] for p in parts])
        for i in range(len(parts[0][1]))
    )
    counts = np.concatenate([p[2] for p in parts])
    if len(counts) == 0:
        return kv, kn, counts
    codes = [_code_column(v, m) for v, m in zip(kv, kn)]
    order = np.lexsort(tuple(reversed(codes)))
    mat = np.stack(codes)[:, order] if codes else np.zeros((0, len(counts)))
    boundary = np.any(mat[:, 1:] != mat[:, :-1], axis=0)
    starts = np.concatenate([[0], np.nonzero(boundary)[0] + 1])
    merged_counts = np.add.reduceat(counts[order], starts).astype(np.int64)
    sel = order[starts]
    return (
        tuple(v[sel] for v in kv),
        tuple(m[sel] for m in kn),
        merged_counts,
    )
