"""Sorted-run files — the on-disk unit of the out-of-core spill engine.

A *run* is a sequence of canonically sorted, key-unique frequency blocks:
per block a counts vector plus one typed key column (values + null mask)
per grouping column, encoded with the SAME key-column codec the v3
frequency state payload uses (states/serde.py:encode_key_column), so the
two on-disk key encodings cannot drift apart. Because the frequency
monoid's merge is a sorted-merge-add, runs need no index or bloom
structures — the k-way merger (spill/merge.py) streams them back with one
block buffered per run.

Canonical key order (shared contract with spill/order.py): per column,
null sorts first, then values ascending, with float NaN collapsing to ONE
key that sorts last — exactly the order ``np.unique(equal_nan=True)``
codes induce, i.e. the order ``FrequenciesAndNumRows.sum`` already emits.

Layout: ``MAGIC(4) | VERSION(u16) | n_cols(u16)`` then repeated blocks of
``block_nbytes(i64) | crc32(u32) | G(i64) | counts(<i8 * G) | key column
blocks``; all integers little-endian, EOF terminates. The per-block crc32
is new in v2 (torn/corrupted blocks surface as a typed
CorruptStateException instead of a struct error); v1 files — no crc —
still read. File opens run under the process retry policy
(resilience/retry.py), so a transient IOError costs a backoff, not the
whole spilled grouping.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, List, Optional, Tuple

import numpy as np

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.states.serde import decode_key_column, encode_key_column

MAGIC = b"DQRN"
VERSION = 2

_u16 = struct.Struct("<H")
_u32 = struct.Struct("<I")
_i64 = struct.Struct("<q")

# A frequency block: (key_values per column, key_nulls per column, counts).
Block = Tuple[Tuple[np.ndarray, ...], Tuple[np.ndarray, ...], np.ndarray]


def encode_block(
    key_values: Tuple[np.ndarray, ...],
    key_nulls: Tuple[np.ndarray, ...],
    counts: np.ndarray,
) -> bytes:
    G = len(counts)
    out = [_i64.pack(G), np.ascontiguousarray(counts, dtype="<i8").tobytes()]
    for values, nulls in zip(key_values, key_nulls):
        out.append(encode_key_column(values, nulls))
    return b"".join(out)


def decode_block(buf: bytes, n_cols: int) -> Block:
    (G,) = _i64.unpack_from(buf, 0)
    off = 8
    counts = np.frombuffer(buf, dtype="<i8", count=G, offset=off).copy()
    off += 8 * G
    key_values = []
    key_nulls = []
    for _ in range(n_cols):
        values, nulls, off = decode_key_column(buf, off, G)
        key_values.append(values)
        key_nulls.append(nulls)
    return tuple(key_values), tuple(key_nulls), counts


class RunWriter:
    """Appends sorted blocks to one run file. The caller guarantees blocks
    arrive in canonical key order with globally unique keys across the run
    (the store sorts + dedups before flushing)."""

    def __init__(self, path: str, n_cols: int):
        from deequ_tpu.resilience.retry import retry_call

        self.path = path
        self.n_cols = n_cols
        self.groups_written = 0
        self.bytes_written = 0
        self._f = retry_call(
            lambda: open(path, "wb"), what=f"open spill run {path}"
        )
        header = MAGIC + _u16.pack(VERSION) + _u16.pack(n_cols)
        self._f.write(header)
        self.bytes_written += len(header)

    def write_block(
        self,
        key_values: Tuple[np.ndarray, ...],
        key_nulls: Tuple[np.ndarray, ...],
        counts: np.ndarray,
    ) -> None:
        if len(counts) == 0:
            return
        payload = encode_block(key_values, key_nulls, counts)
        self._f.write(_i64.pack(len(payload)))
        self._f.write(_u32.pack(zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)
        self.groups_written += len(counts)
        self.bytes_written += 12 + len(payload)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def write_run(
    path: str, blocks: Iterator[Block], n_cols: int
) -> RunWriter:
    """Spool an iterator of sorted blocks into one run file; returns the
    closed writer (for its stats)."""
    writer = RunWriter(path, n_cols)
    try:
        for key_values, key_nulls, counts in blocks:
            writer.write_block(key_values, key_nulls, counts)
    finally:
        writer.close()
    return writer


class RunReader:
    """Streams one run's blocks back; holds ONE block in memory."""

    def __init__(self, path: str):
        from deequ_tpu.resilience.retry import retry_call

        self.path = path
        self.bytes_read = 0
        with retry_call(
            lambda: open(path, "rb"), what=f"open spill run {path}"
        ) as f:
            header = f.read(8)
        if header[:4] != MAGIC:
            raise ValueError(f"{path} is not a spill run file (bad magic)")
        (self.version,) = _u16.unpack_from(header, 4)
        if self.version > VERSION:
            raise ValueError(
                f"spill run version {self.version} is newer than supported "
                f"{VERSION}"
            )
        (self.n_cols,) = _u16.unpack_from(header, 6)

    def blocks(self) -> Iterator[Block]:
        from deequ_tpu.resilience.retry import retry_call

        with retry_call(
            lambda: open(self.path, "rb"), what=f"open spill run {self.path}"
        ) as f:
            f.seek(8)
            while True:
                size_raw = f.read(8)
                if len(size_raw) < 8:
                    return
                (nbytes,) = _i64.unpack(size_raw)
                crc = None
                if self.version >= 2:
                    crc_raw = f.read(4)
                    if len(crc_raw) < 4:
                        raise CorruptStateException(
                            f"spill run {self.path}", "truncated block header"
                        )
                    (crc,) = _u32.unpack(crc_raw)
                payload = f.read(nbytes)
                if len(payload) < nbytes:
                    raise CorruptStateException(
                        f"spill run {self.path}",
                        f"torn block: expected {nbytes} bytes, "
                        f"found {len(payload)}",
                    )
                if crc is not None and (
                    zlib.crc32(payload) & 0xFFFFFFFF
                ) != crc:
                    raise CorruptStateException(
                        f"spill run {self.path}", "block checksum mismatch"
                    )
                self.bytes_read += (12 if crc is not None else 8) + nbytes
                yield decode_block(payload, self.n_cols)
