"""Out-of-core spill engine for high-cardinality grouping states.

Converts the hard OOM cliff of RAM-resident frequency tables into
graceful disk-backed degradation: deltas fold in RAM under a byte budget,
spill as canonically sorted binary runs, and merge back k-way at finalize
as a bounded stream of blocks the metric layer consumes directly. See
docs/out_of_core_streaming.md ("Spilling grouping state to disk").
"""

from deequ_tpu.spill.store import (
    DEFAULT_BUDGET_BYTES,
    SpilledFrequencies,
    SpillingFrequencyStore,
    budget_batch_rows,
    resolve_group_budget,
)

__all__ = [
    "DEFAULT_BUDGET_BYTES",
    "SpilledFrequencies",
    "SpillingFrequencyStore",
    "budget_batch_rows",
    "resolve_group_budget",
]
