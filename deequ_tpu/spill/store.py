"""SpillingFrequencyStore — bounded-RSS accumulation of grouping states.

The engine-level answer to the reference's spillable shuffle
(GroupingAnalyzers.scala:66-78 backed by Spark's ExternalSorter, with the
StorageLevel knob at AnalysisRunner.scala:493-497): frequency-state deltas
fold into an in-RAM tree (the same binary-counter fold as
StreamStateFolder) until the tail exceeds a configurable byte budget, at
which point the tail collapses, canonically sorts, and flushes to disk as
one sorted run (spill/runs.py). Finalize streams the runs back through a
bounded-fan-in k-way merge (spill/merge.py) as sorted, globally-unique
blocks — the metric layer consumes those blocks without ever holding the
full frequency table (analyzers/grouping.py), so a grouping whose distinct
count outgrows RAM degrades to disk bandwidth instead of OOM.

``SpilledFrequencies`` is the resulting State: still a member of the same
commutative monoid (``sum`` re-spills through a fresh store), still
serializable (states/serde.py tag 13), with metric math running over the
streamed blocks.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import weakref
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.analyzers.base import State, StreamStateFolder
from deequ_tpu.analyzers.grouping import FrequenciesAndNumRows
from deequ_tpu.spill.merge import collapse_runs, merge_runs
from deequ_tpu.spill.order import is_strictly_ascending, merge_add_sorted
from deequ_tpu.spill.runs import Block, RunWriter

# flush the in-RAM tail at this fraction of the budget: headroom for the
# collapse's merge scratch (~2x the tail transiently) and the finalize
# merger's per-run buffers
SPILL_FRACTION = 0.5

# default in-RAM group budget when spilling is requested without a size
DEFAULT_BUDGET_BYTES = 512 << 20

_ENV_BUDGET = "DEEQU_TPU_GROUP_MEMORY_BUDGET"


def resolve_group_budget(data=None, explicit: Optional[int] = None) -> Optional[int]:
    """Budget resolution: explicit argument > table attribute > environment
    variable (bytes). None = unbounded (the pre-spill behavior)."""
    if explicit is not None:
        return int(explicit)
    attr = getattr(data, "group_memory_budget", None)
    if attr is not None:
        return int(attr)
    from deequ_tpu.envcfg import env_value

    return env_value(_ENV_BUDGET)


def budget_batch_rows(budget_bytes: int) -> int:
    """Rows per slice when a budgeted in-memory table re-dispatches
    through the streaming fold (runner grouping + own-pass branches):
    ~256B/row of grouping state keeps each batch's delta inside the spill
    threshold, floored at 64K rows (dispatch amortization) and capped at
    16M (slice cost)."""
    return int(min(max(budget_bytes // 256, 1 << 16), 1 << 24))


def state_nbytes(state: FrequenciesAndNumRows) -> int:
    n = state.counts.nbytes
    for v, m in zip(state.key_values, state.key_nulls):
        n += v.nbytes + m.nbytes
    return n


_NUMERIC_KINDS = set("iufb")


class SpillingFrequencyStore:
    """Accumulates FrequenciesAndNumRows deltas under a byte budget,
    spilling sorted runs to disk past it. ``result()`` returns a plain
    in-RAM state when nothing spilled (zero behavior change for data that
    fits) or a ``SpilledFrequencies`` otherwise."""

    def __init__(
        self,
        columns: Sequence[str],
        budget_bytes: Optional[int] = None,
        spill_dir: Optional[str] = None,
    ):
        self.columns = tuple(columns)
        self.budget_bytes = int(budget_bytes or DEFAULT_BUDGET_BYTES)
        self._spill_dir = spill_dir
        self._tmpdir: Optional[str] = None
        self._finalizer = None
        self._result_taken = False
        self._folder = StreamStateFolder()
        self._tail_bytes = 0
        self._all_canonical = True
        self._run_paths: List[str] = []
        self._spilled_num_rows = 0
        self._bytes_per_group = 64.0  # refined from real flushes
        # running per-column dtype promotion (None until a typed, not
        # all-null column is seen); int ranges tracked so a later
        # promotion to float64 can refuse >2^53 keys like sum() does
        self._dtypes: List[Optional[np.dtype]] = [None] * len(self.columns)
        self._int_lo = [0] * len(self.columns)
        self._int_hi = [0] * len(self.columns)

    # -- budget accounting ---------------------------------------------------

    def _ensure_tmpdir(self) -> str:
        if self._tmpdir is None:
            self._tmpdir = tempfile.mkdtemp(
                prefix="deequ_spill_", dir=self._spill_dir
            )
            self._finalizer = weakref.finalize(
                self, shutil.rmtree, self._tmpdir, ignore_errors=True
            )
        return self._tmpdir

    def _track_dtypes(self, state: FrequenciesAndNumRows) -> None:
        for i, (v, m) in enumerate(zip(state.key_values, state.key_nulls)):
            if len(v) == 0 or bool(m.all()):
                continue  # empty/all-null columns constrain nothing
            kind = v.dtype.kind
            if kind in "iu":
                lo = int(v[~m].min()) if (~m).any() else 0
                hi = int(v[~m].max()) if (~m).any() else 0
                self._int_lo[i] = min(self._int_lo[i], lo)
                self._int_hi[i] = max(self._int_hi[i], hi)
            have = self._dtypes[i]
            if have is None:
                self._dtypes[i] = (
                    np.dtype(np.str_) if kind in "USO" else v.dtype
                )
                continue
            have_num = have.kind in _NUMERIC_KINDS
            new_num = kind in _NUMERIC_KINDS
            if have_num != new_num:
                raise ValueError(
                    f"cannot spill frequency states with mismatched "
                    f"group-key types ({have} vs {v.dtype}) for columns "
                    f"{self.columns}"
                )
            if have_num:
                common = np.promote_types(have, v.dtype)
                if common.kind == "f" and (
                    self._int_hi[i] > 2 ** 53 or self._int_lo[i] < -(2 ** 53)
                ):
                    raise ValueError(
                        "cannot merge integer group keys above 2^53 into a "
                        "float64-promoted key space: promotion would "
                        "collapse distinct keys"
                    )
                self._dtypes[i] = common

    # -- accumulation --------------------------------------------------------

    def add(self, state: Optional[State], canonical: bool = False) -> None:
        """Fold one frequency delta in; spill when the tail exceeds the
        budget's spill threshold. ``canonical=True`` asserts the delta is
        already in canonical key order (e.g. built by
        ``group_counts_state(..., canonicalize=True)``), letting flushes
        skip the re-sort."""
        if state is None:
            return
        if isinstance(state, SpilledFrequencies):
            # merging an already-spilled state in: stream its blocks
            if tuple(state.columns) != self.columns:
                raise ValueError(
                    f"cannot spill frequency states over different "
                    f"columns: {self.columns} vs {tuple(state.columns)}"
                )
            self._spilled_num_rows += state.num_rows
            for kv, kn, counts in state.blocks():
                self.add(
                    FrequenciesAndNumRows(self.columns, kv, kn, counts, 0),
                    canonical=True,
                )
            return
        if not isinstance(state, FrequenciesAndNumRows):
            raise TypeError(
                f"spill store holds frequency states, got "
                f"{type(state).__name__}"
            )
        if state.columns != self.columns:
            raise ValueError(
                f"cannot spill frequency states over different columns: "
                f"{self.columns} vs {state.columns}"
            )
        self._track_dtypes(state)
        # VERIFY canonical claims (O(G) adjacent-row compare) instead of
        # trusting provenance: a mis-claimed delta would silently corrupt
        # the k-way merge's prefix-slicing argument
        if canonical:
            canonical = is_strictly_ascending(
                state.key_values, state.key_nulls
            )
        # pre-flush: folding a delta onto a near-threshold tail would
        # overshoot the budget by up to one delta; flushing first bounds
        # the peak at max(threshold, one delta) instead
        if (
            self._tail_bytes
            and self._tail_bytes + state_nbytes(state)
            >= self.budget_bytes * SPILL_FRACTION
        ):
            self._flush()
        if not canonical:
            self._all_canonical = False
        self._folder.add(state)
        self._tail_bytes = sum(
            state_nbytes(s) for _, s in self._folder._stack
        )
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        SCAN_STATS.peak_group_state_bytes = max(
            SCAN_STATS.peak_group_state_bytes, self._tail_bytes
        )
        if self._tail_bytes >= self.budget_bytes * SPILL_FRACTION:
            self._flush()

    def _collapse(self) -> Optional[FrequenciesAndNumRows]:
        merged = self._folder.result()
        self._folder = StreamStateFolder()
        self._tail_bytes = 0
        return merged

    def _run_block_groups(self) -> int:
        """Groups per run block, sized so a finalize merge of
        ``_max_fanin()`` runs buffers ~budget/4 bytes total."""
        target_bytes = max(
            1 << 20, int(self.budget_bytes / 4 / self._max_fanin())
        )
        return max(4096, int(target_bytes / max(self._bytes_per_group, 1.0)))

    def _max_fanin(self) -> int:
        return 16

    def _flush(self) -> None:
        merged = self._collapse()
        if merged is None or merged.num_groups == 0:
            if merged is not None:
                self._spilled_num_rows += merged.num_rows
            return
        kv, kn, counts = merged.key_values, merged.key_nulls, merged.counts
        # sum() emits canonical order, but a single un-merged delta keeps
        # its producer's order — sort AND dedup unless every input was
        # verified canonical (merge_add_sorted also collapses duplicate
        # keys a lone unsorted delta may carry, keeping the run's
        # unique-keys invariant)
        if not self._all_canonical:
            kv, kn, counts = merge_add_sorted([(kv, kn, counts)])
        self._bytes_per_group = max(
            1.0, state_nbytes(merged) / max(merged.num_groups, 1)
        )
        path = os.path.join(
            self._ensure_tmpdir(), f"run_{len(self._run_paths):05d}.run"
        )
        writer = RunWriter(path, len(self.columns))
        bg = self._run_block_groups()
        for start in range(0, len(counts), bg):
            end = start + bg
            writer.write_block(
                tuple(v[start:end] for v in kv),
                tuple(m[start:end] for m in kn),
                counts[start:end],
            )
        writer.close()
        self._run_paths.append(path)
        self._spilled_num_rows += merged.num_rows
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        SCAN_STATS.spill_runs += 1
        SCAN_STATS.spill_bytes_written += writer.bytes_written
        # subsequent tails start empty and re-canonical
        self._all_canonical = True

    def _adopt_sorted_blocks(
        self, blocks: Iterator[Block], num_rows: int
    ) -> None:
        """Install pre-merged (globally sorted, key-unique) blocks as one
        run — used by serde decode so a persisted spilled state round-trips
        without materializing."""
        path = os.path.join(
            self._ensure_tmpdir(), f"run_{len(self._run_paths):05d}.run"
        )
        writer = RunWriter(path, len(self.columns))
        for kv, kn, counts in blocks:
            self._track_dtypes(
                FrequenciesAndNumRows(self.columns, kv, kn, counts, 0)
            )
            writer.write_block(kv, kn, counts)
        writer.close()
        self._run_paths.append(path)
        self._spilled_num_rows += num_rows

    # -- finalize ------------------------------------------------------------

    def result(self) -> Optional[State]:
        self._result_taken = True
        if not self._run_paths:
            # nothing spilled: plain state (or None). Rows folded in via
            # already-spilled INPUT states (whose blocks carry num_rows=0)
            # are tracked in _spilled_num_rows and must be re-added here.
            merged = self._collapse()
            if self._spilled_num_rows == 0:
                return merged
            if merged is None:
                return FrequenciesAndNumRows(
                    self.columns,
                    tuple(np.empty(0) for _ in self.columns),
                    tuple(np.zeros(0, dtype=bool) for _ in self.columns),
                    np.zeros(0, dtype=np.int64),
                    self._spilled_num_rows,
                )
            return FrequenciesAndNumRows(
                merged.columns, merged.key_values, merged.key_nulls,
                merged.counts, merged.num_rows + self._spilled_num_rows,
            )
        self._flush()
        return SpilledFrequencies(self)

    def blocks(self, out_groups: Optional[int] = None) -> Iterator[Block]:
        """Merged, canonically sorted, globally key-unique blocks across
        all runs. Each call re-streams from disk — but the cascade that
        collapses >fan-in runs happens ONCE (the collapsed run set
        replaces ``_run_paths``), so repeat consumers (count stats,
        Histogram top-N, MI's two passes, serde encode) pay only the
        final in-memory merge."""
        og = out_groups or self._run_block_groups()
        if len(self._run_paths) > self._max_fanin():
            self._run_paths = collapse_runs(
                self._run_paths,
                len(self.columns),
                dtypes=self._dtypes,
                out_groups=og,
                max_fanin=self._max_fanin(),
                scratch_dir=self._ensure_tmpdir(),
            )
        yield from merge_runs(
            self._run_paths,
            len(self.columns),
            dtypes=self._dtypes,
            out_groups=og,
            max_fanin=self._max_fanin(),
            scratch_dir=self._ensure_tmpdir(),
        )

    @property
    def num_rows(self) -> int:
        return self._spilled_num_rows

    def release(self) -> None:
        if self._finalizer is not None:
            self._finalizer()

    # -- context manager -----------------------------------------------------
    #
    # ``with SpillingFrequencyStore(...) as store:`` guarantees the temp
    # spill directory never outlives a FAILED run: an exception inside the
    # block releases it immediately (instead of waiting on GC finalizers,
    # which a crashing process may never run in a predictable order). A
    # normal exit keeps the directory alive only when ``result()`` was
    # taken — a SpilledFrequencies result streams its runs from that
    # directory, so the consumer (or its weakref finalizer) owns cleanup.

    def __enter__(self) -> "SpillingFrequencyStore":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None or not self._result_taken:
            self.release()
        return False


class SpilledFrequencies(State):
    """A frequency state whose groups live in sorted runs on disk.

    Same monoid, same metrics — but consumers iterate ``blocks()`` instead
    of touching whole-table arrays. ``count_stats()`` (one streaming pass,
    cached) covers every count-distribution analyzer; Histogram's top-N
    and MutualInformation stream their own passes
    (analyzers/grouping.py)."""

    def __init__(self, store: SpillingFrequencyStore):
        self._store = store
        self.columns = store.columns
        self.num_rows = store.num_rows
        self._stats = None

    def blocks(self, out_groups: Optional[int] = None) -> Iterator[Block]:
        return self._store.blocks(out_groups)

    # -- monoid --------------------------------------------------------------

    def sum(self, other: State) -> State:
        if isinstance(other, (FrequenciesAndNumRows, SpilledFrequencies)):
            if tuple(other.columns) != self.columns:
                raise ValueError(
                    f"cannot merge frequency states over different "
                    f"columns: {self.columns} vs {tuple(other.columns)}"
                )
            merged = SpillingFrequencyStore(
                self.columns,
                self._store.budget_bytes,
                spill_dir=self._store._spill_dir,
            )
            merged.add(self, canonical=True)
            merged.add(other, canonical=isinstance(other, SpilledFrequencies))
            return merged.result()
        return NotImplemented

    # -- streamed aggregates -------------------------------------------------

    def count_stats(self):
        """CountStats over the streamed blocks (cached single disk pass):
        integer aggregates are exact vs the in-RAM path; entropy sums
        blockwise partials (ulp-level association difference only)."""
        if self._stats is None:
            from deequ_tpu.ops.segment import CountStats

            num_groups = 0
            singletons = 0
            neg_plogp = 0.0
            n = self.num_rows
            for _kv, _kn, counts in self.blocks():
                num_groups += len(counts)
                singletons += int((counts == 1).sum())
                if n > 0:
                    p = counts.astype(np.float64) / n
                    neg_plogp += float(-(p * np.log(p)).sum())
            entropy = neg_plogp if (n > 0 and num_groups > 0) else float("nan")
            self._stats = CountStats(n, num_groups, singletons, entropy)
        return self._stats

    @property
    def num_groups(self) -> int:
        return self.count_stats().num_groups

    # -- materialization (small states / tests / compatibility) --------------

    def to_frequencies(self) -> FrequenciesAndNumRows:
        """Materialize the full in-RAM state — O(#groups) host memory;
        escape hatch for consumers with no block path (MutualInformation
        marginal join, tests)."""
        kvs: List[List[np.ndarray]] = [[] for _ in self.columns]
        kns: List[List[np.ndarray]] = [[] for _ in self.columns]
        counts: List[np.ndarray] = []
        for kv, kn, c in self.blocks():
            for i in range(len(self.columns)):
                kvs[i].append(kv[i])
                kns[i].append(kn[i])
            counts.append(c)
        if not counts:
            return FrequenciesAndNumRows(
                self.columns,
                tuple(np.empty(0) for _ in self.columns),
                tuple(np.zeros(0, dtype=bool) for _ in self.columns),
                np.zeros(0, dtype=np.int64),
                self.num_rows,
            )
        return FrequenciesAndNumRows(
            self.columns,
            tuple(np.concatenate(parts) for parts in kvs),
            tuple(np.concatenate(parts) for parts in kns),
            np.concatenate(counts),
            self.num_rows,
        )

    def as_dict(self) -> Dict[tuple, int]:
        return self.to_frequencies().as_dict()

    def __eq__(self, other) -> bool:
        if isinstance(other, (SpilledFrequencies, FrequenciesAndNumRows)):
            return (
                tuple(self.columns) == tuple(other.columns)
                and self.num_rows == other.num_rows
                and self.as_dict() == other.as_dict()
            )
        return NotImplemented

    __hash__ = None  # mutable disk-backed payload

    def __repr__(self) -> str:
        return (
            f"SpilledFrequencies(columns={self.columns}, "
            f"runs={len(self._store._run_paths)}, num_rows={self.num_rows})"
        )
