"""Exception hierarchy for metric calculation failures.

Mirrors the reference semantics (analyzers/runners/MetricCalculationException.scala:19-78):
failures during metric computation are *data* — they are captured inside
``Metric.value`` rather than aborting a run.
"""

from __future__ import annotations


class MetricCalculationException(Exception):
    """Base class for anything that goes wrong while computing a metric."""


class MetricCalculationRuntimeException(MetricCalculationException):
    """Runtime failure during state/metric computation."""


class MetricCalculationPreconditionException(MetricCalculationException):
    """A precondition on the input schema was violated."""


class NoSuchColumnException(MetricCalculationPreconditionException):
    def __init__(self, column: str):
        super().__init__(f"Input data does not include column {column}!")
        self.column = column


class WrongColumnTypeException(MetricCalculationPreconditionException):
    pass


class NoColumnsSpecifiedException(MetricCalculationPreconditionException):
    pass


class NumberOfSpecifiedColumnsException(MetricCalculationPreconditionException):
    pass


class IllegalAnalyzerParameterException(MetricCalculationPreconditionException):
    def __init__(self, message: str):
        super().__init__(f"Can't execute the analysis: {message}")


class EmptyStateException(MetricCalculationRuntimeException):
    pass


class CorruptStateException(MetricCalculationRuntimeException):
    """Persisted bytes failed integrity validation (checksum mismatch,
    torn write, undecodable payload). Raised instead of the raw
    JSON/struct error so callers can distinguish 'the file is damaged'
    from 'the code is wrong' — damaged state is recoverable by
    recomputing; a struct error is a bug."""

    def __init__(self, what: str, detail: str = ""):
        msg = f"corrupt persisted state: {what}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.what = what


class RetryExhaustedException(MetricCalculationRuntimeException):
    """A retried I/O operation kept failing past the RetryPolicy's attempt
    budget or deadline. ``__cause__`` carries the last underlying error."""

    def __init__(self, what: str, attempts: int, cause: BaseException):
        super().__init__(
            f"{what} still failing after {attempts} attempts: {cause}"
        )
        self.attempts = attempts
        self.__cause__ = cause


def wrap_if_necessary(exception: BaseException) -> MetricCalculationException:
    """Ensure an arbitrary error is a MetricCalculationException (reference L69)."""
    if isinstance(exception, MetricCalculationException):
        return exception
    wrapped = MetricCalculationRuntimeException(str(exception))
    wrapped.__cause__ = exception
    return wrapped
