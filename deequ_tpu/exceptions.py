"""Exception hierarchy for metric calculation failures.

Mirrors the reference semantics (analyzers/runners/MetricCalculationException.scala:19-78):
failures during metric computation are *data* — they are captured inside
``Metric.value`` rather than aborting a run.

Device faults are part of the same taxonomy: the scan engine classifies
raw ``jaxlib``/``XlaRuntimeError`` failures at its three device
boundaries (pack/transfer, trace/compile, execute) into the typed
``Device*Exception`` family below, so callers — and the degradation
policies (chunk bisection, CPU fallback, watchdog; ops/scan_engine.py) —
never have to pattern-match runtime strings.
"""

from __future__ import annotations

import re
from typing import Optional, Tuple


class MetricCalculationException(Exception):
    """Base class for anything that goes wrong while computing a metric."""


class EnvConfigError(ValueError):
    """A malformed ``DEEQU_TPU_*`` environment variable
    (deequ_tpu/envcfg.py — the consolidated registry every switch parses
    through). Subclasses ``ValueError`` so pre-registry callers that
    caught validation errors keep working; carries the variable name,
    the offending raw value, and what would have been accepted, so a
    deployment misconfiguration reads as exactly that instead of a
    stack trace into whichever module happened to parse it first."""

    def __init__(self, name: str, raw: str, expected: str):
        super().__init__(f"{name} must be {expected}, got {raw!r}")
        self.name = name
        self.raw = raw
        self.expected = expected


class MetricCalculationRuntimeException(MetricCalculationException):
    """Runtime failure during state/metric computation."""


class MetricCalculationPreconditionException(MetricCalculationException):
    """A precondition on the input schema was violated."""


class NoSuchColumnException(MetricCalculationPreconditionException):
    def __init__(self, column: str):
        super().__init__(f"Input data does not include column {column}!")
        self.column = column


class WrongColumnTypeException(MetricCalculationPreconditionException):
    pass


class NoColumnsSpecifiedException(MetricCalculationPreconditionException):
    pass


class NumberOfSpecifiedColumnsException(MetricCalculationPreconditionException):
    pass


class IllegalAnalyzerParameterException(MetricCalculationPreconditionException):
    def __init__(self, message: str):
        super().__init__(f"Can't execute the analysis: {message}")


class EmptyStateException(MetricCalculationRuntimeException):
    pass


class CorruptStateException(MetricCalculationRuntimeException):
    """Persisted bytes failed integrity validation (checksum mismatch,
    torn write, undecodable payload). Raised instead of the raw
    JSON/struct error so callers can distinguish 'the file is damaged'
    from 'the code is wrong' — damaged state is recoverable by
    recomputing; a struct error is a bug."""

    def __init__(self, what: str, detail: str = ""):
        msg = f"corrupt persisted state: {what}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.what = what


class ReusingNotPossibleResultsMissingException(
    MetricCalculationRuntimeException, RuntimeError
):
    """Raised when fail_if_results_missing is set and the repository lacks
    some requested analyzer results (reference AnalysisRunner.scala:552).
    Lives here so ALL failure types share one taxonomy; re-exported from
    ``analyzers.runner`` for compatibility, and still a RuntimeError for
    call sites that caught it as one before the move."""


class ServeException(MetricCalculationRuntimeException):
    """Base for serving-layer (deequ_tpu/serve) operational failures —
    conditions of the SERVICE, not of any one suite's data (those stay
    failure metrics / typed device errors as everywhere else)."""


class ServiceClosedException(ServeException):
    """A submit/resume/flush against a stopped VerificationService."""


class ControlPlaneException(MetricCalculationRuntimeException):
    """Typed failure of the closed-loop quality control plane
    (deequ_tpu/control): an illegal lifecycle transition on the
    CheckRegistry, a shadow evaluation requested outside the
    ``best_effort`` SLO class (the isolation invariant — a candidate
    check must never consume critical capacity), or a profile replay
    that cannot reconstruct a tenant's history."""


class ServiceOverloadedException(ServeException):
    """Typed backpressure: the service refused to buffer this request —
    the pending queue is at ``max_pending``, or (round 15, the admission
    tier's subclasses below) the request's SLO class ran out of budget
    or its deadline expired in-queue. The caller sheds load or retries
    after ``retry_after_s``; the service never buffers without bound.

    Structured fields (all optional — pre-round-15 raise sites carried a
    message only): ``queue_depth`` is the pending count at refusal,
    ``retry_after_s`` the service's drain-rate-derived estimate of when
    a retry could be admitted, ``slo_class`` the refused request's SLO
    class (``"critical"`` | ``"standard"`` | ``"best_effort"``)."""

    def __init__(self, message: str, queue_depth: Optional[int] = None,
                 retry_after_s: Optional[float] = None,
                 slo_class: Optional[str] = None):
        super().__init__(message)
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s
        self.slo_class = slo_class


class AdmissionRejectedException(ServiceOverloadedException):
    """The admission controller (deequ_tpu/serve/admission.py) refused
    this request at ``submit()``: its SLO class's queue budget is
    exhausted, the brownout ladder is shedding its class (level 1 sheds
    ``best_effort``, level 3 admits ``critical`` only), or its tenant is
    over the brownout inflight cap (level 2). ``reason`` names which
    (``"class_budget"`` | ``"brownout_best_effort"`` |
    ``"brownout_critical_only"`` | ``"tenant_inflight_cap"``);
    ``retry_after_s`` is always populated — admission rejection is
    backpressure with a schedule, not an error."""

    def __init__(self, message: str, reason: str = "class_budget",
                 queue_depth: Optional[int] = None,
                 retry_after_s: Optional[float] = None,
                 slo_class: Optional[str] = None):
        super().__init__(message, queue_depth=queue_depth,
                         retry_after_s=retry_after_s, slo_class=slo_class)
        self.reason = reason


class DeadlineExceededException(ServiceOverloadedException):
    """An ACCEPTED request's absolute SLO deadline expired before its
    dispatch: the deadline-aware queue sheds it pre-dispatch (resolved
    exactly once, typed, on its original future) instead of burning
    device time on a result whose caller already gave up — and a fleet
    failover re-dispatch sheds an expired victim the same way rather
    than replaying it stale. ``waited_s`` is how long the request sat
    accepted; ``deadline_ms`` the SLO it missed. Computation is never
    degraded — only which requests run."""

    def __init__(self, message: str, tenant=None,
                 slo_class: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 waited_s: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(message, queue_depth=queue_depth,
                         retry_after_s=retry_after_s, slo_class=slo_class)
        self.tenant = tenant
        self.deadline_ms = deadline_ms
        self.waited_s = waited_s


class LateDataException(MetricCalculationRuntimeException):
    """A windowed stream (deequ_tpu/windows) received rows whose event
    time is older than the stream's watermark under the ``refuse`` late
    policy: the caller asked for an error instead of silent exclusion.
    Under ``drop`` the rows are counted (``ScanStats.late_rows``); under
    ``side_output`` their batch-aligned row ranges are quarantined on the
    partial-result surface — this exception is the third, strictest
    routing. ``late_rows`` is how many rows in the offending batch were
    late; ``watermark`` the fence they fell behind; ``oldest_event_time``
    the worst offender's event time."""

    def __init__(self, message: str, stream: Optional[str] = None,
                 late_rows: Optional[int] = None,
                 watermark: Optional[float] = None,
                 oldest_event_time: Optional[float] = None):
        super().__init__(message)
        self.stream = stream
        self.late_rows = late_rows
        self.watermark = watermark
        self.oldest_event_time = oldest_event_time


class StaleEpochException(ServeException):
    """A fenced-out coordinator (serve/lease.py) tried to act: its lease
    epoch is older than the highest epoch the cluster has observed — a
    zombie that stalled through a lease takeover and woke up after a
    successor resumed on the same ledger. Raised at ``submit()`` when
    the on-disk lease outranks the coordinator's epoch, and sent back
    typed by workers that refuse a stale-epoch dispatch frame, so a
    split brain surfaces as a refusal instead of a double-resolution.

    ``stale_epoch`` is the refused writer's epoch; ``current_epoch``
    the highest epoch the refusing side has seen; ``holder`` names the
    current lease holder when known. Like the backpressure family, the
    fields decompose onto wire frames and reconstruct on the far side."""

    def __init__(self, message: str, stale_epoch: Optional[int] = None,
                 current_epoch: Optional[int] = None,
                 holder: Optional[str] = None):
        super().__init__(message)
        self.stale_epoch = stale_epoch
        self.current_epoch = current_epoch
        self.holder = holder


class RetryExhaustedException(MetricCalculationRuntimeException):
    """A retried I/O operation kept failing past the RetryPolicy's attempt
    budget or deadline. ``__cause__`` carries the last underlying error."""

    def __init__(self, what: str, attempts: int, cause: BaseException):
        super().__init__(
            f"{what} still failing after {attempts} attempts: {cause}"
        )
        self.attempts = attempts
        self.__cause__ = cause


class RunBudgetExhaustedException(MetricCalculationRuntimeException):
    """The run-level fault budget (resilience/governance.py) ran out
    mid-ladder: the COMPOSED retry ladder — I/O retries, OOM bisections,
    encoded demotions, mesh reshards, CPU fallbacks — charged more
    attempts than ``max_total_attempts`` allows, or the wall clock passed
    ``run_deadline``. Raised by ``RunBudget.charge`` at the first charge
    past the budget, so no rung can keep burning time after the run is
    over budget.

    ``reason`` is ``"max_total_attempts"`` or ``"run_deadline"``;
    ``ledger`` is the budget's charge snapshot (what each rung spent);
    ``degraded`` is True when the governing policy is
    ``on_budget_exhausted="degrade"`` — the verification layers then
    convert this into a PARTIAL result (failure metrics for the analyzers
    the exhausted scan could not finish, exact
    ``unverified_row_ranges`` for the rows never verified) instead of
    propagating; under ``"raise"`` it surfaces to the caller typed."""

    def __init__(self, reason: str, ledger: Optional[dict] = None,
                 degraded: bool = True, detail: str = ""):
        msg = f"run budget exhausted ({reason})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.reason = reason
        self.ledger = dict(ledger or {})
        self.degraded = bool(degraded)


class PlanLintError(MetricCalculationException):
    """A static contract violation found in a scan program BEFORE dispatch
    (deequ_tpu/lint/plan_lint.py): the traced jaxpr of a ``ScanPlan``-built
    program contradicts the contracts the plan declares — a
    selection-variant plan containing a ``sort`` primitive, a host
    callback inside a one-fetch fused program, a fold leaf whose merge
    disagrees with its registered reduction tag. Raised at trace time,
    per plan, under ``run_scan(plan_lint="error")`` /
    ``DEEQU_TPU_PLAN_LINT=error`` — the static twin of the runtime
    counter asserts (``device_sort_passes``/``device_fetches``), catching
    planner/packer drift before a single chunk dispatches.

    ``findings`` carries the structured finding rows (rule, severity,
    message) the lint pass produced."""

    def __init__(self, message: str, findings=()):
        super().__init__(message)
        self.findings = tuple(findings)


class PlanLintWarning(UserWarning):
    """A plan-lint finding surfaced in ``plan_lint="warn"`` mode (or a
    warning-severity finding in ``"error"`` mode): the scan proceeds, the
    finding is recorded on ``ScanStats.plan_lints``, and deployments can
    escalate or silence it through the standard warnings filters."""


class GroupBudgetIgnoredWarning(UserWarning):
    """``group_memory_budget`` was configured together with checkpointing:
    mid-store spill state is not serializable, so spill is disabled and
    frequency folds stay in host RAM. Emitted exactly ONCE per analysis
    run (never per batch); the typed category lets deployments suppress
    or escalate it through the standard warnings filters."""


# -- device fault taxonomy ---------------------------------------------------
#
# Spark gives the reference fault tolerance for free (lost tasks re-execute
# from lineage); JAX/XLA gives us raw RuntimeErrors with status-code
# prefixes. The scan engine classifies them ONCE, at the device boundary
# where they surfaced, into this typed family — the degradation policies
# (bisection/fallback/watchdog) and user code both dispatch on types.

#: the device boundaries where classification happens
DEVICE_BOUNDARIES = ("transfer", "trace", "execute", "fetch")


class DeviceException(MetricCalculationRuntimeException):
    """A classified device-layer (XLA/jaxlib) failure.

    ``boundary`` names where it surfaced: ``"transfer"`` (device_put /
    chunk pack), ``"trace"`` (jit trace / compile), ``"execute"``
    (dispatch / block_until_ready), or ``"fetch"`` (the device->host
    result materialization — with the on-device partial fold this is
    where ASYNC execute failures surface, since it is the scan's one
    blocking round trip).

    ``device_ids`` names the mesh members the raw error implicated (XLA
    messages often carry the failing chip: "device 3", "TPU_2", "chip
    #5"); empty when the fault is unattributable. Attribution is what
    lets the degraded-mesh policy shrink the mesh around ONE dead chip
    instead of abandoning all of them."""

    def __init__(self, message: str, boundary: str = "execute",
                 device_ids: Tuple[int, ...] = ()):
        super().__init__(message)
        self.boundary = boundary
        self.device_ids = tuple(device_ids)


class DeviceOOMException(DeviceException):
    """Device memory (HBM) exhausted — RESOURCE_EXHAUSTED / allocator
    failures. Recoverable by scanning in smaller chunks (the engine's
    adaptive chunk bisection) or by falling back to the host backend."""


class DeviceCompileException(DeviceException):
    """The fused program failed to lower/compile for the accelerator
    (INVALID_ARGUMENT / UNIMPLEMENTED / Mosaic or XLA compilation errors).
    Retrying the same program on the same backend cannot help; the CPU
    fallback re-jits it on the host backend."""


class DeviceLostException(DeviceException):
    """The accelerator died or never came up: backend initialization
    failures, device halts, DATA_LOSS / UNAVAILABLE / ABORTED / INTERNAL
    runtime states. The run can only continue on another backend."""


class DeviceHangException(DeviceException):
    """A blocking device call exceeded the compute watchdog's wall-clock
    deadline — a hung device converted into a typed, catchable failure
    (the blocked host thread is abandoned; it cannot be cancelled)."""

    def __init__(self, message: str, boundary: str = "execute",
                 deadline: Optional[float] = None):
        super().__init__(message, boundary)
        self.deadline = deadline


class MeshDegradedException(DeviceException):
    """A collective-boundary failure on a multi-chip mesh attributable to
    specific mesh members (``device_ids``): one chip's shard faulted while
    the rest of the mesh is presumed healthy. The degraded-mesh policy in
    ``run_scan`` responds by evicting residency pinned to the implicated
    devices, rebuilding the mesh over the largest healthy subset, and
    re-dispatching the same fused program — the CPU fallback is reached
    only when NO accelerator subset remains."""


class PeerLostException(DeviceException):
    """A multi-host run lost contact with one or more peer processes
    (barrier/heartbeat timeout across the DCN tier). ``lost_processes``
    names the process indices that stopped responding (empty when the
    timeout could not be attributed). With ``on_peer_loss="degrade"`` the
    surviving hosts complete the run and the lost hosts' row ranges are
    reported unverified instead of raising this."""

    def __init__(self, message: str, lost_processes: Tuple[int, ...] = (),
                 boundary: str = "execute"):
        super().__init__(message, boundary)
        self.lost_processes = tuple(lost_processes)


class WorkerLostException(DeviceException):
    """A serving-fleet worker (deequ_tpu/serve/fleet.py) died or stopped
    heartbeating: its process/thread is gone or it stalled past the
    membership timeout. ``worker_ids`` names the lost fleet members —
    the in-process analogue of ``PeerLostException``'s lost hosts. The
    fleet responds with FAILOVER, not abort: the lost worker's accepted
    requests re-dispatch onto surviving workers on their ORIGINAL
    futures (each re-dispatch charging the tenant's own run budget, kind
    ``worker_failover``); this exception reaches a caller only when no
    survivor remains or a request exhausted its failover retries."""

    def __init__(self, message: str, worker_ids: Tuple[int, ...] = (),
                 boundary: str = "execute"):
        super().__init__(message, boundary)
        self.worker_ids = tuple(worker_ids)


# message patterns per class, checked in order — OOM first (an OOM during
# compilation must bisect, not fall back), then compile, then lost
_OOM_RE = re.compile(
    r"RESOURCE_EXHAUSTED|[Oo]ut of memory|\bOOM\b|[Aa]llocation.*"
    r"(failed|exceeds)|[Ff]ailed to allocate|HBM.*exceed", re.DOTALL
)
_COMPILE_RE = re.compile(
    r"INVALID_ARGUMENT|UNIMPLEMENTED|[Cc]ompilation (failure|error)|"
    r"[Ff]ailed to compile|Mosaic|XLA can't deduce|[Ll]owering",
    re.DOTALL,
)
_LOST_RE = re.compile(
    r"DATA_LOSS|UNAVAILABLE|ABORTED|INTERNAL|DEADLINE_EXCEEDED|"
    r"[Dd]evice.*(lost|halt|reset)|[Uu]nable to initialize backend|"
    r"[Ff]ailed to initialize|[Nn]o visible.*devic|TPU.*unavailable",
    re.DOTALL,
)

# device attribution: XLA/runtime messages that name the failing chip do
# so with a handful of SINGULAR shapes ("device 3", "device: 3", "TPU_2",
# "TPU:2", "chip #5", "mesh position 4", "core 1"). The word prefix keeps
# byte counts and addresses from parsing as device ids, and the prefix is
# deliberately singular-only: enumeration text in whole-backend failures
# ("visible devices: 0,1") names the SET, not a culprit, and must not
# misattribute a backend-wide loss to its first listed chip
_DEVICE_ID_RE = re.compile(
    r"(?:device|TPU|chip|core|mesh position)[ _:#]+(\d+)",
    re.IGNORECASE,
)


def implicated_devices(exception: BaseException) -> Tuple[int, ...]:
    """The device ids a raw error message names, in order, deduplicated.
    Empty when the failure is unattributable (whole-backend faults,
    allocator OOMs that don't say where)."""
    if isinstance(exception, DeviceException) and exception.device_ids:
        return exception.device_ids
    text = f"{type(exception).__name__}: {exception}"
    seen = []
    for m in _DEVICE_ID_RE.finditer(text):
        did = int(m.group(1))
        if did not in seen:
            seen.append(did)
    return tuple(seen)


def _device_error_strength(exception: BaseException) -> Optional[str]:
    """``"strong"`` when the exception TYPE is device-shaped (jaxlib
    surfaces runtime failures as XlaRuntimeError, a RuntimeError from the
    jaxlib/jax modules — checked structurally so no jaxlib import is
    needed and test doubles with the same shape classify identically);
    ``"weak"`` for plain RuntimeError/MemoryError, which only classify on
    an unambiguous message pattern; None for everything else."""
    for klass in type(exception).__mro__:
        if klass.__name__ in (
            "XlaRuntimeError", "JaxRuntimeError", "InternalError"
        ):
            return "strong"
        module = getattr(klass, "__module__", "") or ""
        if module.startswith(("jaxlib", "jax.")) or module == "jax":
            return "strong"
    if isinstance(exception, (RuntimeError, MemoryError)):
        return "weak"
    return None


def classify_device_error(
    exception: BaseException, boundary: str = "execute"
) -> Optional[DeviceException]:
    """Map a raw device-layer error to its typed DeviceException, or None
    when the error is not device-shaped (logic errors must propagate
    untouched). Already-classified exceptions pass through unchanged.

    A plain RuntimeError with no recognizable status pattern stays
    unclassified even at the trace boundary — application bugs raised
    inside an op's update fn must surface as themselves, not trigger a
    pointless CPU fallback under a misleading device-fault type."""
    if isinstance(exception, DeviceException):
        return exception
    strength = _device_error_strength(exception)
    if strength is None:
        return None
    text = f"{type(exception).__name__}: {exception}"
    device_ids = implicated_devices(exception)
    klass = None
    if isinstance(exception, MemoryError) or _OOM_RE.search(text):
        klass = DeviceOOMException
    elif _COMPILE_RE.search(text):
        klass = DeviceCompileException
    elif _LOST_RE.search(text):
        # a loss the message pins on specific chips is a MESH fault — the
        # rest of the mesh is presumed healthy and the degraded-mesh
        # policy can shrink around the dead member(s); an unattributed
        # loss stays a whole-backend DeviceLostException
        klass = MeshDegradedException if device_ids else DeviceLostException
    elif boundary == "trace" and strength == "strong":
        # an unrecognized jax/jaxlib failure while tracing/compiling is a
        # compile failure by position: the program never ran
        klass = DeviceCompileException
    if klass is None:
        return None
    typed = klass(f"[{boundary}] {text}", boundary=boundary)
    typed.device_ids = device_ids
    typed.__cause__ = exception
    return typed


def wrap_if_necessary(exception: BaseException) -> MetricCalculationException:
    """Ensure an arbitrary error is a MetricCalculationException (reference L69)."""
    if isinstance(exception, MetricCalculationException):
        return exception
    wrapped = MetricCalculationRuntimeException(str(exception))
    wrapped.__cause__ = exception
    return wrapped
