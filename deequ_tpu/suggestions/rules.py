"""Constraint suggestion rules (reference suggestions/rules/*.scala).

Each rule inspects one column profile and, when applicable, emits a
``ConstraintSuggestion`` carrying an executable constraint plus the Python
code snippet that would add it to a Check.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import TYPE_CHECKING, List, Tuple

from deequ_tpu.analyzers.grouping import NULL_FIELD_REPLACEMENT
from deequ_tpu.analyzers.scan import DataTypeInstances
from deequ_tpu.checks import IsOne
from deequ_tpu.constraints import (
    ConstrainableDataTypes,
    completeness_constraint,
    compliance_constraint,
    data_type_constraint,
    uniqueness_constraint,
)
from deequ_tpu.profiles.profiler import ColumnProfile, NumericColumnProfile

if TYPE_CHECKING:
    from deequ_tpu.suggestions.runner import ConstraintSuggestion


def _sql_escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace("'", "\\'")


class ConstraintRule:
    """(reference suggestions/rules/ConstraintRule.scala:34-43)"""

    rule_description: str = ""

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        raise NotImplementedError

    def candidate(self, profile: ColumnProfile, num_records: int):
        raise NotImplementedError

    def __repr__(self) -> str:
        return type(self).__name__ + "()"


@dataclass(frozen=True)
class CompleteIfCompleteRule(ConstraintRule):
    """Complete in the sample -> NOT NULL constraint
    (reference rules/CompleteIfCompleteRule.scala:25-31)."""

    rule_description = (
        "If a column is complete in the sample, we suggest a NOT NULL constraint"
    )

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        return profile.completeness == 1.0

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        return ConstraintSuggestion(
            constraint=completeness_constraint(profile.column, IsOne),
            column_name=profile.column,
            current_value=f"Completeness: {profile.completeness}",
            description=f"'{profile.column}' is not null",
            suggesting_rule=self,
            code_for_constraint=f'.is_complete("{profile.column}")',
        )


@dataclass(frozen=True)
class RetainCompletenessRule(ConstraintRule):
    """Model completeness as a binomial proportion; suggest the lower bound
    of its 95% confidence interval
    (reference rules/RetainCompletenessRule.scala:28-34)."""

    rule_description = (
        "If a column is incomplete in the sample, we model its completeness "
        "as a binomial variable, estimate a confidence interval and use this "
        "to define a lower bound for the completeness"
    )

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        return 0.2 < profile.completeness < 1.0

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        p = profile.completeness
        n = max(num_records, 1)
        z = 1.96
        target = p - z * math.sqrt(p * (1 - p) / n)
        target = math.floor(target * 100) / 100  # round DOWN to 2 decimals
        bound_percent = int((1.0 - target) * 100)
        return ConstraintSuggestion(
            constraint=completeness_constraint(
                profile.column, lambda v, t=target: v >= t
            ),
            column_name=profile.column,
            current_value=f"Completeness: {profile.completeness}",
            description=(
                f"'{profile.column}' has less than {bound_percent}% missing values"
            ),
            suggesting_rule=self,
            code_for_constraint=(
                f'.has_completeness("{profile.column}", lambda v: v >= {target}, '
                f'hint="It should be above {target}!")'
            ),
        )


@dataclass(frozen=True)
class RetainTypeRule(ConstraintRule):
    """Inferred non-string type -> type constraint
    (reference rules/RetainTypeRule.scala:27-39)."""

    rule_description = "If we detect a non-string type, we suggest a type constraint"

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        testable = profile.data_type in (
            DataTypeInstances.INTEGRAL,
            DataTypeInstances.FRACTIONAL,
            DataTypeInstances.BOOLEAN,
        )
        return profile.is_data_type_inferred and testable

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        type_to_check = {
            DataTypeInstances.FRACTIONAL: ConstrainableDataTypes.FRACTIONAL,
            DataTypeInstances.INTEGRAL: ConstrainableDataTypes.INTEGRAL,
            DataTypeInstances.BOOLEAN: ConstrainableDataTypes.BOOLEAN,
        }[profile.data_type]
        return ConstraintSuggestion(
            constraint=data_type_constraint(profile.column, type_to_check, IsOne),
            column_name=profile.column,
            current_value=f"DataType: {profile.data_type.value}",
            description=f"'{profile.column}' has type {profile.data_type.value}",
            suggesting_rule=self,
            code_for_constraint=(
                f'.has_data_type("{profile.column}", '
                f"ConstrainableDataTypes.{profile.data_type.value.upper()})"
            ),
        )


def _unique_value_ratio(profile: ColumnProfile) -> float:
    entries = profile.histogram.values
    num_unique = sum(1 for v in entries.values() if v.absolute == 1)
    return num_unique / len(entries) if entries else 1.0


@dataclass(frozen=True)
class CategoricalRangeRule(ConstraintRule):
    """Low-cardinality string column -> IS IN constraint over its values
    (reference rules/CategoricalRangeRule.scala:27-46)."""

    rule_description = (
        "If we see a categorical range for a column, we suggest an IS IN (...) "
        "constraint"
    )

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        if profile.histogram is None or profile.data_type != DataTypeInstances.STRING:
            return False
        return _unique_value_ratio(profile) <= 0.1

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        by_popularity = sorted(
            (
                (k, v)
                for k, v in profile.histogram.values.items()
                if k != NULL_FIELD_REPLACEMENT
            ),
            key=lambda kv: kv[1].absolute,
            reverse=True,
        )
        categories_sql = ", ".join(f"'{_sql_escape(k)}'" for k, _ in by_popularity)
        categories_code = ", ".join(repr(k) for k, _ in by_popularity)
        description = f"'{profile.column}' has value range {categories_sql}"
        condition = f"`{profile.column}` IS NULL OR `{profile.column}` IN ({categories_sql})"
        return ConstraintSuggestion(
            constraint=compliance_constraint(description, condition, IsOne),
            column_name=profile.column,
            current_value="Compliance: 1",
            description=description,
            suggesting_rule=self,
            code_for_constraint=(
                f'.is_contained_in("{profile.column}", [{categories_code}])'
            ),
        )


@dataclass(frozen=True)
class FractionalCategoricalRangeRule(ConstraintRule):
    """Top categories covering >= target fraction -> IS IN constraint with a
    fractional assertion (reference rules/FractionalCategoricalRangeRule.
    scala:29-99)."""

    target_data_coverage_fraction: float = 0.9

    rule_description = (
        "If we see a categorical range for most values in a column, we "
        "suggest an IS IN (...) constraint that should hold for most values"
    )

    def _top_categories(self, profile: ColumnProfile) -> List[Tuple[str, object]]:
        entries = sorted(
            profile.histogram.values.items(),
            key=lambda kv: kv[1].ratio,
            reverse=True,
        )
        out = []
        covered = 0.0
        for k, v in entries:
            if covered >= self.target_data_coverage_fraction:
                break
            out.append((k, v))
            covered += v.ratio
        return out

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        if profile.histogram is None or profile.data_type != DataTypeInstances.STRING:
            return False
        top = self._top_categories(profile)
        ratio_sum = sum(v.ratio for _, v in top)
        return _unique_value_ratio(profile) <= 0.4 and ratio_sum < 1

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        top = self._top_categories(profile)
        ratio_sum = sum(v.ratio for _, v in top)
        by_popularity = sorted(
            ((k, v) for k, v in top if k != NULL_FIELD_REPLACEMENT),
            key=lambda kv: kv[1].absolute,
            reverse=True,
        )
        categories_sql = ", ".join(f"'{_sql_escape(k)}'" for k, _ in by_popularity)
        categories_code = ", ".join(repr(k) for k, _ in by_popularity)
        # binomial confidence-interval lower bound on the observed coverage
        # (reference FractionalCategoricalRangeRule.scala:77-80)
        p = ratio_sum
        n = max(num_records, 1)
        z = 1.96
        target = math.floor((p - z * math.sqrt(p * (1 - p) / n)) * 100) / 100
        description = (
            f"'{profile.column}' has value range {categories_sql} for at "
            f"least {target * 100:.0f}% of values"
        )
        condition = f"`{profile.column}` IN ({categories_sql})"
        return ConstraintSuggestion(
            constraint=compliance_constraint(
                description, condition, lambda v, t=target: v >= t
            ),
            column_name=profile.column,
            current_value=f"Compliance: {ratio_sum}",
            description=description,
            suggesting_rule=self,
            code_for_constraint=(
                f'.is_contained_in("{profile.column}", [{categories_code}], '
                f"lambda v: v >= {target}, "
                f'hint="It should be above {target}!")'
            ),
        )


@dataclass(frozen=True)
class NonNegativeNumbersRule(ConstraintRule):
    """Only non-negative values observed -> isNonNegative
    (reference rules/NonNegativeNumbersRule.scala:25-34)."""

    rule_description = (
        "If we see only non-negative numbers in a column, we suggest a "
        "corresponding constraint"
    )

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        return (
            isinstance(profile, NumericColumnProfile)
            and profile.minimum is not None
            and profile.minimum >= 0.0
        )

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        description = f"'{profile.column}' has no negative values"
        minimum = (
            str(profile.minimum)
            if isinstance(profile, NumericColumnProfile) and profile.minimum is not None
            else "Error while calculating minimum!"
        )
        return ConstraintSuggestion(
            constraint=compliance_constraint(
                description, f"COALESCE(`{profile.column}`, 0.0) >= 0", IsOne
            ),
            column_name=profile.column,
            current_value=f"Minimum: {minimum}",
            description=description,
            suggesting_rule=self,
            code_for_constraint=f'.is_non_negative("{profile.column}")',
        )


@dataclass(frozen=True)
class UniqueIfApproximatelyUniqueRule(ConstraintRule):
    """Approx distinct count close to the record count -> UNIQUE
    (reference rules/UniqueIfApproximatelyUniqueRule.scala:28-38)."""

    rule_description = (
        "If the ratio of approximate num distinct values in a column is "
        "close to the number of records (within the error of the HLL "
        "sketch), we suggest a UNIQUE constraint"
    )

    def should_be_applied(self, profile: ColumnProfile, num_records: int) -> bool:
        if num_records == 0:
            return False
        distinctness = profile.approximate_num_distinct_values / num_records
        return profile.completeness == 1.0 and abs(1.0 - distinctness) <= 0.08

    def candidate(self, profile: ColumnProfile, num_records: int):
        from deequ_tpu.suggestions.runner import ConstraintSuggestion

        distinctness = profile.approximate_num_distinct_values / max(num_records, 1)
        return ConstraintSuggestion(
            constraint=uniqueness_constraint((profile.column,), IsOne),
            column_name=profile.column,
            current_value=f"ApproxDistinctness: {distinctness}",
            description=f"'{profile.column}' is unique",
            suggesting_rule=self,
            code_for_constraint=f'.is_unique("{profile.column}")',
        )
