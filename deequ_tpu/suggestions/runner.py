"""Constraint suggestion runner (reference suggestions/
ConstraintSuggestionRunner.scala:59-136, ConstraintSuggestionResult.scala).

Profiles the data, applies rules per column, optionally splits the data into
train/test and evaluates the suggested constraints on the held-out part.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from deequ_tpu.checks import Check, CheckLevel
from deequ_tpu.constraints import Constraint
from deequ_tpu.data.table import ColumnarTable
from deequ_tpu.profiles.profiler import (
    ColumnProfile,
    ColumnProfiler,
    ColumnProfiles,
    DEFAULT_CARDINALITY_THRESHOLD,
)
from deequ_tpu.suggestions.rules import (
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    ConstraintRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
)


class Rules:
    """(reference ConstraintSuggestionRunner.scala:30-36)"""

    DEFAULT: List[ConstraintRule] = [
        CompleteIfCompleteRule(),
        RetainCompletenessRule(),
        RetainTypeRule(),
        CategoricalRangeRule(),
        FractionalCategoricalRangeRule(),
        NonNegativeNumbersRule(),
    ]


@dataclass
class ConstraintSuggestion:
    """(reference suggestions/ConstraintSuggestion.scala:25-33)"""

    constraint: Constraint
    column_name: str
    current_value: str
    description: str
    suggesting_rule: ConstraintRule
    code_for_constraint: str


@dataclass
class ConstraintSuggestionResult:
    """(reference suggestions/ConstraintSuggestionResult.scala:32-53)"""

    column_profiles: ColumnProfiles
    suggestions: Dict[str, List[ConstraintSuggestion]]
    verification_result: Optional[object] = None  # VerificationResult

    @property
    def all_suggestions(self) -> List[ConstraintSuggestion]:
        return [s for group in self.suggestions.values() for s in group]

    def profiles_as_json(self) -> str:
        return self.column_profiles.to_json()

    def suggestions_as_json(self) -> str:
        return json.dumps(
            {
                "constraint_suggestions": [
                    {
                        "constraint_name": str(s.constraint),
                        "column_name": s.column_name,
                        "current_value": s.current_value,
                        "description": s.description,
                        "suggesting_rule": repr(s.suggesting_rule),
                        "rule_description": s.suggesting_rule.rule_description,
                        "code_for_constraint": s.code_for_constraint,
                    }
                    for s in self.all_suggestions
                ]
            }
        )

    def evaluation_as_json(self) -> str:
        if self.verification_result is None:
            return json.dumps({"constraint_suggestions": []})
        status_by_constraint = {}
        for check_result in self.verification_result.check_results.values():
            for cr in check_result.constraint_results:
                status_by_constraint[str(cr.constraint)] = cr.status.value
        return json.dumps(
            {
                "constraint_suggestions": [
                    {
                        "constraint_name": str(s.constraint),
                        "column_name": s.column_name,
                        "description": s.description,
                        "evaluation_status": status_by_constraint.get(
                            str(s.constraint), "Unknown"
                        ),
                    }
                    for s in self.all_suggestions
                ]
            }
        )


class ConstraintSuggestionRunner:
    @staticmethod
    def on_data(data: ColumnarTable) -> "ConstraintSuggestionRunBuilder":
        return ConstraintSuggestionRunBuilder(data)


class ConstraintSuggestionRunBuilder:
    def __init__(self, data: ColumnarTable):
        self._data = data
        self._rules: List[ConstraintRule] = []
        self._restrict_to_columns: Optional[Sequence[str]] = None
        self._threshold = DEFAULT_CARDINALITY_THRESHOLD
        self._print_status_updates = False
        self._testset_ratio: Optional[float] = None
        self._testset_split_random_seed: Optional[int] = None
        self._metrics_repository = None
        self._reuse_key = None
        self._fail_if_missing = False
        self._save_key = None
        self._kll_profiling = False
        self._kll_parameters = None

    def add_constraint_rule(self, rule: ConstraintRule):
        self._rules.append(rule)
        return self

    def add_constraint_rules(self, rules: Sequence[ConstraintRule]):
        self._rules.extend(rules)
        return self

    def restrict_to_columns(self, columns: Sequence[str]):
        self._restrict_to_columns = columns
        return self

    def with_low_cardinality_histogram_threshold(self, threshold: int):
        self._threshold = threshold
        return self

    def print_status_updates(self, value: bool):
        self._print_status_updates = value
        return self

    def use_train_test_split_with_test_set_ratio(
        self, ratio: float, seed: Optional[int] = None
    ):
        if not (0.0 < ratio < 1.0):
            raise ValueError("Testset ratio must be in ]0, 1[")
        self._testset_ratio = ratio
        self._testset_split_random_seed = seed
        return self

    def with_kll_profiling(self):
        self._kll_profiling = True
        return self

    def set_kll_parameters(self, parameters):
        self._kll_parameters = parameters
        return self

    def use_repository(self, repository):
        self._metrics_repository = repository
        return self

    def reuse_existing_results_for_key(self, key, fail_if_missing: bool = False):
        self._reuse_key = key
        self._fail_if_missing = fail_if_missing
        return self

    def save_or_append_result(self, key):
        self._save_key = key
        return self

    def run(self) -> ConstraintSuggestionResult:
        # optional train/test split (reference L87)
        if self._testset_ratio is not None:
            train_ratio = 1.0 - self._testset_ratio
            seed = (
                self._testset_split_random_seed
                if self._testset_split_random_seed is not None
                else 0
            )
            train, test = self._data.random_split(
                (train_ratio, self._testset_ratio), seed=seed
            )
        else:
            train, test = self._data, None

        profiles = ColumnProfiler.profile(
            train,
            restrict_to_columns=self._restrict_to_columns,
            print_status_updates=self._print_status_updates,
            low_cardinality_histogram_threshold=self._threshold,
            metrics_repository=self._metrics_repository,
            reuse_existing_results_using_key=self._reuse_key,
            fail_if_results_for_reusing_missing=self._fail_if_missing,
            save_in_metrics_repository_using_key=self._save_key,
            kll_profiling=self._kll_profiling,
            kll_parameters=self._kll_parameters,
        )

        suggestions: Dict[str, List[ConstraintSuggestion]] = {}
        for name, profile in profiles.profiles.items():
            for rule in self._rules:
                if rule.should_be_applied(profile, profiles.num_records):
                    suggestions.setdefault(name, []).append(
                        rule.candidate(profile, profiles.num_records)
                    )

        verification_result = None
        if test is not None and suggestions:
            from deequ_tpu.verification import VerificationSuite

            check = Check(
                CheckLevel.WARNING, "generated constraints",
            )
            for group in suggestions.values():
                for s in group:
                    check = check.add_constraint(s.constraint)
            verification_result = (
                VerificationSuite.on_data(test).add_check(check).run()
            )

        return ConstraintSuggestionResult(profiles, suggestions, verification_result)
