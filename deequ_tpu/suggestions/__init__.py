from deequ_tpu.suggestions.rules import (
    CategoricalRangeRule,
    CompleteIfCompleteRule,
    ConstraintRule,
    FractionalCategoricalRangeRule,
    NonNegativeNumbersRule,
    RetainCompletenessRule,
    RetainTypeRule,
    UniqueIfApproximatelyUniqueRule,
)
from deequ_tpu.suggestions.runner import (
    ConstraintSuggestion,
    ConstraintSuggestionResult,
    ConstraintSuggestionRunner,
    Rules,
)

__all__ = [
    "CategoricalRangeRule",
    "CompleteIfCompleteRule",
    "ConstraintRule",
    "ConstraintSuggestion",
    "ConstraintSuggestionResult",
    "ConstraintSuggestionRunner",
    "FractionalCategoricalRangeRule",
    "NonNegativeNumbersRule",
    "RetainCompletenessRule",
    "RetainTypeRule",
    "Rules",
    "UniqueIfApproximatelyUniqueRule",
]
