// Native host kernels for deequ_tpu.
//
// The TPU compute path is JAX/XLA; these C++ kernels cover the *host-side*
// hot loops that feed it — the role the reference's Catalyst/JVM layer plays
// for Spark (SURVEY.md §2.4). All operate on a packed string batch:
// one contiguous utf-8 buffer plus an (n+1)-entry offset array, which is
// exactly how dictionary values are shipped from numpy without per-string
// Python objects.
//
// Exposed via ctypes (see native/__init__.py); pure-Python fallbacks exist
// for every function, so an unbuilt extension only costs speed.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 kernels.cpp -o _kernels.so

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// xxHash64 (public algorithm, reimplemented) — batch over a packed buffer.
// Mirrors deequ_tpu.ops.hll.xxhash64_bytes bit-for-bit.
// ---------------------------------------------------------------------------

static const uint64_t P1 = 0x9E3779B185EBCA87ULL;
static const uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
static const uint64_t P3 = 0x165667B19E3779F9ULL;
static const uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
static const uint64_t P5 = 0x27D4EB2F165667C5ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // little-endian hosts only (x86-64 / arm64)
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static uint64_t xxh64(const uint8_t* data, int64_t n, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + n;
  uint64_t h;
  if (n >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = rotl64(v1 + read64(p) * P2, 31) * P1; p += 8;
      v2 = rotl64(v2 + read64(p) * P2, 31) * P1; p += 8;
      v3 = rotl64(v3 + read64(p) * P2, 31) * P1; p += 8;
      v4 = rotl64(v4 + read64(p) * P2, 31) * P1; p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    uint64_t vs[4] = {v1, v2, v3, v4};
    for (int i = 0; i < 4; i++) {
      uint64_t k = rotl64(vs[i] * P2, 31) * P1;
      h ^= k;
      h = h * P1 + P4;
    }
  } else {
    h = seed + P5;
  }
  h += (uint64_t)n;
  while (p + 8 <= end) {
    uint64_t k = rotl64(read64(p) * P2, 31) * P1;
    h ^= k;
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

void xxhash64_batch(const uint8_t* buffer, const int64_t* offsets, int64_t n,
                    uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = xxh64(buffer + offsets[i], offsets[i + 1] - offsets[i], seed);
  }
}

// ---------------------------------------------------------------------------
// DataType classification — regex-free scanner equivalent to the reference's
// FRACTIONAL ^(-|\+)? ?\d*\.\d*$ / INTEGRAL ^(-|\+)? ?\d*$ /
// BOOLEAN ^(true|false)$ patterns (StatefulDataType.scala:36-38), matching
// deequ_tpu.analyzers.scan._classify_string.
// Classes: 1=fractional, 2=integral, 3=boolean, 4=string.
// ---------------------------------------------------------------------------

static int32_t classify(const uint8_t* s, int64_t n) {
  // boolean?
  if (n == 4 && std::memcmp(s, "true", 4) == 0) return 3;
  if (n == 5 && std::memcmp(s, "false", 5) == 0) return 3;
  // optional sign, then optional single space, then digits with <= 1 dot
  int64_t i = 0;
  if (i < n && (s[i] == '-' || s[i] == '+')) i++;
  if (i < n && s[i] == ' ') i++;
  int dots = 0;
  for (; i < n; i++) {
    if (s[i] == '.') {
      dots++;
      if (dots > 1) return 4;
    } else if (s[i] < '0' || s[i] > '9') {
      return 4;
    }
  }
  return dots == 1 ? 1 : 2;  // note: "" and "-" classify as integral, like
                             // the reference's \d* patterns
}

void classify_batch(const uint8_t* buffer, const int64_t* offsets, int64_t n,
                    int32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = classify(buffer + offsets[i], offsets[i + 1] - offsets[i]);
  }
}

// ---------------------------------------------------------------------------
// Batch utf-8 length (code points) — for MinLength/MaxLength lookup tables.
// Counts non-continuation bytes, matching Python's len(str).
// ---------------------------------------------------------------------------

void utf8_lengths(const uint8_t* buffer, const int64_t* offsets, int64_t n,
                  int64_t* out) {
  for (int64_t i = 0; i < n; i++) {
    int64_t count = 0;
    for (int64_t j = offsets[i]; j < offsets[i + 1]; j++) {
      if ((buffer[j] & 0xC0) != 0x80) count++;
    }
    out[i] = count;
  }
}

}  // extern "C"
