"""Native host kernels (C++, ctypes-bound) with pure-Python fallbacks.

The device compute path is JAX/XLA; this module accelerates the host-side
hot loops that feed it: per-distinct-value hashing, type classification and
utf-8 lengths over dictionary batches. The extension compiles on first use
(g++, cached next to the source); if the toolchain is unavailable every
entry point silently falls back to the Python implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "kernels.cpp")
_SO = os.path.join(_HERE, "_kernels.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        from deequ_tpu.envcfg import env_value

        if env_value("DEEQU_TPU_DISABLE_NATIVE"):
            return None
        needs_build = (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if needs_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.xxhash64_batch.argtypes = [u8p, i64p, ctypes.c_int64,
                                       ctypes.c_uint64, u64p]
        lib.xxhash64_batch.restype = None
        lib.classify_batch.argtypes = [u8p, i64p, ctypes.c_int64, i32p]
        lib.classify_batch.restype = None
        lib.utf8_lengths.argtypes = [u8p, i64p, ctypes.c_int64, i64p]
        lib.utf8_lengths.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _pack(values: Sequence[str]):
    """Pack strings into (contiguous utf-8 buffer, int64 offsets[n+1])."""
    encoded: List[bytes] = [str(v).encode("utf-8") for v in values]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    buffer = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    if len(buffer) == 0:
        buffer = np.zeros(1, dtype=np.uint8)
    return buffer, offsets


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def hash_strings(values: Sequence[str], seed: int) -> Optional[np.ndarray]:
    """Batch xxhash64; None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    buffer, offsets = _pack(values)
    out = np.empty(len(values), dtype=np.uint64)
    lib.xxhash64_batch(
        _ptr(buffer, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        len(values), ctypes.c_uint64(seed), _ptr(out, ctypes.c_uint64),
    )
    return out


def classify_strings(values: Sequence[str]) -> Optional[np.ndarray]:
    """Batch DataType classification (1=fractional..4=string)."""
    lib = _load()
    if lib is None:
        return None
    buffer, offsets = _pack(values)
    out = np.empty(len(values), dtype=np.int32)
    lib.classify_batch(
        _ptr(buffer, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        len(values), _ptr(out, ctypes.c_int32),
    )
    return out


def utf8_lengths(values: Sequence[str]) -> Optional[np.ndarray]:
    """Batch string lengths in code points."""
    lib = _load()
    if lib is None:
        return None
    buffer, offsets = _pack(values)
    out = np.empty(len(values), dtype=np.int64)
    lib.utf8_lengths(
        _ptr(buffer, ctypes.c_uint8), _ptr(offsets, ctypes.c_int64),
        len(values), _ptr(out, ctypes.c_int64),
    )
    return out
