"""Continuous windowed verification (docs/windows.md).

Event-time windows as an extra fold dimension of the fused device
program: every open pane advances in ONE dispatch per batch
(engine.WindowedStream), watermarks fence window closes with typed late
routing (spec.WatermarkPolicy), pane state + the exactly-once close
fence persist through the checksummed window-state store (state.py),
and streams register as SLO-classed tenants whose late closes shed
TYPED under overload (service.StreamHub).
"""

from deequ_tpu.windows.engine import (
    SUPPORTED_ANALYZERS,
    WINDOW_STATS,
    WindowClose,
    WindowedStream,
    clear_program_cache,
    drive,
    pane_signature,
)
from deequ_tpu.windows.service import StreamHub
from deequ_tpu.windows.spec import (
    LATE_POLICIES,
    WatermarkPolicy,
    WindowSpec,
    resolve_watermark_policy,
    resolve_window_spec,
)
from deequ_tpu.windows.state import (
    WindowState,
    WindowStateStore,
    stream_fingerprint,
)

__all__ = [
    "LATE_POLICIES",
    "SUPPORTED_ANALYZERS",
    "WINDOW_STATS",
    "WatermarkPolicy",
    "WindowClose",
    "WindowSpec",
    "WindowState",
    "WindowStateStore",
    "WindowedStream",
    "StreamHub",
    "clear_program_cache",
    "drive",
    "pane_signature",
    "resolve_watermark_policy",
    "resolve_window_spec",
    "stream_fingerprint",
]
