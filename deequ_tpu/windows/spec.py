"""Window algebra: event-time window specs and watermark policies.

A window is an event-time interval ``[start, start + size)`` whose start
is aligned to the slide grid (``start = k * slide`` for integer ``k``).
``slide == size`` is a tumbling window (each row in exactly one pane);
``slide < size`` is sliding (each row in ``ceil(size/slide)`` panes).
Panes are the unit everything downstream folds over: the device program
advances every open pane in ONE dispatch per batch (the window fold
axis, TiLT arXiv:2301.12030), and the close protocol emits one
VerificationResult per pane exactly once.

The watermark is the stream's bounded-disorder fence (per-stream,
monotone): ``watermark = max(watermark, max_event_time_seen - lag_s)``.
Windows close when ``end <= watermark``; rows with
``event_time < watermark`` are LATE and route by the typed policy
(``drop`` / ``side_output`` / ``refuse`` — never silently folded into a
pane that already closed).

Defaults resolve from the envcfg registry (DEEQU_TPU_WINDOW_SIZE_S /
DEEQU_TPU_WINDOW_SLIDE_S / DEEQU_TPU_WATERMARK_LAG_S /
DEEQU_TPU_LATE_POLICY); malformed values raise typed
:class:`~deequ_tpu.exceptions.EnvConfigError`, never silently disable.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional

LATE_POLICIES = ("drop", "side_output", "refuse")


@dataclass(frozen=True)
class WindowSpec:
    """One stream's window geometry (seconds of event time)."""

    size_s: float
    slide_s: float
    time_column: str = "ts"

    def __post_init__(self):
        size = float(self.size_s)
        slide = float(self.slide_s)
        if not math.isfinite(size) or size <= 0.0:
            raise ValueError(f"WindowSpec.size_s must be finite > 0, got {self.size_s!r}")
        if not math.isfinite(slide) or slide <= 0.0:
            raise ValueError(f"WindowSpec.slide_s must be finite > 0, got {self.slide_s!r}")
        if slide > size:
            raise ValueError(
                f"WindowSpec.slide_s ({slide}) must not exceed size_s ({size}): "
                "a slide past the size would leave event-time gaps no pane covers"
            )
        object.__setattr__(self, "size_s", size)
        object.__setattr__(self, "slide_s", slide)

    @property
    def tumbling(self) -> bool:
        return self.slide_s == self.size_s

    def pane_starts_for(self, t: float) -> List[float]:
        """Every aligned window start whose pane covers event time ``t``
        (``start <= t < start + size``), oldest first."""
        newest = math.floor(t / self.slide_s) * self.slide_s
        starts: List[float] = []
        start = newest
        while start + self.size_s > t:
            starts.append(start)
            start -= self.slide_s
        return sorted(starts)

    def signature(self) -> tuple:
        """Hashable identity for plan/lint memo keys and fingerprints."""
        return (self.size_s, self.slide_s, self.time_column)


@dataclass(frozen=True)
class WatermarkPolicy:
    """Bounded-disorder watermark: ``lag_s`` of allowed event-time
    disorder, plus the typed routing for rows that fall behind it."""

    lag_s: float
    late_policy: str = "drop"

    def __post_init__(self):
        lag = float(self.lag_s)
        if not math.isfinite(lag) or lag < 0.0:
            raise ValueError(f"WatermarkPolicy.lag_s must be finite >= 0, got {self.lag_s!r}")
        if self.late_policy not in LATE_POLICIES:
            raise ValueError(
                f"WatermarkPolicy.late_policy must be one of {list(LATE_POLICIES)}, "
                f"got {self.late_policy!r}"
            )
        object.__setattr__(self, "lag_s", lag)

    def signature(self) -> tuple:
        return (self.lag_s, self.late_policy)


def resolve_window_spec(
    spec: Optional[WindowSpec] = None, time_column: str = "ts"
) -> WindowSpec:
    """An explicit spec wins; otherwise the envcfg defaults (tumbling
    when DEEQU_TPU_WINDOW_SLIDE_S is unset). Malformed env values raise
    EnvConfigError here — a stream never starts half-configured."""
    if spec is not None:
        if not isinstance(spec, WindowSpec):
            raise ValueError(f"spec must be a WindowSpec, got {type(spec).__name__}")
        return spec
    from deequ_tpu.envcfg import env_value

    size = env_value("DEEQU_TPU_WINDOW_SIZE_S")
    slide = env_value("DEEQU_TPU_WINDOW_SLIDE_S")
    return WindowSpec(size, size if slide is None else slide, time_column)


def resolve_watermark_policy(
    policy: Optional[WatermarkPolicy] = None,
) -> WatermarkPolicy:
    if policy is not None:
        if not isinstance(policy, WatermarkPolicy):
            raise ValueError(
                f"policy must be a WatermarkPolicy, got {type(policy).__name__}"
            )
        return policy
    from deequ_tpu.envcfg import env_value

    return WatermarkPolicy(
        env_value("DEEQU_TPU_WATERMARK_LAG_S"), env_value("DEEQU_TPU_LATE_POLICY")
    )
