"""The window fold axis: every open pane advances in ONE dispatch per batch.

A :class:`WindowedStream` turns the one-shot fused scan into continuous
windowed verification over an unbounded stream. Each arriving batch is
staged once and run through a single jitted pane program whose output is
the (W, leaves) block of per-pane monoid partials — sliding/tumbling
event-time windows are an extra fold DIMENSION of the device program
(the window fold axis, TiLT arXiv:2301.12030; Flare arXiv:1703.08219
motivates keeping advancement inside the one-dispatch/one-fetch
contract), never W host loops. Fold-tag semantics are preserved per
pane (sum/min/max leaves, exactly the scan engine's
``KNOWN_FOLD_TAGS`` subset), so per-window metrics are bit-identical to
a one-shot run over the same rows: pane leaves feed the analyzers' own
``state_from_scan_result`` / ``compute_metric_from`` path, and checks
evaluate through ``VerificationSuite._evaluate``.

Watermark + late data: the per-stream watermark is monotone
(``max(watermark, max_event_time - lag)``); window closes are fenced by
it, and rows older than it route by the typed policy — ``drop`` (counted
on ``ScanStats.late_rows``), ``side_output`` (batch-aligned row ranges
quarantined on the partial-result surface via
``ScanStats.record_unverified``), ``refuse`` (typed
:class:`~deequ_tpu.exceptions.LateDataException`; the batch is refused
atomically, state unchanged).

Crash safety: pane accumulators + watermark + the emitted-window ledger
persist through :class:`~deequ_tpu.windows.state.WindowStateStore`
(checksummed, atomic, versioned). The close fence (``closed_through``)
is persisted BEFORE a close emits, so a SIGKILL'd stream resumed from
any snapshot re-emits NOTHING: replayed closes at or below the fence
are suppressed (counted, never re-observed by the repository/monitor) —
window-close alerts are exactly-once through double resume. When the
state store itself is refusing writes, the engine keeps emitting
(availability) and COUNTS the unpersisted fence advance
(``state_save_failures``) — degraded resumability is reported, never
silent.

The pane program is cached module-wide by (analyzer signature, window
geometry, batch/pane shape) — a thousand streams with the same shape
share ONE trace — and lints under the ``plan-window-refeed`` rule
(lint/plan_lint.py) when DEEQU_TPU_PLAN_LINT is armed, with the window
signature folded into the lint memo key.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deequ_tpu.exceptions import LateDataException
from deequ_tpu.windows.spec import (
    WatermarkPolicy,
    WindowSpec,
    resolve_watermark_policy,
    resolve_window_spec,
)
from deequ_tpu.windows.state import (
    WindowState,
    WindowStateStore,
    stream_fingerprint,
)

_POS_INF = float("inf")
_NEG_INF = float("-inf")

#: host-side merge per fold tag (tiny per-pane scalars; the association
#: is the running left fold itself, so checkpoint/resume is bit-identical)
_MERGE: Dict[str, Callable[[float, float], float]] = {
    "sum": lambda a, b: a + b,
    "min": min,
    "max": max,
}


class _WindowStats:
    """Process-global windowed-verification counters (the obs registry's
    ``windows`` section reads these through; bench asserts the
    one-dispatch-per-batch contract on ``pane_dispatches``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        # one per processed batch, regardless of open-pane count — the
        # O(1)-dispatches observable behind config 13
        self.pane_dispatches = 0
        self.panes_opened = 0
        self.panes_closed = 0
        # emitted closes vs closes a resumed replay suppressed (the
        # exactly-once pair) vs closes the brownout shed typed
        self.closes_emitted = 0
        self.closes_suppressed = 0
        self.window_sheds = 0
        self.late_rows = 0
        self.side_output_ranges = 0
        self.refused_batches = 0
        self.stream_resumes = 0
        self.programs_built = 0
        self.state_saves = 0
        self.state_save_failures = 0

    @property
    def open_panes(self) -> int:
        return self.panes_opened - self.panes_closed

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                k: v for k, v in self.__dict__.items()
                if not k.startswith("_")
            }
        snap["open_panes"] = self.open_panes
        return snap

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + int(by))


WINDOW_STATS = _WindowStats()


# -- pane-op derivation ------------------------------------------------------

#: analyzer families with EXACT pane folds (order-insensitive monoid
#: merges over sum/min/max leaves): anything else would break the
#: bit-identical-to-one-shot contract and is refused typed at
#: registration, never silently approximated
SUPPORTED_ANALYZERS = (
    "Size", "Completeness", "Sum", "Minimum", "Maximum", "Mean",
)


def pane_signature(analyzers: Sequence[Any]) -> Tuple[Tuple[str, Optional[str]], ...]:
    """The pane program's identity for one analyzer set: (family, column)
    per analyzer. Raises typed ValueError for unsupported families or
    filtered (``where=``) analyzers — a stream never starts with an
    analyzer its pane fold cannot reproduce bit-identically."""
    sig = []
    for a in analyzers:
        kind = type(a).__name__
        if kind not in SUPPORTED_ANALYZERS:
            raise ValueError(
                f"analyzer {a} is not supported on the window fold axis: "
                f"pane folds are exact only for {list(SUPPORTED_ANALYZERS)}"
            )
        if getattr(a, "where", None) is not None:
            raise ValueError(
                f"analyzer {a} carries a where= filter; filtered pane "
                "folds are not supported on the window fold axis"
            )
        sig.append((kind, getattr(a, "column", None)))
    return tuple(sig)


def _leaf_plans(sig) -> List[Tuple[int, Optional[str], str, Dict[str, str]]]:
    """Per-analyzer leaf layout: (index, column, family, {leaf: fold tag})."""
    plans = []
    for i, (kind, col) in enumerate(sig):
        if kind == "Size":
            tags = {"n": "sum"}
        elif kind == "Completeness":
            tags = {"matches": "sum", "count": "sum"}
        elif kind == "Sum":
            tags = {"sum": "sum", "n": "sum"}
        elif kind == "Minimum":
            tags = {"value": "min", "n": "sum"}
        elif kind == "Maximum":
            tags = {"value": "max", "n": "sum"}
        else:  # Mean
            tags = {"sum": "sum", "count": "sum"}
        plans.append((i, col, kind, tags))
    return plans


def leaf_tags(sig) -> Dict[str, str]:
    """Flat leaf key ("<i>:<name>") -> fold tag for one signature."""
    out: Dict[str, str] = {}
    for i, _col, _kind, tags in _leaf_plans(sig):
        for name, tag in tags.items():
            out[f"{i}:{name}"] = tag
    return out


def _data_columns(sig) -> Tuple[str, ...]:
    return tuple(sorted({col for _kind, col in sig if col is not None}))


def _make_step(sig, size_s: float, data_cols: Tuple[str, ...]):
    """Build the UNJITTED pane step: flat args -> {leaf key: (W,) f64}
    plus the late-row census. One call advances EVERY open pane."""
    import jax.numpy as jnp

    plans = _leaf_plans(sig)
    k = len(data_cols)

    def step(times, starts, fence, *flat):
        data = dict(zip(data_cols, flat[:k]))
        valid = dict(zip(data_cols, flat[k:]))
        live = times >= fence
        within = (
            (times[None, :] >= starts[:, None])
            & (times[None, :] < starts[:, None] + size_s)
        )
        member = within & live[None, :]
        out = {}
        for i, col, kind, _tags in plans:
            if kind == "Size":
                out[f"{i}:n"] = jnp.sum(member, axis=1, dtype=jnp.float64)
                continue
            ok = member & valid[col][None, :]
            if kind == "Completeness":
                out[f"{i}:matches"] = jnp.sum(ok, axis=1, dtype=jnp.float64)
                out[f"{i}:count"] = jnp.sum(member, axis=1, dtype=jnp.float64)
            elif kind in ("Sum", "Mean"):
                total = jnp.sum(
                    jnp.where(ok, data[col][None, :], 0.0), axis=1,
                    dtype=jnp.float64,
                )
                if kind == "Sum":
                    out[f"{i}:sum"] = total
                    out[f"{i}:n"] = jnp.sum(ok, axis=1, dtype=jnp.float64)
                else:
                    out[f"{i}:sum"] = total
                    out[f"{i}:count"] = jnp.sum(ok, axis=1, dtype=jnp.float64)
            elif kind == "Minimum":
                out[f"{i}:value"] = jnp.min(
                    jnp.where(ok, data[col][None, :], _POS_INF), axis=1
                )
                out[f"{i}:n"] = jnp.sum(ok, axis=1, dtype=jnp.float64)
            else:  # Maximum
                out[f"{i}:value"] = jnp.max(
                    jnp.where(ok, data[col][None, :], _NEG_INF), axis=1
                )
                out[f"{i}:n"] = jnp.sum(ok, axis=1, dtype=jnp.float64)
        out["__late__"] = jnp.sum(times < fence, dtype=jnp.float64)
        return out

    return step


# the module-wide pane-program cache: streams sharing an analyzer
# signature + geometry share ONE trace (a ~1k-stream fleet pays one
# compile, the config-13 premise)
_PROGRAM_LOCK = threading.Lock()
_PROGRAM_CACHE: Dict[tuple, Any] = {}


def clear_program_cache() -> None:
    with _PROGRAM_LOCK:
        _PROGRAM_CACHE.clear()


def _pane_program(
    sig,
    spec: WindowSpec,
    policy: WatermarkPolicy,
    n: int,
    w: int,
):
    """The jitted pane step for (signature, geometry, batch rows, pane
    bucket) — built once, linted once (plan-window-refeed) when the plan
    lint is armed, then shared across every stream with this shape."""
    import jax

    data_cols = _data_columns(sig)
    key = (sig, spec.signature(), policy.signature(), n, w)
    with _PROGRAM_LOCK:
        prog = _PROGRAM_CACHE.get(key)
    if prog is not None:
        return prog

    step = _make_step(sig, spec.size_s, data_cols)
    jitted = jax.jit(step)

    from deequ_tpu.lint.plan_lint import plan_lint_mode

    mode = plan_lint_mode(None)
    if mode != "off":
        from deequ_tpu.lint.plan_lint import enforce_plan_lint, lint_plan_cached
        from deequ_tpu.ops.scan_engine import SCAN_STATS
        from deequ_tpu.ops.scan_plan import plan_windowed_scan

        tags = leaf_tags(sig)
        plan_ir = plan_windowed_scan(
            fold_tags=tuple(tags[k] for k in sorted(tags)),
            panes=w,
            window_spec=spec.signature(),
            watermark_policy=policy.signature(),
        )
        f64 = np.float64
        avals = [
            jax.ShapeDtypeStruct((n,), f64),   # times
            jax.ShapeDtypeStruct((w,), f64),   # pane starts
            jax.ShapeDtypeStruct((), f64),     # watermark fence
        ]
        avals += [jax.ShapeDtypeStruct((n,), f64) for _ in data_cols]
        avals += [jax.ShapeDtypeStruct((n,), np.bool_) for _ in data_cols]
        # the memo key carries the window signature: the same analyzer
        # set under a different geometry lints fresh (plan-window-refeed
        # checks the declared spec itself)
        memo_key = ("windowed", sig, spec.signature(), policy.signature(), n, w)
        findings, traced = lint_plan_cached(plan_ir, step, tuple(avals), memo_key)
        if traced:
            SCAN_STATS.plan_lint_traces += 1
        if findings:
            SCAN_STATS.plan_lints.extend(f.as_dict() for f in findings)
        enforce_plan_lint(findings, mode)

    with _PROGRAM_LOCK:
        existing = _PROGRAM_CACHE.get(key)
        if existing is not None:
            return existing
        _PROGRAM_CACHE[key] = jitted
        WINDOW_STATS.programs_built += 1
    return jitted


def _fetch_leaves(out) -> Dict[str, np.ndarray]:
    """The ONE device->host materialization per batch (the windowed
    analogue of the scan engine's one-fetch contract) — charged to the
    fetch telemetry via ``SCAN_STATS.record_fetch``."""
    from deequ_tpu.ops.scan_engine import SCAN_STATS

    host = {k: np.asarray(v) for k, v in out.items()}
    SCAN_STATS.record_fetch(sum(a.nbytes for a in host.values()))
    return host


# -- the stream --------------------------------------------------------------


@dataclass(frozen=True)
class WindowClose:
    """One pane leaving the open set. Exactly one of the flags explains
    what happened: ``emitted`` (verdict delivered), ``suppressed`` (a
    resumed replay hit the exactly-once fence), ``shed`` (the brownout
    dropped a late close, typed)."""

    stream: str
    start: float
    end: float
    emitted: bool
    suppressed: bool
    shed: bool
    result: Optional[Any]  # VerificationResult when emitted


class WindowedStream:
    """Continuous windowed verification over one unbounded stream.

    Feed host batches (``{column: np.ndarray}``; float columns use NaN
    for nulls, the event-time column must be finite) through
    :meth:`process_batch`; each call is one device dispatch and returns
    the :class:`WindowClose` records the advancing watermark produced.
    Construct with the same ``state_dir`` after a SIGKILL and the stream
    resumes mid-window bit-identically from the newest valid snapshot
    (re-feed batches from :attr:`next_batch_index`).
    """

    def __init__(
        self,
        stream_id: str,
        analyzers: Sequence[Any],
        checks: Sequence[Any] = (),
        spec: Optional[WindowSpec] = None,
        policy: Optional[WatermarkPolicy] = None,
        time_column: Optional[str] = None,
        state_dir: Optional[str] = None,
        checkpoint_every: int = 4,
        batch_rows: Optional[int] = None,
        repository=None,
        monitor=None,
        slo=None,
        should_shed: Optional[Callable[[Any, float], bool]] = None,
        budget=None,
        retry=None,
    ):
        if not analyzers:
            raise ValueError("a windowed stream needs at least one analyzer")
        if checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        self.stream_id = str(stream_id)
        self.analyzers = tuple(analyzers)
        self.checks = tuple(checks)
        self.spec = resolve_window_spec(spec, time_column or "ts")
        if time_column is not None and self.spec.time_column != time_column:
            raise ValueError(
                f"time_column {time_column!r} conflicts with "
                f"spec.time_column {self.spec.time_column!r}"
            )
        self.policy = resolve_watermark_policy(policy)
        self.sig = pane_signature(self.analyzers)
        self._tags = leaf_tags(self.sig)
        self.checkpoint_every = int(checkpoint_every)
        self.batch_rows = batch_rows
        self.repository = repository
        self.monitor = monitor
        self.slo = slo
        self.should_shed = should_shed
        self.budget = budget
        self.fingerprint = stream_fingerprint(
            self.stream_id,
            [f"{k}:{c}" for k, c in self.sig],
            self.spec.signature(),
            self.policy.signature(),
            batch_rows,
        )
        self._state = WindowState()
        self._rows_seen = 0
        self.resumed = False
        self._store = None
        if state_dir is not None:
            self._store = WindowStateStore(state_dir, retry=retry)
            recovered = self._store.load_latest(self.fingerprint)
            if recovered is not None:
                self._state = recovered
                self._rows_seen = recovered.batch_index * (batch_rows or 0)
                self.resumed = True
                WINDOW_STATS.inc("stream_resumes")

    # -- introspection ----------------------------------------------------

    @property
    def next_batch_index(self) -> int:
        """First batch index NOT yet folded — a resumed driver re-feeds
        the stream from here."""
        return self._state.batch_index

    @property
    def watermark(self) -> float:
        return self._state.watermark

    @property
    def closed_through(self) -> float:
        return self._state.closed_through

    @property
    def open_panes(self) -> List[float]:
        return sorted(self._state.panes)

    @property
    def emitted_windows(self) -> List[float]:
        return list(self._state.emitted)

    @property
    def late_rows(self) -> int:
        return self._state.late_rows

    @property
    def side_ranges(self) -> List[Tuple[int, int]]:
        return list(self._state.side_ranges)

    @property
    def sheds(self) -> List[Tuple[float, str]]:
        return list(self._state.shed)

    # -- the batch step ---------------------------------------------------

    def process_batch(
        self, batch: Dict[str, Any], row_start: Optional[int] = None
    ) -> List[WindowClose]:
        """Fold one batch (ONE device dispatch across every open pane),
        advance the watermark, and close every pane it fences off."""
        times = self._event_times(batch)
        n = times.shape[0]
        start_row = self._rows_seen if row_start is None else int(row_start)
        fence = self._state.watermark

        late = int(np.sum(times < fence))
        if late:
            self._route_late(times, fence, late, start_row, n)

        starts = self._pane_starts(times, fence)
        if starts:
            leaves = self._dispatch(batch, times, starts, fence)
            self._merge(starts, leaves)
        self._rows_seen = start_row + n
        self._state.batch_index += 1

        if n:
            batch_max = float(np.max(times))
            advanced = max(self._state.watermark, batch_max - self.policy.lag_s)
            self._state.watermark = advanced
        closes = self._close_ready(self._state.watermark)

        if self._store is not None and not closes:
            # close paths already persisted the fence; otherwise honor
            # the periodic cadence
            if self._state.batch_index % self.checkpoint_every == 0:
                self._save()
        return closes

    def flush(self) -> List[WindowClose]:
        """End-of-stream: close every remaining open pane (the watermark
        jumps to +inf). Unbounded streams never call this."""
        self._state.watermark = _POS_INF
        return self._close_ready(_POS_INF)

    # -- internals --------------------------------------------------------

    def _event_times(self, batch) -> np.ndarray:
        col = self.spec.time_column
        if col not in batch:
            raise ValueError(
                f"stream {self.stream_id!r}: batch is missing the event-time "
                f"column {col!r}"
            )
        times = np.array(batch[col], dtype=np.float64, copy=False)  # deequ-lint: ignore[host-fetch] -- host batch input, no device round trip
        if times.ndim != 1:
            raise ValueError("event-time column must be 1-D")
        if times.size and not np.all(np.isfinite(times)):
            raise ValueError(
                f"stream {self.stream_id!r}: event-time column {col!r} has "
                "non-finite entries; every row needs a valid event time"
            )
        return times

    def _route_late(self, times, fence, late, start_row, n) -> None:
        from deequ_tpu.ops.scan_engine import SCAN_STATS

        policy = self.policy.late_policy
        if policy == "refuse":
            WINDOW_STATS.inc("refused_batches")
            oldest = float(np.min(times[times < fence]))
            raise LateDataException(
                f"stream {self.stream_id!r}: {late} row(s) behind the "
                f"watermark {fence} (oldest event time {oldest}) under the "
                "'refuse' late policy; the batch was refused atomically",
                stream=self.stream_id, late_rows=late,
                watermark=fence, oldest_event_time=oldest,
            )
        self._state.late_rows += late
        WINDOW_STATS.inc("late_rows", late)
        SCAN_STATS.record_late_rows(late)
        if policy == "side_output":
            # batch-aligned quarantine on the partial-result surface:
            # the range is REPORTED (unverified_row_ranges), never silent
            self._state.side_ranges.append((start_row, start_row + n))
            WINDOW_STATS.inc("side_output_ranges")
            SCAN_STATS.record_unverified(
                start_row, start_row + n,
                reason=f"stream {self.stream_id}: {late} late row(s) "
                       f"behind watermark {fence}",
                kind="late_side_output",
            )

    def _pane_starts(self, times, fence) -> List[float]:
        live = times[times >= fence]
        needed = set(self._state.panes)
        if live.size:
            slide = self.spec.slide_s
            size = self.spec.size_s
            newest = np.floor(live / slide) * slide
            covers = max(1, int(math.ceil(size / slide)))
            for j in range(covers):
                cand = newest - j * slide
                ok = cand + size > live
                for s in np.unique(cand[ok]):
                    needed.add(float(s))
        return sorted(needed)

    def _dispatch(self, batch, times, starts, fence) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        w = len(starts)
        bucket = 1 << max(0, (w - 1).bit_length())
        prog = _pane_program(self.sig, self.spec, self.policy, times.shape[0], bucket)
        starts_arr = np.full(bucket, _POS_INF, dtype=np.float64)
        starts_arr[:w] = starts
        data_cols = _data_columns(self.sig)
        flat = []
        valids = []
        for col in data_cols:
            if col not in batch:
                raise ValueError(
                    f"stream {self.stream_id!r}: batch is missing column {col!r}"
                )
            arr = np.array(batch[col], dtype=np.float64, copy=False)  # deequ-lint: ignore[host-fetch] -- host batch input, no device round trip
            flat.append(jnp.asarray(arr))
            valids.append(jnp.asarray(~np.isnan(arr)))
        out = prog(
            jnp.asarray(times), jnp.asarray(starts_arr),
            jnp.asarray(np.float64(fence)), *flat, *valids,
        )
        WINDOW_STATS.inc("pane_dispatches")
        return _fetch_leaves(out)

    def _merge(self, starts, leaves) -> None:
        for j, start in enumerate(starts):
            acc = self._state.panes.get(start)
            if acc is None:
                acc = {}
                self._state.panes[start] = acc
                WINDOW_STATS.inc("panes_opened")
            for key, tag in self._tags.items():
                val = float(leaves[key][j])
                if key in acc:
                    acc[key] = _MERGE[tag](acc[key], val)
                else:
                    acc[key] = val

    def _close_ready(self, watermark) -> List[WindowClose]:
        ready = [
            s for s in sorted(self._state.panes)
            if s + self.spec.size_s <= watermark
        ]
        if not ready:
            return []
        closes: List[WindowClose] = []
        pending: List[Tuple[float, float, Optional[Dict[str, float]], str]] = []
        for start in ready:
            end = start + self.spec.size_s
            leaves = self._state.panes.pop(start)
            WINDOW_STATS.inc("panes_closed")
            if end <= self._state.closed_through:
                # the exactly-once fence: a resumed replay rebuilt a pane
                # whose close already emitted — suppress, re-emit NOTHING
                WINDOW_STATS.inc("closes_suppressed")
                closes.append(WindowClose(
                    self.stream_id, start, end,
                    emitted=False, suppressed=True, shed=False, result=None,
                ))
                continue
            lateness = watermark - end
            if self._shed_close(lateness):
                from deequ_tpu.resilience.governance import try_charge

                cls = getattr(self.slo, "cls", "standard")
                self._state.shed.append((end, cls))
                self._state.closed_through = end
                WINDOW_STATS.inc("window_sheds")
                try_charge(
                    self.budget, "window_shed",
                    stream=self.stream_id, window_end=end, slo_class=cls,
                )
                closes.append(WindowClose(
                    self.stream_id, start, end,
                    emitted=False, suppressed=False, shed=True, result=None,
                ))
                continue
            pending.append((start, end, leaves, "emit"))
            self._state.closed_through = end
        # persist the advanced fence BEFORE any emit: a crash past this
        # save replays with every pending close suppressed (exactly-once);
        # a failed save is counted — emission proceeds (availability) with
        # resumability degraded, reported on state_save_failures
        self._save()
        for start, end, leaves, _ in pending:
            result = self._evaluate(leaves)
            self._state.emitted.append(end)
            WINDOW_STATS.inc("closes_emitted")
            self._observe(start, end, result)
            closes.append(WindowClose(
                self.stream_id, start, end,
                emitted=True, suppressed=False, shed=False, result=result,
            ))
        if pending:
            # capture the emitted ledger too (best-effort; the fence
            # already fenced duplicates)
            self._save()
        return closes

    def _shed_close(self, lateness_s: float) -> bool:
        if self.should_shed is None:
            return False
        return bool(self.should_shed(self.slo, lateness_s))

    def _evaluate(self, leaves: Dict[str, float]):
        from deequ_tpu.analyzers.runner import AnalyzerContext
        from deequ_tpu.verification import VerificationSuite

        plans = _leaf_plans(self.sig)
        metric_map = {}
        for i, analyzer in enumerate(self.analyzers):
            _i, _col, _kind, tags = plans[i]
            result = {name: leaves[f"{i}:{name}"] for name in tags}
            state = analyzer.state_from_scan_result(result)
            metric_map[analyzer] = analyzer.compute_metric_from(state)
        ctx = AnalyzerContext(metric_map)
        return VerificationSuite._evaluate(self.checks, ctx)

    def _observe(self, start: float, end: float, result) -> None:
        if self.repository is not None:
            from deequ_tpu.analyzers.runner import AnalyzerContext
            from deequ_tpu.repository.base import AnalysisResult, ResultKey

            key = ResultKey(
                int(round(end * 1000.0)),
                {
                    "stream": self.stream_id,
                    "window_start": repr(start),
                    "window_end": repr(end),
                },
            )
            self.repository.save(
                AnalysisResult(key, AnalyzerContext(dict(result.metrics)))
            )
        if self.monitor is not None:
            try:
                self.monitor.observe_verification(self.stream_id, result)
            # deequ-lint: ignore[bare-except] -- monitoring is observation, never outcome: a watch-rule error must not fail a window close that already emitted; the error is counted on MONITOR_STATS
            except Exception:  # noqa: BLE001
                from deequ_tpu.repository.monitor import MONITOR_STATS

                MONITOR_STATS.monitor_errors += 1

    def _save(self) -> None:
        if self._store is None:
            return
        ok = self._store.save(self.fingerprint, self._state)
        WINDOW_STATS.inc("state_saves" if ok else "state_save_failures")


def drive(stream: WindowedStream, batches, flush: bool = False) -> List[WindowClose]:
    """Advance ``stream`` over ``batches`` (an iterable of host batch
    dicts), skipping every batch a resumed stream already folded. The
    windowed executor seam (``ops/scan_executors.run_windowed_scan``)
    delegates here."""
    closes: List[WindowClose] = []
    skip = stream.next_batch_index
    for i, batch in enumerate(batches):
        if i < skip:
            continue
        closes.extend(stream.process_batch(batch))
    if flush:
        closes.extend(stream.flush())
    return closes
