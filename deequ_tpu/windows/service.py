"""Streams are tenants: SLO-classed registration, brownout-typed sheds.

A :class:`StreamHub` owns a fleet of :class:`WindowedStream`s the way
``VerificationService`` owns request tenants: every stream registers
under an :class:`~deequ_tpu.serve.admission.Slo`, per-window
VerificationResults append to the shared metrics repository and feed the
shared :class:`~deequ_tpu.repository.monitor.QualityMonitor` at window
close (the PR-13 save/resolve seams), and overload demotes LATE window
closes to TYPED sheds — ``window_shed`` records charged through the
run-budget governance ledger — while ``critical`` streams keep closing
on deadline. A shed is never silent staleness: the close is recorded on
the stream's shed ledger (and persists through kill-and-resume), the
window's fence still advances (the stale verdict is dropped, not
deferred), and the brownout signal that caused it is observable.

The shed predicate is deterministic in event time: a close is LATE when
the watermark has moved past the window end by more than the stream's
SLO deadline (``(watermark - end) * 1000 > deadline_ms``); only late
closes of non-critical streams shed, and only while the hub's overload
level is raised (wire a ``BrownoutController`` via
:meth:`update_pressure`, hand the hub a ``VerificationService`` to
share its monitor, or drive :meth:`set_overload` directly — the chaos
``window`` seam does the latter with scripted overload spikes).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from deequ_tpu.windows.engine import WindowClose, WindowedStream
from deequ_tpu.windows.spec import WatermarkPolicy, WindowSpec


class StreamHub:
    """Registry + close governor for a fleet of windowed streams."""

    def __init__(
        self,
        repository=None,
        monitor=None,
        service=None,
        brownout=None,
        budget=None,
        state_root: Optional[str] = None,
        checkpoint_every: int = 4,
        retry=None,
    ):
        self.repository = repository
        self.monitor = monitor if monitor is not None else getattr(
            service, "monitor", None
        )
        self.service = service
        self.brownout = brownout
        self.budget = budget
        self.state_root = state_root
        self.checkpoint_every = int(checkpoint_every)
        self._retry = retry
        self._overload_level = 0
        self._streams: Dict[str, WindowedStream] = {}
        self._lock = threading.RLock()
        #: every typed shed the hub governed: (stream, window_end, slo cls)
        self.sheds: List[tuple] = []

    # -- overload signal --------------------------------------------------

    def set_overload(self, level: int) -> None:
        """Directly set the overload level (0 = healthy). The chaos
        ``window`` seam scripts spikes through here."""
        self._overload_level = max(0, int(level))

    def update_pressure(self, queue_depth: int, cost_frac=None) -> int:
        """Feed queue pressure through the wired BrownoutController (the
        serving ladder's hysteresis) and adopt its level."""
        if self.brownout is not None:
            self._overload_level = int(
                self.brownout.update(queue_depth, cost_frac)
            )
        return self._overload_level

    @property
    def overload_level(self) -> int:
        return self._overload_level

    def _should_shed(self, slo, lateness_s: float) -> bool:
        if self._overload_level < 1:
            return False
        if slo is None or getattr(slo, "cls", "standard") == "critical":
            # critical streams keep closing on deadline, whatever the level
            return False
        deadline_ms = float(getattr(slo, "deadline_ms", 0.0) or 0.0)
        return lateness_s * 1000.0 > deadline_ms

    # -- registration -----------------------------------------------------

    def register_stream(
        self,
        stream_id: str,
        analyzers: Sequence[Any],
        checks: Sequence[Any] = (),
        slo=None,
        spec: Optional[WindowSpec] = None,
        policy: Optional[WatermarkPolicy] = None,
        time_column: Optional[str] = None,
        batch_rows: Optional[int] = None,
    ) -> WindowedStream:
        """Register one stream under an Slo (default: the serving
        default class). Re-registering a live stream id is refused typed
        — two writers on one window-state directory would fence each
        other's closes."""
        from deequ_tpu.serve.admission import resolve_slo

        with self._lock:
            if stream_id in self._streams:
                raise ValueError(f"stream {stream_id!r} is already registered")
            state_dir = None
            if self.state_root is not None:
                state_dir = f"{self.state_root.rstrip('/')}/{stream_id}"
            stream = WindowedStream(
                stream_id,
                analyzers,
                checks=checks,
                spec=spec,
                policy=policy,
                time_column=time_column,
                state_dir=state_dir,
                checkpoint_every=self.checkpoint_every,
                batch_rows=batch_rows,
                repository=self.repository,
                monitor=self.monitor,
                slo=resolve_slo(slo),
                should_shed=self._should_shed,
                budget=self.budget,
                retry=self._retry,
            )
            self._streams[stream_id] = stream
            return stream

    def deregister_stream(self, stream_id: str) -> None:
        with self._lock:
            self._streams.pop(stream_id, None)

    def stream(self, stream_id: str) -> WindowedStream:
        with self._lock:
            stream = self._streams.get(stream_id)
        if stream is None:
            raise ValueError(f"no registered stream {stream_id!r}")
        return stream

    @property
    def stream_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._streams)

    # -- batch routing ----------------------------------------------------

    def process_batch(
        self, stream_id: str, batch: Dict[str, Any]
    ) -> List[WindowClose]:
        """Advance one stream by one batch; shed closes are recorded on
        the hub ledger too (the cross-stream observable)."""
        closes = self.stream(stream_id).process_batch(batch)
        for close in closes:
            if close.shed:
                cls = getattr(
                    self.stream(stream_id).slo, "cls", "standard"
                )
                self.sheds.append((stream_id, close.end, cls))
        return closes
