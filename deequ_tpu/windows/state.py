"""Crash-safe window state: pane stacks + watermark + emitted-window ledger.

One :class:`WindowState` is everything a windowed stream needs to resume
mid-window bit-identically after a SIGKILL: the per-pane leaf
accumulators (plain f64 monoid partials — the running left fold itself,
so a resumed merge uses the exact association an uninterrupted run
would), the monotone watermark, the late/side-output ledgers, and the
exactly-once close fence (``closed_through`` + the emitted-window
ledger): a resumed stream suppresses every replayed close at or below
the fence and re-emits NOTHING.

Persistence rides the PR-2 checksummed checkpoint machinery
(resilience/atomic.py): versioned files ``wstate_<seq>.dqws`` inside a
checksum envelope, written atomically, the last ``keep`` retained so a
write torn by a crash falls back to its predecessor — the same
fallback contract the crashpoint matrix (resilience/vfs_faults.py)
verifies for the stream-checkpoint store, which this store joins as the
fifth durable surface. Saves are best-effort by contract: a failed save
is COUNTED and degrades resumability, never correctness. The close-time
save is NOT best-effort in spirit — the engine persists the advanced
close fence BEFORE emitting, so a crash between fence and emit costs an
alert (at-most-once for that tail), never a duplicate.

Format: ``DQWN | version(u16) | fingerprint | seq(i64) | batch_index(i64)
| watermark(f64) | closed_through(f64) | late_rows(i64) | side ranges |
shed ledger | emitted ledger | panes`` in a checksum envelope.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from deequ_tpu.exceptions import CorruptStateException
from deequ_tpu.resilience.atomic import (
    atomic_write_bytes,
    read_checksummed,
    wrap_checksum,
)

MAGIC = b"DQWN"
VERSION = 1

_u16 = struct.Struct("<H")
_i64 = struct.Struct("<q")
_f64 = struct.Struct("<d")

#: emitted-window ledger entries retained in full; older closes are
#: summarized by the fence (closed_through) alone, which is all the
#: exactly-once suppression needs
LEDGER_CAP = 256


def _pack_str(s: str) -> bytes:
    raw = s.encode("utf-8")
    return _i64.pack(len(raw)) + raw


def _unpack_str(buf: bytes, off: int) -> Tuple[str, int]:
    (n,) = _i64.unpack_from(buf, off)
    off += 8
    return buf[off:off + n].decode("utf-8"), off + n


@dataclass
class WindowState:
    """One recovered snapshot of a windowed stream (see module doc)."""

    batch_index: int = 0
    watermark: float = float("-inf")
    #: the exactly-once close fence: highest window end ever EMITTED (or
    #: shed typed) — a resumed replay suppresses closes at or below it
    closed_through: float = float("-inf")
    late_rows: int = 0
    #: quarantined [start, stop) global row ranges (side_output policy)
    side_ranges: List[Tuple[int, int]] = field(default_factory=list)
    #: typed sheds: (window_end, slo_class) — closes the brownout dropped
    shed: List[Tuple[float, str]] = field(default_factory=list)
    #: emitted-window ledger: window ends, in emit order (capped)
    emitted: List[float] = field(default_factory=list)
    #: open pane accumulators: window start -> {leaf key: f64 partial}
    panes: Dict[float, Dict[str, float]] = field(default_factory=dict)


def _encode(fingerprint: str, seq: int, state: WindowState) -> bytes:
    out = [MAGIC, _u16.pack(VERSION), _pack_str(fingerprint)]
    out.append(_i64.pack(seq))
    out.append(_i64.pack(state.batch_index))
    out.append(_f64.pack(state.watermark))
    out.append(_f64.pack(state.closed_through))
    out.append(_i64.pack(state.late_rows))
    out.append(_i64.pack(len(state.side_ranges)))
    for start, stop in state.side_ranges:
        out.append(_i64.pack(start))
        out.append(_i64.pack(stop))
    out.append(_i64.pack(len(state.shed)))
    for end, cls in state.shed:
        out.append(_f64.pack(end))
        out.append(_pack_str(cls))
    emitted = state.emitted[-LEDGER_CAP:]
    out.append(_i64.pack(len(emitted)))
    for end in emitted:
        out.append(_f64.pack(end))
    out.append(_i64.pack(len(state.panes)))
    for start in sorted(state.panes):
        leaves = state.panes[start]
        out.append(_f64.pack(start))
        out.append(_i64.pack(len(leaves)))
        for key in sorted(leaves):
            out.append(_pack_str(key))
            out.append(_f64.pack(leaves[key]))
    return b"".join(out)


def _decode(payload: bytes, what: str) -> Tuple[str, int, WindowState]:
    if payload[:4] != MAGIC:
        raise CorruptStateException(what, "bad window-state magic")
    (version,) = _u16.unpack_from(payload, 4)
    if version > VERSION:
        raise CorruptStateException(
            what, f"window-state version {version} newer than supported {VERSION}"
        )
    off = 6
    fingerprint, off = _unpack_str(payload, off)
    (seq,) = _i64.unpack_from(payload, off); off += 8
    state = WindowState()
    (state.batch_index,) = _i64.unpack_from(payload, off); off += 8
    (state.watermark,) = _f64.unpack_from(payload, off); off += 8
    (state.closed_through,) = _f64.unpack_from(payload, off); off += 8
    (state.late_rows,) = _i64.unpack_from(payload, off); off += 8
    (n_ranges,) = _i64.unpack_from(payload, off); off += 8
    for _ in range(n_ranges):
        (start,) = _i64.unpack_from(payload, off); off += 8
        (stop,) = _i64.unpack_from(payload, off); off += 8
        state.side_ranges.append((start, stop))
    (n_shed,) = _i64.unpack_from(payload, off); off += 8
    for _ in range(n_shed):
        (end,) = _f64.unpack_from(payload, off); off += 8
        cls, off = _unpack_str(payload, off)
        state.shed.append((end, cls))
    (n_emitted,) = _i64.unpack_from(payload, off); off += 8
    for _ in range(n_emitted):
        (end,) = _f64.unpack_from(payload, off); off += 8
        state.emitted.append(end)
    (n_panes,) = _i64.unpack_from(payload, off); off += 8
    for _ in range(n_panes):
        (start,) = _f64.unpack_from(payload, off); off += 8
        (n_leaves,) = _i64.unpack_from(payload, off); off += 8
        leaves: Dict[str, float] = {}
        for _ in range(n_leaves):
            key, off = _unpack_str(payload, off)
            (val,) = _f64.unpack_from(payload, off); off += 8
            leaves[key] = val
        state.panes[start] = leaves
    return fingerprint, seq, state


class WindowStateStore:
    """Owns one window-state directory for one logical stream.

    ``fingerprint`` ties snapshots to the stream's configuration
    (analyzer set + window geometry + batch geometry): a snapshot
    written under a different fingerprint is ignored on resume rather
    than folded into the wrong stream. The last ``keep`` snapshots are
    retained so a snapshot torn by a crash falls back to its
    predecessor.
    """

    def __init__(self, directory: str, keep: int = 2, retry=None):
        from deequ_tpu.data.fs import filesystem_for, strip_scheme
        from deequ_tpu.resilience.retry import RetryingFileSystem

        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = strip_scheme(directory)
        self.keep = int(keep)
        self._fs = RetryingFileSystem(filesystem_for(directory), retry)
        self._retry = retry
        self._seq = 0
        # telemetry for tests/bench: how many saves happened / failed
        self.saves = 0
        self.save_failures = 0

    def _path(self, seq: int) -> str:
        return self._fs.join(self.directory, f"wstate_{seq:010d}.dqws")

    def _list(self) -> List[str]:
        if not self._fs.exists(self.directory):
            return []
        return [
            n
            for n in self._fs.listdir(self.directory)
            if n.startswith("wstate_") and n.endswith(".dqws")
        ]

    def _resync_seq(self) -> None:
        """Advance the write sequence past every snapshot on disk so a
        writer never reuses (and silently overwrites) a live sequence
        number — a resumed process and the crashpoint adapter both
        construct fresh stores over an existing directory."""
        try:
            names = self._list()
        # deequ-lint: ignore[bare-except] -- an unlistable store degrades to seq 0; the atomic write itself still cannot tear an existing file
        except Exception:  # noqa: BLE001 — unlistable: keep current seq
            return
        for name in names:
            try:
                self._seq = max(
                    self._seq, int(name[len("wstate_"):-len(".dqws")])
                )
            except ValueError:
                continue

    def save(self, fingerprint: str, state: WindowState) -> bool:
        """Persist one snapshot (atomic + checksummed). Returns False —
        and keeps the stream alive — when storage refuses past retries:
        a failed save degrades resumability, not correctness (the engine
        checks the return value at CLOSE-time saves and refuses to treat
        an unpersisted fence as advanced)."""
        if self._seq == 0:
            self._resync_seq()
        self._seq += 1
        try:
            payload = wrap_checksum(_encode(fingerprint, self._seq, state))
            self._fs.makedirs(self.directory)
            atomic_write_bytes(
                self._fs, self._path(self._seq), payload,
                retry=self._retry,
                what=f"window state seq {self._seq}",
            )
        # deequ-lint: ignore[bare-except] -- window-state saves are best-effort by contract: a failed save is COUNTED (save_failures) and the stream continues; the engine treats a failed CLOSE-time save as an unadvanced fence
        except Exception:  # noqa: BLE001 — saving is best-effort
            self.save_failures += 1
            return False
        self.saves += 1
        self._prune()
        return True

    def _prune(self) -> None:
        try:
            names = sorted(self._list())
        # deequ-lint: ignore[bare-except] -- pruning is housekeeping; an unlistable store must not fail the stream
        except Exception:  # noqa: BLE001 — pruning is housekeeping only
            return
        for stale in names[: max(len(names) - self.keep, 0)]:
            try:
                self._fs.delete(self._fs.join(self.directory, stale))
            # deequ-lint: ignore[bare-except] -- stale snapshot files are harmless; deletion is best-effort
            except Exception:  # noqa: BLE001 — stale files are harmless
                pass

    def load_latest(self, fingerprint: str) -> Optional[WindowState]:
        """Newest valid snapshot matching ``fingerprint`` — corrupt or
        mismatched files are skipped (falling back to older ones), never
        fatal: worst case the stream restarts from batch 0. Resyncs the
        store's write sequence past every file seen so a resumed writer
        never reuses a live sequence number."""
        try:
            names = sorted(self._list(), reverse=True)
        # deequ-lint: ignore[bare-except] -- unreachable store degrades to a fresh stream (documented load_latest contract)
        except Exception:  # noqa: BLE001 — unreachable store: start fresh
            return None
        self._resync_seq()
        for name in names:
            path = self._fs.join(self.directory, name)
            try:
                payload = read_checksummed(
                    self._fs, path, f"window state {name}", retry=self._retry
                )
                found_fp, seq, state = _decode(payload, f"window state {name}")
            # deequ-lint: ignore[bare-except] -- damaged snapshots fall back to older ones; CorruptStateException is typed upstream
            except Exception:  # noqa: BLE001 — damaged snapshot: fall back
                continue
            if found_fp != fingerprint:
                continue
            return state
        return None

    def clear(self) -> None:
        """Drop all snapshots (a completed/abandoned stream's cleanup)."""
        try:
            names = self._list()
        # deequ-lint: ignore[bare-except] -- unreachable store means nothing to clear; best-effort
        except Exception:  # noqa: BLE001 — unreachable store: nothing kept
            return
        for name in names:
            try:
                self._fs.delete(self._fs.join(self.directory, name))
            # deequ-lint: ignore[bare-except] -- per-file deletion during clear() is best-effort
            except Exception:  # noqa: BLE001
                pass


def stream_fingerprint(
    stream_id: str,
    analyzer_keys,
    window_signature: tuple,
    policy_signature: tuple,
    batch_rows: Optional[int],
) -> str:
    """Stable identity of a windowed stream's fold configuration: the
    analyzer set, the window/watermark geometry, and the batch geometry
    (batch boundaries must match for a resumed fold to be meaningful)."""
    import hashlib

    basis = repr((
        str(stream_id), sorted(str(k) for k in analyzer_keys),
        tuple(window_signature), tuple(policy_signature), batch_rows,
    )).encode()
    return hashlib.sha1(basis).hexdigest()
