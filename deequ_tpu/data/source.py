"""Out-of-core batch sources — bounded-memory ingestion.

The reference reads TB datasets because Spark streams partitions from
storage instead of materializing tables (the 3-pass profiler is explicitly
designed around that, reference profiles/ColumnProfiler.scala:57-68). The
TPU-native analogue: a ``BatchSource`` yields fixed-size ``ColumnarTable``
batches straight off storage; the scan engine packs each batch into device
chunks with a read-ahead thread so host decode, host->device transfer, and
device compute overlap. Host RSS stays bounded by
O(batch_rows x row_width x read_ahead), independent of dataset size.

``ParquetBatchSource`` streams row batches via
``pyarrow.ParquetFile.iter_batches`` — the schema and row count come from
file metadata, so nothing is read until batches are consumed.
"""

from __future__ import annotations

from typing import Collection, Dict, Iterator, List, Optional, Sequence

import numpy as np

from deequ_tpu.data.table import Column, ColumnarTable, DType, Field, Schema

# target host bytes per streamed batch (host representation, before packing)
DEFAULT_BATCH_BYTES = 256 << 20


def batch_rows_for_schema(
    schema: Schema,
    target_bytes: int = DEFAULT_BATCH_BYTES,
    encoded: Collection[str] = (),
) -> int:
    """Rows per batch so one batch is ~target_bytes on host.

    ``encoded`` names the columns the source reports as dictionary-
    encoded: those arrive as int16 codes (+ a tiny dictionary), 2
    bytes/row, not the 9 bytes/row of a decoded value+mask column —
    sizing them full-width under-fills batches 2-8x on dictionary-heavy
    tables (each batch then ships a fraction of the target bytes and the
    per-batch fixed costs dominate)."""
    encoded = set(encoded)
    bytes_per_row = 0
    for f in schema:
        if f.dtype == DType.STRING:
            bytes_per_row += 4  # i32 codes
        elif f.name in encoded:
            bytes_per_row += 2  # i16 dictionary codes (validity rides in them)
        else:
            bytes_per_row += 9  # value + mask
    bytes_per_row = max(bytes_per_row, 1)
    return int(min(max(target_bytes // bytes_per_row, 1 << 16), 1 << 24))


class BatchSource:
    """Protocol for bounded-memory batch producers."""

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def num_rows(self) -> Optional[int]:
        """Total rows if knowable from metadata, else None."""
        return None

    @property
    def encoded_column_names(self) -> frozenset:
        """Columns this source delivers dictionary-ENCODED (int16 codes +
        dictionary + validity bitmap as the Column payload). Drives the
        source's encoded-aware batch SIZING (``batch_rows_for_schema``)
        and is advertised for introspection/tests; note the scan engine
        routes off the actual ``Column.encoding`` payload of each batch,
        not this property — a custom source must attach ``ColumnChunk``
        payloads (or call ``table.encode()`` per batch) for the encoded
        plane to engage. Default: none."""
        return frozenset()

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        raise NotImplementedError

    def batches_from(
        self,
        start: int = 0,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        """Batches from batch index ``start`` — the seek primitive the
        resilience layer's retry-reopen and checkpoint-resume paths use
        (deequ_tpu/resilience). Batch boundaries are deterministic for a
        fixed ``batch_rows``, so index k names the same rows every call.

        Default: re-iterate and drop the first ``start`` batches (skipped
        batches are re-decoded but not processed). Sources with native
        seeks override this."""
        import itertools

        return itertools.islice(
            self.batches(columns=columns, batch_rows=batch_rows), start, None
        )

    def with_retry(self, policy=None):
        """This source wrapped so every batch read runs under a
        RetryPolicy (resilience/retry.py: reopen-and-fast-forward on
        transient errors)."""
        from deequ_tpu.resilience.retry import RetryingBatchSource

        return RetryingBatchSource(self, policy)


def _restrict_arrow_schema(arrow_schema, names, what: str):
    """Map requested column names onto an arrow schema -> engine Fields."""
    fields = []
    for name in names:
        idx = arrow_schema.get_field_index(name)
        if idx < 0:
            raise ValueError(f"column {name!r} not in {what}")
        fields.append(Field(name, _arrow_field_dtype(arrow_schema.field(idx).type)))
    return fields


def _arrow_field_dtype(pa_type) -> DType:
    import pyarrow as pa

    if pa.types.is_dictionary(pa_type):
        pa_type = pa_type.value_type
    if pa.types.is_integer(pa_type):
        return DType.INTEGRAL
    if pa.types.is_floating(pa_type):
        return DType.FRACTIONAL
    if pa.types.is_boolean(pa_type):
        return DType.BOOLEAN
    return DType.STRING


def _dictionary_encoded_columns(pf, names, schema) -> frozenset:
    """The NUMERIC columns of one ParquetFile whose every column chunk
    was written dictionary-encoded (metadata only; see the caller's
    rationale comment). The writer's dictionary page itself is PLAIN, so
    PLAIN next to a *_DICTIONARY encoding is normal — a genuinely
    overflowed (high-cardinality fallback) column is caught later, at
    re-encode time, by the int16 cardinality cap."""
    meta = pf.metadata
    # physical column order -> name, for the selected flat columns
    phys_names = [
        meta.schema.column(i).path for i in range(meta.num_columns)
    ]
    wanted = {
        n for n in names
        if schema[n].dtype in (DType.INTEGRAL, DType.FRACTIONAL)
    }
    encoded = set(wanted) if meta.num_row_groups else set()
    for rg in range(meta.num_row_groups):
        group = meta.row_group(rg)
        for i, name in enumerate(phys_names):
            if name not in encoded:
                continue
            encs = set(group.column(i).encodings)
            if not encs & {"PLAIN_DICTIONARY", "RLE_DICTIONARY"}:
                encoded.discard(name)
    return frozenset(encoded)


def _encode_arrow_batch(table, encode_names: set, batch_rows: int):
    """Dictionary-encode the named columns of one Arrow table IN ARROW
    (``pc.dictionary_encode``: hash-based C++, ~O(n)) so ``from_arrow``
    carries codes + dictionary + validity through as the Column's
    encoded payload instead of materializing full-width numpy values.
    pyarrow's Parquet reader only returns DictionaryArrays for
    byte-array columns, so numerics the file METADATA reports as
    dictionary-encoded are re-encoded here — the decoded numpy form
    still never exists. Columns whose dictionary exceeds the int16 cap
    (the writer's overflow fallback) are dropped from ``encode_names``
    (mutated) and stay plain for the rest of the stream."""
    import pyarrow.compute as pc

    from deequ_tpu.data.table import MAX_ENCODED_CARDINALITY

    for name in sorted(encode_names & set(table.column_names)):
        idx = table.column_names.index(name)
        combined = table.column(idx).combine_chunks()
        encoded = pc.dictionary_encode(combined)
        # density rule: past 1 dictionary entry per 4 rows the encoded
        # form (2B codes + 8B/distinct) stops beating the decoded
        # 9B/row — near-unique columns (the writer's dictionary
        # survived only because the file is small) stay plain. The
        # denominator is the FULL batch size (never this batch's
        # length: a short remainder/tail batch must not permanently
        # demote a genuinely low-cardinality column for the rest of the
        # stream), bounded by the source's total rows so a small file
        # doesn't inherit a huge configured batch as its density budget
        cap = min(
            MAX_ENCODED_CARDINALITY,
            max(max(batch_rows, len(combined)) // 4, 1),
        )
        if len(encoded.dictionary) > cap:
            encode_names.discard(name)
            continue
        table = table.set_column(idx, name, encoded)
    return table


class ParquetBatchSource(BatchSource):
    """Stream one or more Parquet files as ColumnarTable batches.

    Schema and total row count come from file metadata (no data read);
    ``batches`` decodes ``pyarrow.ParquetFile.iter_batches`` output one
    batch at a time — the whole file is never materialized.
    """

    def __init__(
        self,
        paths,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
        pre_buffer: bool = False,
    ):
        import pyarrow.parquet as pq

        # pre_buffer=True (pyarrow's default) reads ALL row groups ahead —
        # O(file) host memory, exactly what out-of-core must avoid; False
        # streams row groups on demand (measured: 1GB file iterates at
        # 0.35GB RSS vs 1.44GB pre-buffered). Set True only for
        # high-latency object stores where random reads dominate.
        self.pre_buffer = pre_buffer
        self.paths: List[str] = [paths] if isinstance(paths, str) else list(paths)
        if not self.paths:
            raise ValueError("ParquetBatchSource needs at least one path")
        self._restrict = list(columns) if columns is not None else None
        self._batch_rows = batch_rows
        # metadata-only pass: schema + row count without reading data pages
        first = pq.ParquetFile(self.paths[0])
        arrow_schema = first.schema_arrow
        names = (
            self._restrict
            if self._restrict is not None
            else list(arrow_schema.names)
        )
        self._schema = Schema(
            _restrict_arrow_schema(arrow_schema, names, "parquet schema")
        )
        # dictionary-encoded column detection (metadata only): a column
        # qualifies when EVERY row group of EVERY file wrote it purely
        # dictionary-encoded — a 'PLAIN' encoding next to the dictionary
        # one means the writer's dictionary page overflowed mid-chunk
        # (the high-cardinality fallback) and the column must decode
        self._encoded = _dictionary_encoded_columns(first, names, self._schema)
        n = first.metadata.num_rows
        for path in self.paths[1:]:
            pf = pq.ParquetFile(path)
            self._encoded &= _dictionary_encoded_columns(
                pf, names, self._schema
            )
            # compare only the SELECTED fields, by name: batches() reads
            # columns by name per file, so extra/reordered unselected
            # columns in a later file are fine — a selected column that
            # is missing or type-changed is not
            other = pf.schema_arrow
            for name in names:
                idx = other.get_field_index(name)
                # compare the MAPPED engine dtype, not exact arrow types:
                # width-compatible files (int32 vs int64, float32 vs
                # float64) decode to the same Column dtype per batch and
                # stream fine; only genuine kind conflicts (int vs string)
                # should fail fast
                if idx < 0 or _arrow_field_dtype(
                    other.field(idx).type
                ) != _arrow_field_dtype(arrow_schema.field(name).type):
                    raise ValueError(
                        f"parquet schema mismatch: column {name!r} in "
                        f"{path!r} is "
                        f"{other.field(idx).type if idx >= 0 else 'missing'},"
                        f" expected {arrow_schema.field(name).type} "
                        f"(from {self.paths[0]!r})"
                    )
            n += pf.metadata.num_rows
        self._num_rows = int(n)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return self._num_rows

    @property
    def encoded_column_names(self) -> frozenset:
        return self._encoded

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.data.io import from_arrow

        names = list(columns) if columns is not None else self._schema.column_names
        names = [n for n in self._schema.column_names if n in set(names)]
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(
            Schema([self._schema[n] for n in names]),
            encoded=self._encoded & set(names),
        )
        # columns the file metadata reports dictionary-encoded stay
        # encoded end to end: re-encoded in Arrow per batch (see
        # _encode_arrow_batch), then carried by from_arrow as the
        # Column's ColumnChunk payload — codes + dictionary + validity
        # bitmap, never decoded f64/i64 on host
        enc_active = set(self._encoded & set(names))
        # the density denominator: a full batch, or the whole (smaller)
        # source — a 4k-row file read at a 16M-row default batch size
        # must judge density against its 4k rows
        cap_rows = min(rows, self._num_rows) if self._num_rows else rows
        for path in self.paths:
            pf = pq.ParquetFile(path, pre_buffer=self.pre_buffer)
            for record_batch in pf.iter_batches(batch_size=rows, columns=names):
                tab = pa.Table.from_batches([record_batch])
                if enc_active:
                    tab = _encode_arrow_batch(tab, enc_active, cap_rows)
                yield from_arrow(tab)


def _bool_literals() -> frozenset:
    """Lowered bool literal set, derived from read_csv's _TRUE/_FALSE so
    the two CSV frontends cannot drift apart."""
    from deequ_tpu.data.io import _FALSE, _TRUE

    return frozenset(s.lower() for s in (_TRUE | _FALSE))


def _classify_string_values(col):
    """Capability flags (can_int, can_float, can_bool) for one block's
    non-null string values. Capabilities AND across blocks and the final
    type applies read_csv's precedence (int > float > bool > string), so
    block-local classification can never disagree with a whole-file pass
    — e.g. '0'/'1' rows in one block and 'true' in another still join to
    BOOLEAN, exactly as read_csv infers over the full column."""
    import pyarrow as pa
    import pyarrow.compute as pc

    def can_cast(t):
        try:
            pc.cast(col, t)
            return True
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            return False

    can_int = can_cast(pa.int64())
    can_float = can_int or can_cast(pa.float64())
    lowered = set(pc.utf8_lower(col).unique().to_pylist())
    can_bool = lowered <= _bool_literals()
    return can_int, can_float, can_bool


class CSVBatchSource(BatchSource):
    """Stream a CSV file as ColumnarTable batches via pyarrow's streaming
    CSV reader (C++ incremental parser; the file is never materialized).

    Null semantics match ``read_csv``: ONLY the empty cell is null
    (strings stay strings — 'NA'/'nan' literals are data, not nulls).

    Schema: ``column_types`` pins dtypes directly (the bounded-memory
    path for huge files); otherwise one streaming schema pass infers each
    column's widened type over the WHOLE file (int64 -> float64 ->
    string), so a value late in the file can never crash the analysis
    the way a sampled-prefix schema would."""

    def __init__(
        self,
        path: str,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
        delimiter: str = ",",
        column_types: Optional[Dict[str, DType]] = None,
    ):
        import pyarrow as pa

        self.path = path
        self.delimiter = delimiter
        self._restrict = list(columns) if columns is not None else None
        self._batch_rows = batch_rows
        if column_types is not None:
            arrow_of = {
                DType.INTEGRAL: pa.int64(),
                DType.FRACTIONAL: pa.float64(),
                DType.BOOLEAN: pa.bool_(),
                DType.STRING: pa.string(),
            }
            header = self._open(block_rows=1 << 12).schema
            pinned = {
                name: arrow_of[column_types.get(name, DType.STRING)]
                for name in header.names
            }
            arrow_schema = pa.schema(
                [pa.field(n, pinned[n]) for n in header.names]
            )
        else:
            arrow_schema = self._infer_schema_streaming()
        names = (
            self._restrict
            if self._restrict is not None
            else list(arrow_schema.names)
        )
        self._schema = Schema(
            _restrict_arrow_schema(arrow_schema, names, "CSV header")
        )
        self._arrow_schema = arrow_schema
        # CSV has no encoding metadata (unlike Parquet): sniff the FIRST
        # block's cardinality to opt low-cardinality numeric columns into
        # the encoded ingest plane — the PR-8 follow-up (docs/ingest.md).
        # LAZY (None = not sniffed yet): the sniff parses a real block,
        # and a source constructed only for schema introspection must
        # not pay that I/O up front the way the metadata-only Parquet
        # detection never does
        self._encoded: Optional[frozenset] = None

    def _infer_schema_streaming(self):
        """One streaming pass over the file, widening each column's type
        across blocks (bounded memory; reads the file once for schema).

        Every column is READ as string and classified on host: pyarrow's
        open_csv pins each column's type from its first block, so letting
        it infer would raise ArrowInvalid on a type-widening value in any
        later block (e.g. a '3.5' past the first ~4MB of an int column) —
        the exact failure this pass exists to prevent."""
        import pyarrow as pa

        header = self._open(block_rows=1 << 12).schema
        all_string = pa.schema(
            [pa.field(n, pa.string()) for n in header.names]
        )
        caps = {}  # name -> (can_int, can_float, can_bool), AND across blocks
        for record_batch in self._open(
            block_rows=1 << 16, pin_schema=all_string
        ):
            for i, field in enumerate(record_batch.schema):
                name = field.name
                if caps.get(name) == (False, False, False):
                    continue  # already string; cannot widen further
                col = record_batch.column(i).drop_null()
                if len(col) == 0:
                    continue  # all-null block: no information
                c = _classify_string_values(col)
                prev = caps.get(name)
                caps[name] = c if prev is None else tuple(
                    a and b for a, b in zip(prev, c)
                )
        out = []
        for name in header.names:
            c = caps.get(name)
            if c is None:
                t = pa.string()  # all-null column
            else:
                can_int, can_float, can_bool = c
                # read_csv precedence: int > float > bool > string
                if can_int:
                    t = pa.int64()
                elif can_float:
                    t = pa.float64()
                elif can_bool:
                    t = pa.bool_()
                else:
                    t = pa.string()
            out.append(pa.field(name, t))
        return pa.schema(out)

    def _open(self, block_rows: int, pin_schema=None, include=None):
        import pyarrow.csv as pacsv

        # block size in bytes; estimate ~64 bytes/row as a coarse default
        block_bytes = max(block_rows * 64, 1 << 16)
        convert = pacsv.ConvertOptions(
            column_types=dict(zip(pin_schema.names, pin_schema.types))
            if pin_schema is not None
            else None,
            include_columns=include if include is not None else self._restrict,
            # read_csv parity: ONLY the empty cell is null, and it is null
            # in string columns too
            null_values=[""],
            strings_can_be_null=True,
        )
        return pacsv.open_csv(
            self.path,
            read_options=pacsv.ReadOptions(block_size=block_bytes),
            parse_options=pacsv.ParseOptions(delimiter=self.delimiter),
            convert_options=convert,
        )

    def _sniff_encoded_first_block(self) -> frozenset:
        """Cardinality sniff over ONE streamed block: numeric columns
        whose first-block distinct count passes the encoded-ingest
        density rule (<= 1 dictionary entry per 4 rows, capped at the
        int16 code space) are reported via ``encoded_column_names`` and
        re-encoded per batch in ``batches`` — CSV's analogue of the
        Parquet metadata detection. The rule is re-checked per batch by
        ``_encode_arrow_batch``, so a column that only LOOKED
        low-cardinality in the first block demotes to plain
        mid-stream exactly like a Parquet dictionary overflow."""
        import pyarrow.compute as pc

        from deequ_tpu.data.table import MAX_ENCODED_CARDINALITY

        numeric = [
            f.name
            for f in self._schema
            if f.dtype in (DType.INTEGRAL, DType.FRACTIONAL)
        ]
        if not numeric:
            return frozenset()
        reader = self._open(
            block_rows=1 << 16, pin_schema=self._arrow_schema,
            include=numeric,
        )
        try:
            block = reader.read_next_batch()
        except StopIteration:
            return frozenset()  # header-only file
        finally:
            reader.close()
        cap = min(
            MAX_ENCODED_CARDINALITY, max(block.num_rows // 4, 1)
        )
        out = set()
        for i, name in enumerate(block.schema.names):
            distinct = len(pc.unique(block.column(i).drop_null()))
            if 0 < distinct <= cap:
                out.add(name)
        return frozenset(out)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return None  # CSV has no row-count metadata; Size() measures it

    @property
    def encoded_column_names(self) -> frozenset:
        if self._encoded is None:
            self._encoded = self._sniff_encoded_first_block()
        return self._encoded

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        import pyarrow as pa

        from deequ_tpu.data.io import from_arrow

        encoded = self.encoded_column_names  # sniffs on first use
        keep = (
            [n for n in self._schema.column_names if n in set(columns)]
            if columns is not None
            else None
        )
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(
            self._schema,
            encoded=encoded,
        )
        # pruning happens in the reader: pyarrow skips conversion of
        # excluded columns entirely
        reader = self._open(
            block_rows=rows, pin_schema=self._arrow_schema, include=keep
        )
        # sniffed low-cardinality columns ride the encoded plane: codes
        # + dictionary + validity through from_arrow, mirroring the
        # Parquet path (the density denominator is the full batch size;
        # CSV has no total-row metadata to bound it by)
        enc_active = set(
            encoded if keep is None else encoded & set(keep)
        )
        for record_batch in reader:
            tab = pa.Table.from_batches([record_batch])
            if enc_active:
                tab = _encode_arrow_batch(tab, enc_active, rows)
            yield from_arrow(tab)


class TableBatchSource(BatchSource):
    """Adapter: slice an in-memory ColumnarTable into batches (testing and
    incremental pipelines that already hold batches in memory)."""

    def __init__(self, table: ColumnarTable, batch_rows: Optional[int] = None):
        self.table = table
        self._batch_rows = batch_rows

    @property
    def schema(self) -> Schema:
        return self.table.schema

    @property
    def num_rows(self) -> Optional[int]:
        return self.table.num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        yield from self.batches_from(0, columns=columns, batch_rows=batch_rows)

    def batches_from(
        self,
        start: int = 0,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        # native seek: the table is resident, so start is row arithmetic
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(self.schema)
        names = (
            [n for n in self.table.column_names if n in set(columns)]
            if columns is not None
            else self.table.column_names
        )
        n = self.table.num_rows
        view = self.table.select(names)
        for row0 in range(start * rows, max(n, 1) if start == 0 else n, rows):
            idx = np.arange(row0, min(row0 + rows, n))
            yield ColumnarTable([view[c].take(idx) for c in names])
            if row0 + rows >= n:
                break


class GeneratorBatchSource(BatchSource):
    """Batches from a factory of iterators (synthetic benchmark streams:
    data is generated on the fly, never held in full)."""

    def __init__(self, schema: Schema, factory, num_rows: Optional[int] = None):
        self._schema = schema
        self._factory = factory  # () -> Iterator[ColumnarTable]
        self._num_rows = num_rows

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return self._num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        keep = set(columns) if columns is not None else None
        for batch in self._factory():
            if keep is not None:
                names = [n for n in batch.column_names if n in keep]
                yield batch.select(names)
            else:
                yield batch
