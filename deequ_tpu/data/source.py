"""Out-of-core batch sources — bounded-memory ingestion.

The reference reads TB datasets because Spark streams partitions from
storage instead of materializing tables (the 3-pass profiler is explicitly
designed around that, reference profiles/ColumnProfiler.scala:57-68). The
TPU-native analogue: a ``BatchSource`` yields fixed-size ``ColumnarTable``
batches straight off storage; the scan engine packs each batch into device
chunks with a read-ahead thread so host decode, host->device transfer, and
device compute overlap. Host RSS stays bounded by
O(batch_rows x row_width x read_ahead), independent of dataset size.

``ParquetBatchSource`` streams row batches via
``pyarrow.ParquetFile.iter_batches`` — the schema and row count come from
file metadata, so nothing is read until batches are consumed.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deequ_tpu.data.table import Column, ColumnarTable, DType, Field, Schema

# target host bytes per streamed batch (decoded numpy, before packing)
DEFAULT_BATCH_BYTES = 256 << 20


def batch_rows_for_schema(schema: Schema, target_bytes: int = DEFAULT_BATCH_BYTES) -> int:
    """Rows per batch so a decoded batch is ~target_bytes on host."""
    bytes_per_row = 0
    for f in schema:
        bytes_per_row += 4 if f.dtype == DType.STRING else 9  # value + mask
    bytes_per_row = max(bytes_per_row, 1)
    return int(min(max(target_bytes // bytes_per_row, 1 << 16), 1 << 24))


class BatchSource:
    """Protocol for bounded-memory batch producers."""

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def num_rows(self) -> Optional[int]:
        """Total rows if knowable from metadata, else None."""
        return None

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        raise NotImplementedError


def _arrow_field_dtype(pa_type) -> DType:
    import pyarrow as pa

    if pa.types.is_integer(pa_type):
        return DType.INTEGRAL
    if pa.types.is_floating(pa_type):
        return DType.FRACTIONAL
    if pa.types.is_boolean(pa_type):
        return DType.BOOLEAN
    return DType.STRING


class ParquetBatchSource(BatchSource):
    """Stream one or more Parquet files as ColumnarTable batches.

    Schema and total row count come from file metadata (no data read);
    ``batches`` decodes ``pyarrow.ParquetFile.iter_batches`` output one
    batch at a time — the whole file is never materialized.
    """

    def __init__(
        self,
        paths,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
        pre_buffer: bool = False,
    ):
        import pyarrow.parquet as pq

        # pre_buffer=True (pyarrow's default) reads ALL row groups ahead —
        # O(file) host memory, exactly what out-of-core must avoid; False
        # streams row groups on demand (measured: 1GB file iterates at
        # 0.35GB RSS vs 1.44GB pre-buffered). Set True only for
        # high-latency object stores where random reads dominate.
        self.pre_buffer = pre_buffer
        self.paths: List[str] = [paths] if isinstance(paths, str) else list(paths)
        if not self.paths:
            raise ValueError("ParquetBatchSource needs at least one path")
        self._restrict = list(columns) if columns is not None else None
        self._batch_rows = batch_rows
        # metadata-only pass: schema + row count without reading data pages
        first = pq.ParquetFile(self.paths[0])
        arrow_schema = first.schema_arrow
        names = (
            self._restrict
            if self._restrict is not None
            else list(arrow_schema.names)
        )
        fields = []
        for name in names:
            idx = arrow_schema.get_field_index(name)
            if idx < 0:
                raise ValueError(f"column {name!r} not in parquet schema")
            fields.append(Field(name, _arrow_field_dtype(arrow_schema.field(idx).type)))
        self._schema = Schema(fields)
        n = first.metadata.num_rows
        for path in self.paths[1:]:
            n += pq.ParquetFile(path).metadata.num_rows
        self._num_rows = int(n)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return self._num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.data.io import from_arrow

        names = list(columns) if columns is not None else self._schema.column_names
        names = [n for n in self._schema.column_names if n in set(names)]
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(
            Schema([self._schema[n] for n in names])
        )
        for path in self.paths:
            pf = pq.ParquetFile(path, pre_buffer=self.pre_buffer)
            for record_batch in pf.iter_batches(batch_size=rows, columns=names):
                yield from_arrow(pa.Table.from_batches([record_batch]))


class TableBatchSource(BatchSource):
    """Adapter: slice an in-memory ColumnarTable into batches (testing and
    incremental pipelines that already hold batches in memory)."""

    def __init__(self, table: ColumnarTable, batch_rows: Optional[int] = None):
        self.table = table
        self._batch_rows = batch_rows

    @property
    def schema(self) -> Schema:
        return self.table.schema

    @property
    def num_rows(self) -> Optional[int]:
        return self.table.num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(self.schema)
        names = (
            [n for n in self.table.column_names if n in set(columns)]
            if columns is not None
            else self.table.column_names
        )
        n = self.table.num_rows
        view = self.table.select(names)
        for start in range(0, max(n, 1), rows):
            idx = np.arange(start, min(start + rows, n))
            yield ColumnarTable([view[c].take(idx) for c in names])
            if start + rows >= n:
                break


class GeneratorBatchSource(BatchSource):
    """Batches from a factory of iterators (synthetic benchmark streams:
    data is generated on the fly, never held in full)."""

    def __init__(self, schema: Schema, factory, num_rows: Optional[int] = None):
        self._schema = schema
        self._factory = factory  # () -> Iterator[ColumnarTable]
        self._num_rows = num_rows

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return self._num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        keep = set(columns) if columns is not None else None
        for batch in self._factory():
            if keep is not None:
                names = [n for n in batch.column_names if n in keep]
                yield batch.select(names)
            else:
                yield batch
