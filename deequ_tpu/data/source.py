"""Out-of-core batch sources — bounded-memory ingestion.

The reference reads TB datasets because Spark streams partitions from
storage instead of materializing tables (the 3-pass profiler is explicitly
designed around that, reference profiles/ColumnProfiler.scala:57-68). The
TPU-native analogue: a ``BatchSource`` yields fixed-size ``ColumnarTable``
batches straight off storage; the scan engine packs each batch into device
chunks with a read-ahead thread so host decode, host->device transfer, and
device compute overlap. Host RSS stays bounded by
O(batch_rows x row_width x read_ahead), independent of dataset size.

``ParquetBatchSource`` streams row batches via
``pyarrow.ParquetFile.iter_batches`` — the schema and row count come from
file metadata, so nothing is read until batches are consumed.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from deequ_tpu.data.table import Column, ColumnarTable, DType, Field, Schema

# target host bytes per streamed batch (decoded numpy, before packing)
DEFAULT_BATCH_BYTES = 256 << 20


def batch_rows_for_schema(schema: Schema, target_bytes: int = DEFAULT_BATCH_BYTES) -> int:
    """Rows per batch so a decoded batch is ~target_bytes on host."""
    bytes_per_row = 0
    for f in schema:
        bytes_per_row += 4 if f.dtype == DType.STRING else 9  # value + mask
    bytes_per_row = max(bytes_per_row, 1)
    return int(min(max(target_bytes // bytes_per_row, 1 << 16), 1 << 24))


class BatchSource:
    """Protocol for bounded-memory batch producers."""

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def num_rows(self) -> Optional[int]:
        """Total rows if knowable from metadata, else None."""
        return None

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        raise NotImplementedError

    def batches_from(
        self,
        start: int = 0,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        """Batches from batch index ``start`` — the seek primitive the
        resilience layer's retry-reopen and checkpoint-resume paths use
        (deequ_tpu/resilience). Batch boundaries are deterministic for a
        fixed ``batch_rows``, so index k names the same rows every call.

        Default: re-iterate and drop the first ``start`` batches (skipped
        batches are re-decoded but not processed). Sources with native
        seeks override this."""
        import itertools

        return itertools.islice(
            self.batches(columns=columns, batch_rows=batch_rows), start, None
        )

    def with_retry(self, policy=None):
        """This source wrapped so every batch read runs under a
        RetryPolicy (resilience/retry.py: reopen-and-fast-forward on
        transient errors)."""
        from deequ_tpu.resilience.retry import RetryingBatchSource

        return RetryingBatchSource(self, policy)


def _restrict_arrow_schema(arrow_schema, names, what: str):
    """Map requested column names onto an arrow schema -> engine Fields."""
    fields = []
    for name in names:
        idx = arrow_schema.get_field_index(name)
        if idx < 0:
            raise ValueError(f"column {name!r} not in {what}")
        fields.append(Field(name, _arrow_field_dtype(arrow_schema.field(idx).type)))
    return fields


def _arrow_field_dtype(pa_type) -> DType:
    import pyarrow as pa

    if pa.types.is_integer(pa_type):
        return DType.INTEGRAL
    if pa.types.is_floating(pa_type):
        return DType.FRACTIONAL
    if pa.types.is_boolean(pa_type):
        return DType.BOOLEAN
    return DType.STRING


class ParquetBatchSource(BatchSource):
    """Stream one or more Parquet files as ColumnarTable batches.

    Schema and total row count come from file metadata (no data read);
    ``batches`` decodes ``pyarrow.ParquetFile.iter_batches`` output one
    batch at a time — the whole file is never materialized.
    """

    def __init__(
        self,
        paths,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
        pre_buffer: bool = False,
    ):
        import pyarrow.parquet as pq

        # pre_buffer=True (pyarrow's default) reads ALL row groups ahead —
        # O(file) host memory, exactly what out-of-core must avoid; False
        # streams row groups on demand (measured: 1GB file iterates at
        # 0.35GB RSS vs 1.44GB pre-buffered). Set True only for
        # high-latency object stores where random reads dominate.
        self.pre_buffer = pre_buffer
        self.paths: List[str] = [paths] if isinstance(paths, str) else list(paths)
        if not self.paths:
            raise ValueError("ParquetBatchSource needs at least one path")
        self._restrict = list(columns) if columns is not None else None
        self._batch_rows = batch_rows
        # metadata-only pass: schema + row count without reading data pages
        first = pq.ParquetFile(self.paths[0])
        arrow_schema = first.schema_arrow
        names = (
            self._restrict
            if self._restrict is not None
            else list(arrow_schema.names)
        )
        self._schema = Schema(
            _restrict_arrow_schema(arrow_schema, names, "parquet schema")
        )
        n = first.metadata.num_rows
        for path in self.paths[1:]:
            pf = pq.ParquetFile(path)
            # compare only the SELECTED fields, by name: batches() reads
            # columns by name per file, so extra/reordered unselected
            # columns in a later file are fine — a selected column that
            # is missing or type-changed is not
            other = pf.schema_arrow
            for name in names:
                idx = other.get_field_index(name)
                # compare the MAPPED engine dtype, not exact arrow types:
                # width-compatible files (int32 vs int64, float32 vs
                # float64) decode to the same Column dtype per batch and
                # stream fine; only genuine kind conflicts (int vs string)
                # should fail fast
                if idx < 0 or _arrow_field_dtype(
                    other.field(idx).type
                ) != _arrow_field_dtype(arrow_schema.field(name).type):
                    raise ValueError(
                        f"parquet schema mismatch: column {name!r} in "
                        f"{path!r} is "
                        f"{other.field(idx).type if idx >= 0 else 'missing'},"
                        f" expected {arrow_schema.field(name).type} "
                        f"(from {self.paths[0]!r})"
                    )
            n += pf.metadata.num_rows
        self._num_rows = int(n)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return self._num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from deequ_tpu.data.io import from_arrow

        names = list(columns) if columns is not None else self._schema.column_names
        names = [n for n in self._schema.column_names if n in set(names)]
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(
            Schema([self._schema[n] for n in names])
        )
        for path in self.paths:
            pf = pq.ParquetFile(path, pre_buffer=self.pre_buffer)
            for record_batch in pf.iter_batches(batch_size=rows, columns=names):
                yield from_arrow(pa.Table.from_batches([record_batch]))


def _bool_literals() -> frozenset:
    """Lowered bool literal set, derived from read_csv's _TRUE/_FALSE so
    the two CSV frontends cannot drift apart."""
    from deequ_tpu.data.io import _FALSE, _TRUE

    return frozenset(s.lower() for s in (_TRUE | _FALSE))


def _classify_string_values(col):
    """Capability flags (can_int, can_float, can_bool) for one block's
    non-null string values. Capabilities AND across blocks and the final
    type applies read_csv's precedence (int > float > bool > string), so
    block-local classification can never disagree with a whole-file pass
    — e.g. '0'/'1' rows in one block and 'true' in another still join to
    BOOLEAN, exactly as read_csv infers over the full column."""
    import pyarrow as pa
    import pyarrow.compute as pc

    def can_cast(t):
        try:
            pc.cast(col, t)
            return True
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            return False

    can_int = can_cast(pa.int64())
    can_float = can_int or can_cast(pa.float64())
    lowered = set(pc.utf8_lower(col).unique().to_pylist())
    can_bool = lowered <= _bool_literals()
    return can_int, can_float, can_bool


class CSVBatchSource(BatchSource):
    """Stream a CSV file as ColumnarTable batches via pyarrow's streaming
    CSV reader (C++ incremental parser; the file is never materialized).

    Null semantics match ``read_csv``: ONLY the empty cell is null
    (strings stay strings — 'NA'/'nan' literals are data, not nulls).

    Schema: ``column_types`` pins dtypes directly (the bounded-memory
    path for huge files); otherwise one streaming schema pass infers each
    column's widened type over the WHOLE file (int64 -> float64 ->
    string), so a value late in the file can never crash the analysis
    the way a sampled-prefix schema would."""

    def __init__(
        self,
        path: str,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
        delimiter: str = ",",
        column_types: Optional[Dict[str, DType]] = None,
    ):
        import pyarrow as pa

        self.path = path
        self.delimiter = delimiter
        self._restrict = list(columns) if columns is not None else None
        self._batch_rows = batch_rows
        if column_types is not None:
            arrow_of = {
                DType.INTEGRAL: pa.int64(),
                DType.FRACTIONAL: pa.float64(),
                DType.BOOLEAN: pa.bool_(),
                DType.STRING: pa.string(),
            }
            header = self._open(block_rows=1 << 12).schema
            pinned = {
                name: arrow_of[column_types.get(name, DType.STRING)]
                for name in header.names
            }
            arrow_schema = pa.schema(
                [pa.field(n, pinned[n]) for n in header.names]
            )
        else:
            arrow_schema = self._infer_schema_streaming()
        names = (
            self._restrict
            if self._restrict is not None
            else list(arrow_schema.names)
        )
        self._schema = Schema(
            _restrict_arrow_schema(arrow_schema, names, "CSV header")
        )
        self._arrow_schema = arrow_schema

    def _infer_schema_streaming(self):
        """One streaming pass over the file, widening each column's type
        across blocks (bounded memory; reads the file once for schema).

        Every column is READ as string and classified on host: pyarrow's
        open_csv pins each column's type from its first block, so letting
        it infer would raise ArrowInvalid on a type-widening value in any
        later block (e.g. a '3.5' past the first ~4MB of an int column) —
        the exact failure this pass exists to prevent."""
        import pyarrow as pa

        header = self._open(block_rows=1 << 12).schema
        all_string = pa.schema(
            [pa.field(n, pa.string()) for n in header.names]
        )
        caps = {}  # name -> (can_int, can_float, can_bool), AND across blocks
        for record_batch in self._open(
            block_rows=1 << 16, pin_schema=all_string
        ):
            for i, field in enumerate(record_batch.schema):
                name = field.name
                if caps.get(name) == (False, False, False):
                    continue  # already string; cannot widen further
                col = record_batch.column(i).drop_null()
                if len(col) == 0:
                    continue  # all-null block: no information
                c = _classify_string_values(col)
                prev = caps.get(name)
                caps[name] = c if prev is None else tuple(
                    a and b for a, b in zip(prev, c)
                )
        out = []
        for name in header.names:
            c = caps.get(name)
            if c is None:
                t = pa.string()  # all-null column
            else:
                can_int, can_float, can_bool = c
                # read_csv precedence: int > float > bool > string
                if can_int:
                    t = pa.int64()
                elif can_float:
                    t = pa.float64()
                elif can_bool:
                    t = pa.bool_()
                else:
                    t = pa.string()
            out.append(pa.field(name, t))
        return pa.schema(out)

    def _open(self, block_rows: int, pin_schema=None, include=None):
        import pyarrow.csv as pacsv

        # block size in bytes; estimate ~64 bytes/row as a coarse default
        block_bytes = max(block_rows * 64, 1 << 16)
        convert = pacsv.ConvertOptions(
            column_types=dict(zip(pin_schema.names, pin_schema.types))
            if pin_schema is not None
            else None,
            include_columns=include if include is not None else self._restrict,
            # read_csv parity: ONLY the empty cell is null, and it is null
            # in string columns too
            null_values=[""],
            strings_can_be_null=True,
        )
        return pacsv.open_csv(
            self.path,
            read_options=pacsv.ReadOptions(block_size=block_bytes),
            parse_options=pacsv.ParseOptions(delimiter=self.delimiter),
            convert_options=convert,
        )

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return None  # CSV has no row-count metadata; Size() measures it

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        import pyarrow as pa

        from deequ_tpu.data.io import from_arrow

        keep = (
            [n for n in self._schema.column_names if n in set(columns)]
            if columns is not None
            else None
        )
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(self._schema)
        # pruning happens in the reader: pyarrow skips conversion of
        # excluded columns entirely
        reader = self._open(
            block_rows=rows, pin_schema=self._arrow_schema, include=keep
        )
        for record_batch in reader:
            yield from_arrow(pa.Table.from_batches([record_batch]))


class TableBatchSource(BatchSource):
    """Adapter: slice an in-memory ColumnarTable into batches (testing and
    incremental pipelines that already hold batches in memory)."""

    def __init__(self, table: ColumnarTable, batch_rows: Optional[int] = None):
        self.table = table
        self._batch_rows = batch_rows

    @property
    def schema(self) -> Schema:
        return self.table.schema

    @property
    def num_rows(self) -> Optional[int]:
        return self.table.num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        yield from self.batches_from(0, columns=columns, batch_rows=batch_rows)

    def batches_from(
        self,
        start: int = 0,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        # native seek: the table is resident, so start is row arithmetic
        rows = batch_rows or self._batch_rows or batch_rows_for_schema(self.schema)
        names = (
            [n for n in self.table.column_names if n in set(columns)]
            if columns is not None
            else self.table.column_names
        )
        n = self.table.num_rows
        view = self.table.select(names)
        for row0 in range(start * rows, max(n, 1) if start == 0 else n, rows):
            idx = np.arange(row0, min(row0 + rows, n))
            yield ColumnarTable([view[c].take(idx) for c in names])
            if row0 + rows >= n:
                break


class GeneratorBatchSource(BatchSource):
    """Batches from a factory of iterators (synthetic benchmark streams:
    data is generated on the fly, never held in full)."""

    def __init__(self, schema: Schema, factory, num_rows: Optional[int] = None):
        self._schema = schema
        self._factory = factory  # () -> Iterator[ColumnarTable]
        self._num_rows = num_rows

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def num_rows(self) -> Optional[int]:
        return self._num_rows

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        keep = set(columns) if columns is not None else None
        for batch in self._factory():
            if keep is not None:
                names = [n for n in batch.column_names if n in keep]
                yield batch.select(names)
            else:
                yield batch
