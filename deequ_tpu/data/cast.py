"""Column casts shared by the profiler and the streaming layer."""

from __future__ import annotations

import numpy as np

from deequ_tpu.data.table import Column, DType


def cast_string_column(col: Column, target: DType) -> Column:
    """Cast a dictionary-encoded string column to numeric; unparsable values
    become null (the analogue of ColumnProfiler.castColumn, reference
    profiles/ColumnProfiler.scala:346-355). O(cardinality) host work: the
    parse runs once per distinct value, the cast is a gather."""
    if col.dtype != DType.STRING:
        raise TypeError(f"column {col.name} is not a string column")
    if target not in (DType.INTEGRAL, DType.FRACTIONAL):
        raise ValueError(f"cannot cast strings to {target}")
    card = max(len(col.dictionary), 1)
    lut = np.zeros(card, dtype=np.float64)
    ok = np.zeros(card, dtype=np.bool_)
    for i, v in enumerate(col.dictionary):
        try:
            lut[i] = float(v)
            ok[i] = True
        except (TypeError, ValueError):
            pass
    safe = np.maximum(col.codes, 0)
    values = lut[safe]
    mask = (col.codes >= 0) & ok[safe]
    if target == DType.INTEGRAL:
        return Column(col.name, DType.INTEGRAL,
                      values=values.astype(np.int64), mask=mask)
    return Column(col.name, DType.FRACTIONAL, values=values, mask=mask)
