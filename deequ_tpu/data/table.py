"""Columnar in-memory table — the engine's data substrate.

The reference operates on Spark DataFrames (row iterators + Catalyst
expressions). The TPU-native design is columnar: each column is a contiguous
numpy array plus a validity mask; strings are dictionary-encoded (int32 codes
into a host-side array of distinct values) so that all device work happens on
fixed-width numeric arrays, and per-distinct-value host work (regex, length)
is O(cardinality) instead of O(rows).

This mirrors the plan in SURVEY.md §7.1 ("columnar batches instead of row
iterators; strings dictionary-/byte-encoded for device processing").
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np


class DType(enum.Enum):
    FRACTIONAL = "fractional"  # float64
    INTEGRAL = "integral"      # int64
    BOOLEAN = "boolean"
    STRING = "string"

    @property
    def is_numeric(self) -> bool:
        return self in (DType.FRACTIONAL, DType.INTEGRAL)


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True


class Schema:
    def __init__(self, fields: Sequence[Field]):
        self.fields = list(fields)
        self._by_name = {f.name: f for f in self.fields}

    def has_column(self, name: str) -> bool:
        return name in self._by_name

    def __getitem__(self, name: str) -> Field:
        return self._by_name[name]

    def __iter__(self):
        return iter(self.fields)

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}: {f.dtype.value}" for f in self.fields)
        return f"Schema({inner})"


class Column:
    """One column: numeric/bool columns hold ``values`` + ``mask`` (True =
    valid); string columns hold int32 ``codes`` (-1 = null) + ``dictionary``
    of distinct values."""

    def __init__(
        self,
        name: str,
        dtype: DType,
        values: Optional[np.ndarray] = None,
        mask: Optional[np.ndarray] = None,
        codes: Optional[np.ndarray] = None,
        dictionary: Optional[np.ndarray] = None,
    ):
        self.name = name
        self.dtype = dtype
        if dtype == DType.STRING:
            assert codes is not None and dictionary is not None
            self.codes = np.asarray(codes, dtype=np.int32)
            self.dictionary = np.asarray(dictionary, dtype=object)
            self.values = None
            self.mask = self.codes >= 0
        else:
            assert values is not None
            np_dtype = {
                DType.FRACTIONAL: np.float64,
                DType.INTEGRAL: np.int64,
                DType.BOOLEAN: np.bool_,
            }[dtype]
            self.values = np.asarray(values, dtype=np_dtype)
            self.mask = (
                np.ones(len(self.values), dtype=np.bool_)
                if mask is None
                else np.asarray(mask, dtype=np.bool_)
            )
            self.codes = None
            self.dictionary = None

    def __len__(self) -> int:
        return len(self.codes) if self.dtype == DType.STRING else len(self.values)

    @property
    def num_valid(self) -> int:
        return int(self.mask.sum())

    def numeric_values(self) -> np.ndarray:
        """Values as float64 with nulls zeroed (pair with .mask)."""
        if self.dtype == DType.STRING:
            raise TypeError(f"column {self.name} is not numeric")
        vals = self.values.astype(np.float64)
        return np.where(self.mask, vals, 0.0)

    def to_pylist(self) -> list:
        """Decode to a Python list with None for nulls (test/debug helper)."""
        if self.dtype == DType.STRING:
            return [
                self.dictionary[c] if c >= 0 else None for c in self.codes.tolist()
            ]
        out = []
        for v, m in zip(self.values.tolist(), self.mask.tolist()):
            out.append(v if m else None)
        return out

    def take(self, indices: np.ndarray) -> "Column":
        if self.dtype == DType.STRING:
            return Column(
                self.name, self.dtype, codes=self.codes[indices],
                dictionary=self.dictionary,
            )
        return Column(
            self.name, self.dtype, values=self.values[indices], mask=self.mask[indices]
        )


def _infer_and_build(name: str, raw: Iterable) -> Column:
    """Build a Column from a Python sequence, inferring the dtype."""
    items = list(raw)
    non_null = [x for x in items if x is not None]
    if all(isinstance(x, bool) for x in non_null) and non_null:
        values = np.array([bool(x) if x is not None else False for x in items])
        mask = np.array([x is not None for x in items])
        return Column(name, DType.BOOLEAN, values=values, mask=mask)
    if all(isinstance(x, int) and not isinstance(x, bool) for x in non_null) and non_null:
        values = np.array([int(x) if x is not None else 0 for x in items], dtype=np.int64)
        mask = np.array([x is not None for x in items])
        return Column(name, DType.INTEGRAL, values=values, mask=mask)
    if all(isinstance(x, (int, float)) and not isinstance(x, bool) for x in non_null) and non_null:
        values = np.array(
            [float(x) if x is not None else 0.0 for x in items], dtype=np.float64
        )
        mask = np.array([x is not None for x in items])
        return Column(name, DType.FRACTIONAL, values=values, mask=mask)
    # everything else (incl. all-null) is a string column
    return _string_column(name, [None if x is None else str(x) for x in items])


def _string_column(name: str, items: Sequence[Optional[str]]) -> Column:
    strings = np.array([x if x is not None else "" for x in items], dtype=object)
    is_null = np.array([x is None for x in items], dtype=np.bool_)
    if len(items) == 0:
        return Column(name, DType.STRING, codes=np.array([], dtype=np.int32),
                      dictionary=np.array([], dtype=object))
    dictionary, codes = np.unique(strings.astype(str), return_inverse=True)
    codes = codes.astype(np.int32)
    codes[is_null] = -1
    return Column(name, DType.STRING, codes=codes, dictionary=dictionary.astype(object))


class ColumnarTable:
    """An immutable columnar table. The unit the analysis engine consumes."""

    def __init__(self, columns: Sequence[Column]):
        self.columns: Dict[str, Column] = {c.name: c for c in columns}
        lengths = {len(c) for c in columns}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self.num_rows = lengths.pop() if lengths else 0
        self._device_cache = None  # set by persist()

    # -- device residency (the analogue of Spark df.persist()) --------------

    def persist(self, mesh=None) -> "ColumnarTable":
        """Pack + transfer all columns to device HBM once; subsequent scans
        stream from HBM instead of re-shipping host bytes. Multi-pass
        workloads (profiler, repeated verification) become compute-bound."""
        from deequ_tpu.ops.scan_engine import persist_table

        persist_table(self, mesh=mesh)
        return self

    def unpersist(self) -> "ColumnarTable":
        """Release the device-resident buffers (eagerly: the buffers are
        dropped and the cache's HBM-budget accounting zeroed now, not at
        the next GC cycle of whoever else holds the cache object)."""
        from deequ_tpu.ops.scan_engine import _evict_device_cache

        _evict_device_cache(self)
        return self

    @property
    def is_persisted(self) -> bool:
        return self._device_cache is not None

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_pydict(data: Mapping[str, Iterable]) -> "ColumnarTable":
        return ColumnarTable([_infer_and_build(k, v) for k, v in data.items()])

    @staticmethod
    def from_rows(
        rows: Sequence[Sequence], column_names: Sequence[str]
    ) -> "ColumnarTable":
        cols = {name: [] for name in column_names}
        for row in rows:
            for name, v in zip(column_names, row):
                cols[name].append(v)
        return ColumnarTable.from_pydict(cols)

    @staticmethod
    def from_columns(columns: Sequence[Column]) -> "ColumnarTable":
        return ColumnarTable(columns)

    # -- schema / access ----------------------------------------------------

    @property
    def schema(self) -> Schema:
        return Schema([Field(c.name, c.dtype) for c in self.columns.values()])

    @property
    def column_names(self) -> List[str]:
        return list(self.columns.keys())

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def __len__(self) -> int:
        return self.num_rows

    def select(self, names: Sequence[str]) -> "ColumnarTable":
        return ColumnarTable([self.columns[n] for n in names])

    def filter_rows(self, keep: np.ndarray) -> "ColumnarTable":
        idx = np.nonzero(np.asarray(keep, dtype=bool))[0]
        return ColumnarTable([c.take(idx) for c in self.columns.values()])

    def with_column(self, column: Column) -> "ColumnarTable":
        cols = [c for c in self.columns.values() if c.name != column.name]
        cols.append(column)
        return ColumnarTable(cols)

    def head(self, n: int) -> "ColumnarTable":
        idx = np.arange(min(n, self.num_rows))
        return ColumnarTable([c.take(idx) for c in self.columns.values()])

    def concat(self, other: "ColumnarTable") -> "ColumnarTable":
        """Row-wise union (used by incremental-vs-batch equivalence tests)."""
        if set(self.column_names) != set(other.column_names):
            raise ValueError("schema mismatch in concat")
        cols = []
        for name in self.column_names:
            a, b = self.columns[name], other.columns[name]
            if a.dtype != b.dtype:
                raise ValueError(f"dtype mismatch for {name}")
            if a.dtype == DType.STRING:
                merged = list(a.to_pylist()) + list(b.to_pylist())
                cols.append(_string_column(name, merged))
            else:
                cols.append(
                    Column(
                        name,
                        a.dtype,
                        values=np.concatenate([a.values, b.values]),
                        mask=np.concatenate([a.mask, b.mask]),
                    )
                )
        return ColumnarTable(cols)

    def random_split(
        self, fractions: Tuple[float, float], seed: int = 0
    ) -> Tuple["ColumnarTable", "ColumnarTable"]:
        rng = np.random.default_rng(seed)
        u = rng.random(self.num_rows)
        cut = fractions[0] / (fractions[0] + fractions[1])
        return self.filter_rows(u < cut), self.filter_rows(u >= cut)

    def __repr__(self) -> str:
        return f"ColumnarTable({self.num_rows} rows, {self.schema})"
