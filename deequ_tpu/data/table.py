"""Columnar in-memory table — the engine's data substrate.

The reference operates on Spark DataFrames (row iterators + Catalyst
expressions). The TPU-native design is columnar: each column is a contiguous
numpy array plus a validity mask; strings are dictionary-encoded (int32 codes
into a host-side array of distinct values) so that all device work happens on
fixed-width numeric arrays, and per-distinct-value host work (regex, length)
is O(cardinality) instead of O(rows).

This mirrors the plan in SURVEY.md §7.1 ("columnar batches instead of row
iterators; strings dictionary-/byte-encoded for device processing").
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

#: widest dictionary an encoded column chunk may carry: codes are int16
#: (null = -1), so the dictionary must index in [0, 2^15). Past this the
#: encoded form stops paying for itself anyway — codes approach the width
#: of the values.
MAX_ENCODED_CARDINALITY = (1 << 15) - 1


class DType(enum.Enum):
    FRACTIONAL = "fractional"  # float64
    INTEGRAL = "integral"      # int64
    BOOLEAN = "boolean"
    STRING = "string"

    @property
    def is_numeric(self) -> bool:
        return self in (DType.FRACTIONAL, DType.INTEGRAL)


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True


class Schema:
    def __init__(self, fields: Sequence[Field]):
        self.fields = list(fields)
        self._by_name = {f.name: f for f in self.fields}

    def has_column(self, name: str) -> bool:
        return name in self._by_name

    def __getitem__(self, name: str) -> Field:
        return self._by_name[name]

    def __iter__(self):
        return iter(self.fields)

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}: {f.dtype.value}" for f in self.fields)
        return f"Schema({inner})"


@dataclass
class ColumnChunk:
    """The dictionary-encoded column-chunk payload — the Arrow/Parquet-
    native form a ``Column`` can carry INSTEAD of decoded full-width
    values (ROADMAP item 3: encoded device residency).

    - ``codes``: int16 indices into ``dictionary``; -1 marks a null row
      (normalized at construction — every invalid row's code is -1, so
      device programs recover validity as ``codes >= 0`` without a
      separate mask transfer);
    - ``dictionary``: the decoded distinct values (float64 / int64),
      at most :data:`MAX_ENCODED_CARDINALITY` entries;
    - ``validity``: the packed null bitmap (``np.packbits``; 1 bit/row,
      8x smaller than a bool mask), or None when every row is valid.

    At 2 bytes/row vs the decoded planes' 8-9 (f32 pair + mask) /
    4-5 (i32 + mask) bytes, the encoded form is the 2-8x smaller payload
    both HBM residency and host->device staging carry; decode (a
    dictionary gather) fuses into the scan program (``docs/ingest.md``).
    """

    codes: np.ndarray
    dictionary: np.ndarray
    validity: Optional[np.ndarray]
    num_rows: int

    @property
    def cardinality(self) -> int:
        return len(self.dictionary)

    @property
    def nbytes(self) -> int:
        return (
            self.codes.nbytes
            + self.dictionary.nbytes
            + (self.validity.nbytes if self.validity is not None else 0)
        )

    def mask(self) -> np.ndarray:
        """The validity bitmap unpacked to a bool row mask."""
        if self.validity is None:
            return np.ones(self.num_rows, dtype=np.bool_)
        return np.unpackbits(self.validity, count=self.num_rows).astype(
            np.bool_
        )

    def decode(self, np_dtype) -> Tuple[np.ndarray, np.ndarray]:
        """Materialize (values, mask): a host dictionary gather with
        invalid rows zeroed — exactly the full-width form the decoded
        ingest path would have produced."""
        mask = self.mask()
        safe = np.where(mask, self.codes, 0).astype(np.int64)
        if len(self.dictionary) == 0:
            values = np.zeros(self.num_rows, dtype=np_dtype)
        else:
            values = self.dictionary[safe].astype(np_dtype)
            values = np.where(mask, values, values.dtype.type(0))
        return values, mask

    def take(self, indices: np.ndarray) -> "ColumnChunk":
        codes = self.codes[indices]
        valid = codes >= 0
        return ColumnChunk(
            codes=codes,
            dictionary=self.dictionary,
            validity=None if bool(valid.all()) else np.packbits(valid),
            num_rows=len(codes),
        )

    @staticmethod
    def from_codes(
        codes: np.ndarray,
        dictionary: np.ndarray,
        mask: Optional[np.ndarray] = None,
    ) -> "ColumnChunk":
        """Build from raw (possibly wider) codes + dictionary (the Arrow
        DictionaryArray shape). Rows that are masked invalid OR carry a
        negative/NaN-dictionary code are normalized to code -1."""
        codes = np.asarray(codes)
        valid = codes >= 0
        if mask is not None:
            valid = valid & np.asarray(mask, dtype=np.bool_)
        dictionary = np.asarray(dictionary)
        if np.issubdtype(dictionary.dtype, np.floating):
            # engine convention (data/io.py): NaN == null. A NaN
            # dictionary entry makes every row pointing at it null.
            nan_slots = np.isnan(dictionary)
            if nan_slots.any():
                safe = np.where(valid, codes, 0)
                valid = valid & ~nan_slots[safe]
                dictionary = np.where(nan_slots, 0.0, dictionary)
        out = np.where(valid, codes, -1).astype(np.int16)
        return ColumnChunk(
            codes=out,
            dictionary=dictionary,
            validity=None if bool(valid.all()) else np.packbits(valid),
            num_rows=len(out),
        )

    @staticmethod
    def from_values(
        values: np.ndarray,
        mask: np.ndarray,
        max_cardinality: int = MAX_ENCODED_CARDINALITY,
    ) -> Optional["ColumnChunk"]:
        """Dictionary-encode decoded values, or None when the column is
        not worth encoding (cardinality above ``max_cardinality`` — the
        all-unique fallback). VALID NaNs (mask True) share one NaN
        dictionary entry and stay valid: NaN==null folding is an ingest
        convention (data/io.py), not an encoding one — an in-memory
        column that deliberately carries NaN values round-trips."""
        valid = np.asarray(mask, dtype=np.bool_)
        vals = np.asarray(values)[valid]
        is_float = np.issubdtype(vals.dtype, np.floating)
        nan_rows = np.isnan(vals) if is_float else np.zeros(len(vals), bool)
        finite = vals[~nan_rows]
        dictionary = np.unique(finite)
        has_nan = bool(nan_rows.any())
        if len(dictionary) + has_nan > max_cardinality:
            return None
        codes16 = np.full(len(valid), -1, dtype=np.int16)
        pos = np.searchsorted(dictionary, finite)
        inner = np.empty(len(vals), dtype=np.int64)
        inner[~nan_rows] = pos
        if has_nan:
            dictionary = np.concatenate([dictionary, [np.nan]])
            inner[nan_rows] = len(dictionary) - 1
        codes16[valid] = inner.astype(np.int16)
        return ColumnChunk(
            codes=codes16,
            dictionary=dictionary,
            validity=None if bool(valid.all()) else np.packbits(valid),
            num_rows=len(valid),
        )


class Column:
    """One column: numeric/bool columns hold ``values`` + ``mask`` (True =
    valid); string columns hold int32 ``codes`` (-1 = null) + ``dictionary``
    of distinct values.

    Numeric columns may instead carry a dictionary-``encoded``
    :class:`ColumnChunk` payload (Arrow/Parquet-native ingest,
    ``Column.encode()``): ``values``/``mask`` then materialize LAZILY on
    first host access, while the scan engine's encoded ingest path reads
    the codes + dictionary directly and never decodes on host."""

    def __init__(
        self,
        name: str,
        dtype: DType,
        values: Optional[np.ndarray] = None,
        mask: Optional[np.ndarray] = None,
        codes: Optional[np.ndarray] = None,
        dictionary: Optional[np.ndarray] = None,
        encoded: Optional[ColumnChunk] = None,
    ):
        self.name = name
        self.dtype = dtype
        self.encoding: Optional[ColumnChunk] = None
        if dtype == DType.STRING:
            assert codes is not None and dictionary is not None
            self.codes = np.asarray(codes, dtype=np.int32)
            self.dictionary = np.asarray(dictionary, dtype=object)
            self._values = None
            self._mask = self.codes >= 0
        elif encoded is not None:
            assert values is None and mask is None
            self.encoding = encoded
            self._values = None
            self._mask = None
            self.codes = None
            self.dictionary = None
        else:
            assert values is not None
            np_dtype = {
                DType.FRACTIONAL: np.float64,
                DType.INTEGRAL: np.int64,
                DType.BOOLEAN: np.bool_,
            }[dtype]
            self._values = np.asarray(values, dtype=np_dtype)
            self._mask = (
                np.ones(len(self._values), dtype=np.bool_)
                if mask is None
                else np.asarray(mask, dtype=np.bool_)
            )
            self.codes = None
            self.dictionary = None

    @property
    def _np_dtype(self):
        return {
            DType.FRACTIONAL: np.float64,
            DType.INTEGRAL: np.int64,
            DType.BOOLEAN: np.bool_,
        }[self.dtype]

    @property
    def values(self) -> Optional[np.ndarray]:
        if self._values is None and self.encoding is not None:
            self._values, self._mask = self.encoding.decode(self._np_dtype)
        return self._values

    @property
    def mask(self) -> np.ndarray:
        if self._mask is None and self.encoding is not None:
            # mask alone never forces a value decode: the packed validity
            # bitmap (or the -1 codes) carries it
            self._mask = self.encoding.mask()
        return self._mask

    def encode(
        self, max_cardinality: int = MAX_ENCODED_CARDINALITY
    ) -> bool:
        """Attach a dictionary encoding built from the decoded values
        (in-memory tables opting into the encoded ingest path). Returns
        True when the column now carries one; False for non-encodable
        columns (string/boolean, or cardinality past the int16 cap — the
        all-unique fallback stays on the decoded path)."""
        if self.encoding is not None:
            return True
        if self.dtype not in (DType.FRACTIONAL, DType.INTEGRAL):
            return False
        enc = ColumnChunk.from_values(
            self._values, self._mask, max_cardinality
        )
        if enc is None:
            return False
        self.encoding = enc
        return True

    def __len__(self) -> int:
        if self.dtype == DType.STRING:
            return len(self.codes)
        if self._values is None and self.encoding is not None:
            return self.encoding.num_rows
        return len(self._values)

    @property
    def num_valid(self) -> int:
        return int(self.mask.sum())

    def numeric_values(self) -> np.ndarray:
        """Values as float64 with nulls zeroed (pair with .mask)."""
        if self.dtype == DType.STRING:
            raise TypeError(f"column {self.name} is not numeric")
        vals = self.values.astype(np.float64)
        return np.where(self.mask, vals, 0.0)

    def to_pylist(self) -> list:
        """Decode to a Python list with None for nulls (test/debug helper)."""
        if self.dtype == DType.STRING:
            return [
                self.dictionary[c] if c >= 0 else None for c in self.codes.tolist()
            ]
        out = []
        for v, m in zip(self.values.tolist(), self.mask.tolist()):
            out.append(v if m else None)
        return out

    def take(self, indices: np.ndarray) -> "Column":
        if self.dtype == DType.STRING:
            return Column(
                self.name, self.dtype, codes=self.codes[indices],
                dictionary=self.dictionary,
            )
        if self.encoding is not None:
            # slicing an encoded column stays encoded (shared dictionary,
            # sliced codes): batch sources cutting an encoded table into
            # batches must not force a full-width decode per slice
            return Column(
                self.name, self.dtype, encoded=self.encoding.take(indices)
            )
        return Column(
            self.name, self.dtype, values=self.values[indices], mask=self.mask[indices]
        )


def _infer_and_build(name: str, raw: Iterable) -> Column:
    """Build a Column from a Python sequence, inferring the dtype."""
    items = list(raw)
    non_null = [x for x in items if x is not None]
    if all(isinstance(x, bool) for x in non_null) and non_null:
        values = np.array([bool(x) if x is not None else False for x in items])
        mask = np.array([x is not None for x in items])
        return Column(name, DType.BOOLEAN, values=values, mask=mask)
    if all(isinstance(x, int) and not isinstance(x, bool) for x in non_null) and non_null:
        values = np.array([int(x) if x is not None else 0 for x in items], dtype=np.int64)
        mask = np.array([x is not None for x in items])
        return Column(name, DType.INTEGRAL, values=values, mask=mask)
    if all(isinstance(x, (int, float)) and not isinstance(x, bool) for x in non_null) and non_null:
        values = np.array(
            [float(x) if x is not None else 0.0 for x in items], dtype=np.float64
        )
        mask = np.array([x is not None for x in items])
        return Column(name, DType.FRACTIONAL, values=values, mask=mask)
    # everything else (incl. all-null) is a string column
    return _string_column(name, [None if x is None else str(x) for x in items])


def _string_column(name: str, items: Sequence[Optional[str]]) -> Column:
    strings = np.array([x if x is not None else "" for x in items], dtype=object)
    is_null = np.array([x is None for x in items], dtype=np.bool_)
    if len(items) == 0:
        return Column(name, DType.STRING, codes=np.array([], dtype=np.int32),
                      dictionary=np.array([], dtype=object))
    dictionary, codes = np.unique(strings.astype(str), return_inverse=True)
    codes = codes.astype(np.int32)
    codes[is_null] = -1
    return Column(name, DType.STRING, codes=codes, dictionary=dictionary.astype(object))


class ColumnarTable:
    """An immutable columnar table. The unit the analysis engine consumes."""

    def __init__(self, columns: Sequence[Column]):
        self.columns: Dict[str, Column] = {c.name: c for c in columns}
        lengths = {len(c) for c in columns}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self.num_rows = lengths.pop() if lengths else 0
        self._device_cache = None  # set by persist()

    # -- device residency (the analogue of Spark df.persist()) --------------

    def persist(self, mesh=None, encode: Optional[bool] = None) -> "ColumnarTable":
        """Pack + transfer all columns to device HBM once; subsequent scans
        stream from HBM instead of re-shipping host bytes. Multi-pass
        workloads (profiler, repeated verification) become compute-bound.
        Dictionary-encoded columns (``ColumnarTable.encode()`` / Parquet
        ingest) stay encoded in HBM — 2-8x smaller residency — unless
        ``encode=False`` / DEEQU_TPU_ENCODED_INGEST=0."""
        from deequ_tpu.ops.scan_engine import persist_table

        persist_table(self, mesh=mesh, encode=encode)
        return self

    def unpersist(self) -> "ColumnarTable":
        """Release the device-resident buffers (eagerly: the buffers are
        dropped and the cache's HBM-budget accounting zeroed now, not at
        the next GC cycle of whoever else holds the cache object)."""
        from deequ_tpu.ops.scan_engine import _evict_device_cache

        _evict_device_cache(self)
        return self

    @property
    def is_persisted(self) -> bool:
        return self._device_cache is not None

    def encode(
        self,
        columns: Optional[Sequence[str]] = None,
        max_cardinality: int = MAX_ENCODED_CARDINALITY,
    ) -> "ColumnarTable":
        """Attach dictionary encodings to the (named, default: all)
        numeric columns that qualify — the in-memory opt-in to the
        encoded ingest path (Parquet sources arrive encoded already).
        Non-encodable columns (string/boolean, cardinality past the
        int16 cap) are silently left on the decoded path. Encode BEFORE
        persist(): residency packs whatever form the columns carry."""
        names = list(columns) if columns is not None else self.column_names
        for name in names:
            self.columns[name].encode(max_cardinality)
        return self

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_pydict(data: Mapping[str, Iterable]) -> "ColumnarTable":
        return ColumnarTable([_infer_and_build(k, v) for k, v in data.items()])

    @staticmethod
    def from_rows(
        rows: Sequence[Sequence], column_names: Sequence[str]
    ) -> "ColumnarTable":
        cols = {name: [] for name in column_names}
        for row in rows:
            for name, v in zip(column_names, row):
                cols[name].append(v)
        return ColumnarTable.from_pydict(cols)

    @staticmethod
    def from_columns(columns: Sequence[Column]) -> "ColumnarTable":
        return ColumnarTable(columns)

    # -- schema / access ----------------------------------------------------

    @property
    def schema(self) -> Schema:
        return Schema([Field(c.name, c.dtype) for c in self.columns.values()])

    @property
    def column_names(self) -> List[str]:
        return list(self.columns.keys())

    def __getitem__(self, name: str) -> Column:
        return self.columns[name]

    def __contains__(self, name: str) -> bool:
        return name in self.columns

    def __len__(self) -> int:
        return self.num_rows

    def select(self, names: Sequence[str]) -> "ColumnarTable":
        return ColumnarTable([self.columns[n] for n in names])

    def filter_rows(self, keep: np.ndarray) -> "ColumnarTable":
        idx = np.nonzero(np.asarray(keep, dtype=bool))[0]
        return ColumnarTable([c.take(idx) for c in self.columns.values()])

    def with_column(self, column: Column) -> "ColumnarTable":
        cols = [c for c in self.columns.values() if c.name != column.name]
        cols.append(column)
        return ColumnarTable(cols)

    def head(self, n: int) -> "ColumnarTable":
        idx = np.arange(min(n, self.num_rows))
        return ColumnarTable([c.take(idx) for c in self.columns.values()])

    def concat(self, other: "ColumnarTable") -> "ColumnarTable":
        """Row-wise union (used by incremental-vs-batch equivalence tests)."""
        if set(self.column_names) != set(other.column_names):
            raise ValueError("schema mismatch in concat")
        cols = []
        for name in self.column_names:
            a, b = self.columns[name], other.columns[name]
            if a.dtype != b.dtype:
                raise ValueError(f"dtype mismatch for {name}")
            if a.dtype == DType.STRING:
                merged = list(a.to_pylist()) + list(b.to_pylist())
                cols.append(_string_column(name, merged))
            else:
                cols.append(
                    Column(
                        name,
                        a.dtype,
                        values=np.concatenate([a.values, b.values]),
                        mask=np.concatenate([a.mask, b.mask]),
                    )
                )
        return ColumnarTable(cols)

    def random_split(
        self, fractions: Tuple[float, float], seed: int = 0
    ) -> Tuple["ColumnarTable", "ColumnarTable"]:
        rng = np.random.default_rng(seed)
        u = rng.random(self.num_rows)
        cut = fractions[0] / (fractions[0] + fractions[1])
        return self.filter_rows(u < cut), self.filter_rows(u >= cut)

    def __repr__(self) -> str:
        return f"ColumnarTable({self.num_rows} rows, {self.schema})"
