from deequ_tpu.data.table import Column, ColumnarTable, DType, Schema

__all__ = ["Column", "ColumnarTable", "DType", "Schema"]
