from deequ_tpu.data.table import Column, ColumnarTable, DType, Schema
from deequ_tpu.data.source import (
    BatchSource,
    CSVBatchSource,
    GeneratorBatchSource,
    ParquetBatchSource,
    TableBatchSource,
)
from deequ_tpu.data.streaming import StreamingTable, stream_table

__all__ = [
    "Column",
    "ColumnarTable",
    "DType",
    "Schema",
    "BatchSource",
    "CSVBatchSource",
    "GeneratorBatchSource",
    "ParquetBatchSource",
    "TableBatchSource",
    "StreamingTable",
    "stream_table",
]
