from deequ_tpu.data.table import (
    Column,
    ColumnarTable,
    ColumnChunk,
    DType,
    Schema,
)
from deequ_tpu.data.source import (
    BatchSource,
    CSVBatchSource,
    GeneratorBatchSource,
    ParquetBatchSource,
    TableBatchSource,
)
from deequ_tpu.data.streaming import StreamingTable, stream_table

__all__ = [
    "Column",
    "ColumnarTable",
    "ColumnChunk",
    "DType",
    "Schema",
    "BatchSource",
    "CSVBatchSource",
    "GeneratorBatchSource",
    "ParquetBatchSource",
    "TableBatchSource",
    "StreamingTable",
    "stream_table",
]
