"""Pluggable filesystem layer — the analogue of the reference's Hadoop-FS
indirection (io/DfsUtils.scala:24-85) that lets state files and metric
repositories live on local disk, GCS, or S3 behind one interface.

``filesystem_for(path)`` resolves a FileSystem from the path's scheme:

- no scheme / ``file://``  -> LocalFileSystem
- ``gs://`` / ``s3://``    -> FsspecFileSystem (requires the optional
  ``fsspec`` + ``gcsfs``/``s3fs`` packages; a clear ImportError otherwise)
- anything registered via ``register_filesystem(scheme, factory)`` —
  tests register an in-memory scheme to prove the providers are
  storage-agnostic.
"""

from __future__ import annotations

import io
import os
from typing import Callable, Dict, List


class FileSystem:
    """Minimal filesystem interface the providers need."""

    def open(self, path: str, mode: str = "rb"):
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def makedirs(self, path: str) -> None:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Replace ``dst`` with ``src`` (the commit step of the crash-safe
        write-temp-fsync-rename protocol, resilience/atomic.py).

        Default: copy + delete, so FileSystem subclasses written against
        the pre-resilience 6-method interface keep working. Override with
        the store's native atomic rename where one exists — the fallback
        is all-or-nothing only if the store's writes are."""
        with self.open(src, "rb") as f:
            data = f.read()
        with self.open(dst, "wb") as f:
            f.write(data)
        self.delete(src)

    def join(self, *parts: str) -> str:
        return "/".join(p.rstrip("/") for p in parts[:-1]) + "/" + parts[-1]


class LocalFileSystem(FileSystem):
    def open(self, path: str, mode: str = "rb"):
        return open(path, mode)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def listdir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def delete(self, path: str) -> None:
        if os.path.exists(path):
            os.remove(path)

    def rename(self, src: str, dst: str) -> None:
        os.replace(src, dst)  # POSIX atomic replace

    def join(self, *parts: str) -> str:
        return os.path.join(*parts)


class FsspecFileSystem(FileSystem):
    """Remote object stores (GCS/S3/...) via fsspec, when installed."""

    def __init__(self, scheme: str):
        try:
            import fsspec
        except ImportError as e:  # pragma: no cover — env-dependent
            raise ImportError(
                f"paths with scheme '{scheme}://' require the optional "
                f"'fsspec' package (plus gcsfs for gs:// or s3fs for s3://)"
            ) from e
        self._fs = fsspec.filesystem(scheme)
        self.scheme = scheme

    def open(self, path: str, mode: str = "rb"):  # pragma: no cover
        return self._fs.open(path, mode)

    def exists(self, path: str) -> bool:  # pragma: no cover
        return self._fs.exists(path)

    def makedirs(self, path: str) -> None:  # pragma: no cover
        self._fs.makedirs(path, exist_ok=True)

    def listdir(self, path: str) -> List[str]:  # pragma: no cover
        return sorted(
            p.rsplit("/", 1)[-1] for p in self._fs.ls(path, detail=False)
        )

    def delete(self, path: str) -> None:  # pragma: no cover
        if self._fs.exists(path):
            self._fs.rm(path)

    def rename(self, src: str, dst: str) -> None:  # pragma: no cover
        # object stores rename by copy+delete; their single-object puts
        # are already all-or-nothing, so this is still crash-safe
        self._fs.mv(src, dst)


class InMemoryFileSystem(FileSystem):
    """Dict-backed filesystem (tests + ephemeral runs)."""

    def __init__(self):
        self.files: Dict[str, bytes] = {}

    def open(self, path: str, mode: str = "rb"):
        if "r" in mode:
            if path not in self.files:
                raise FileNotFoundError(path)
            data = self.files[path]
            return io.BytesIO(data) if "b" in mode else io.StringIO(data.decode())
        fs = self

        class _Writer(io.BytesIO if "b" in mode else io.StringIO):  # type: ignore[misc]
            def close(inner):
                payload = inner.getvalue()
                fs.files[path] = (
                    payload if isinstance(payload, bytes) else payload.encode()
                )
                super().close()

        return _Writer()

    def exists(self, path: str) -> bool:
        return path in self.files or any(
            k.startswith(path.rstrip("/") + "/") for k in self.files
        )

    def makedirs(self, path: str) -> None:
        pass  # directories are implicit

    def listdir(self, path: str) -> List[str]:
        prefix = path.rstrip("/") + "/"
        return sorted(
            k[len(prefix):] for k in self.files if k.startswith(prefix)
        )

    def delete(self, path: str) -> None:
        self.files.pop(path, None)

    def rename(self, src: str, dst: str) -> None:
        if src not in self.files:
            raise FileNotFoundError(src)
        self.files[dst] = self.files.pop(src)


_REGISTRY: Dict[str, Callable[[str], FileSystem]] = {}
_LOCAL = LocalFileSystem()


def register_filesystem(scheme: str, factory: Callable[[str], FileSystem]) -> None:
    """Register a FileSystem factory for a URL scheme (e.g. tests register
    'mem'; deployments could register an authenticated client)."""
    _REGISTRY[scheme] = factory


def filesystem_for(path: str) -> FileSystem:
    """Resolve the FileSystem responsible for ``path`` by URL scheme."""
    if "://" in path:
        scheme = path.split("://", 1)[0]
        if scheme == "file":
            return _LOCAL
        if scheme in _REGISTRY:
            return _REGISTRY[scheme](path)
        return FsspecFileSystem(scheme)
    return _LOCAL


def strip_scheme(path: str) -> str:
    """file:///x -> /x; other schemes keep the full URL (their fs expects it)."""
    if path.startswith("file://"):
        return path[len("file://"):]
    return path
