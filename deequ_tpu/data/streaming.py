"""StreamingTable — a table handle over a BatchSource, never materialized.

Passing a StreamingTable anywhere a ColumnarTable is accepted
(VerificationSuite.on_data, AnalysisRunner, ColumnProfiler, Histogram, ...)
runs the SAME analysis out-of-core:

- scan-shareable analyzers stream through the fused scan engine in one
  pipelined pass (scan_engine.run_scan detects the streaming handle);
- every other analyzer folds its monoid state per batch
  (``state = state.sum(compute_state_from(batch))``) — the same merge used
  across devices and across incremental runs, applied across batches.

Host memory stays bounded by the batch size regardless of dataset size —
the structural property that lets the reference profile TB datasets
(profiles/ColumnProfiler.scala:57-68).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from deequ_tpu.data.source import BatchSource, TableBatchSource
from deequ_tpu.data.table import ColumnarTable, DType, Field, Schema


class _SchemaColumn:
    """Schema-only view of a streamed column: carries name/dtype (all the
    planner needs to build scan ops) and refuses data access with a clear
    error instead of silently materializing."""

    __slots__ = ("name", "dtype")

    def __init__(self, name: str, dtype: DType):
        self.name = name
        self.dtype = dtype

    def __getattr__(self, item):
        raise AttributeError(
            f"column {self.name!r} belongs to a StreamingTable; its data is "
            f"never materialized — iterate table.batches() instead"
        )


class StreamingTable:
    """Out-of-core table: schema + batch iterator, no resident data."""

    is_streaming = True
    is_persisted = False
    _device_cache = None

    def __init__(
        self,
        source: BatchSource,
        transforms: Optional[
            List[Tuple[Callable[[ColumnarTable], ColumnarTable], frozenset]]
        ] = None,
        schema_override: Optional[Schema] = None,
        group_memory_budget: Optional[int] = None,
        retry_policy=None,
    ):
        # each transform is (fn, input_columns): the inputs are added to
        # column-pruned reads so transforms keep working without forcing a
        # full-width read of the source
        self.source = source
        self._transforms = list(transforms or [])
        self._schema = schema_override or source.schema
        # grouping-state RSS bound carried by the data handle (runners and
        # analyzers read it via spill.resolve_group_budget): frequency
        # tables spill to sorted disk runs past this many bytes
        self.group_memory_budget = group_memory_budget
        # batch-read retry policy carried by the data handle (runners read
        # it via resilience.retry.resolve_retry_policy)
        self.retry_policy = retry_policy

    # -- schema surface (everything the planner touches) --------------------

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def column_names(self) -> List[str]:
        return self._schema.column_names

    @property
    def preferred_batch_rows(self) -> Optional[int]:
        """Source-configured batch size (the user's host-memory budget);
        the scan engine sizes its chunks to it."""
        return getattr(self.source, "_batch_rows", None)

    @property
    def num_rows(self) -> int:
        n = self.source.num_rows
        if n is None:
            raise TypeError(
                "this StreamingTable's source does not know its row count; "
                "use Size() to measure it in a scan"
            )
        return n

    def __contains__(self, name: str) -> bool:
        return self._schema.has_column(name)

    def __getitem__(self, name: str) -> _SchemaColumn:
        f = self._schema[name]
        return _SchemaColumn(f.name, f.dtype)

    def __len__(self) -> int:
        return self.num_rows

    def __repr__(self) -> str:
        return f"StreamingTable({self._schema})"

    # -- batches -------------------------------------------------------------

    def batches(
        self,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        """Yield ColumnarTable batches (optionally column-pruned)."""
        yield from self.batches_from(0, columns=columns, batch_rows=batch_rows)

    def batches_from(
        self,
        start: int = 0,
        columns: Optional[Sequence[str]] = None,
        batch_rows: Optional[int] = None,
    ) -> Iterator[ColumnarTable]:
        """Batches from batch index ``start`` — the seek primitive the
        resilience layer's retry/checkpoint-resume paths use; transforms
        apply per batch exactly as in ``batches``."""
        def src_from(start_idx, read_cols, rows):
            if hasattr(self.source, "batches_from"):
                return self.source.batches_from(
                    start_idx, columns=read_cols, batch_rows=rows
                )
            # duck-typed sources that only implement batches(): the base
            # protocol's islice fallback works unbound on any of them
            return BatchSource.batches_from(
                self.source, start_idx, columns=read_cols, batch_rows=rows
            )

        if self._transforms:
            # read the requested columns plus every transform input, apply
            # transforms per batch, then prune to the request
            read: Optional[List[str]] = None
            if columns is not None:
                want = set(columns)
                for _, inputs in self._transforms:
                    want |= inputs
                read = [n for n in self.source.schema.column_names if n in want]
            for raw in src_from(start, read, batch_rows):
                batch = raw
                for fn, _ in self._transforms:
                    batch = fn(batch)
                if columns is not None:
                    keep = set(columns)
                    batch = batch.select(
                        [n for n in batch.column_names if n in keep]
                    )
                yield batch
        else:
            yield from src_from(start, columns, batch_rows)

    # -- lazy per-batch column casts (profiler pass-2 support) ---------------

    def with_casts(self, casts: Dict[str, DType]) -> "StreamingTable":
        """A new StreamingTable whose string columns named in ``casts`` are
        cast to numeric per batch (unparsable values become null) — the
        out-of-core analogue of ColumnProfiler.castColumn."""
        from deequ_tpu.data.cast import cast_string_column

        def transform(batch: ColumnarTable) -> ColumnarTable:
            out = batch
            for name, target in casts.items():
                if name in out and out[name].dtype == DType.STRING:
                    out = out.with_column(cast_string_column(out[name], target))
            return out

        fields = [
            Field(f.name, casts.get(f.name, f.dtype))
            if f.name in casts
            else f
            for f in self._schema
        ]
        return StreamingTable(
            self.source,
            self._transforms + [(transform, frozenset(casts))],
            Schema(fields),
            group_memory_budget=self.group_memory_budget,
            retry_policy=self.retry_policy,
        )

    def with_group_memory_budget(self, budget_bytes: int) -> "StreamingTable":
        """A new handle whose grouping analyses spill frequency state to
        disk past ``budget_bytes`` of host RAM (deequ_tpu/spill): the
        out-of-core guarantee extends from O(batch) scan state to the
        otherwise O(#distinct) frequency tables."""
        return StreamingTable(
            self.source,
            self._transforms,
            self._schema,
            group_memory_budget=int(budget_bytes),
            retry_policy=self.retry_policy,
        )

    def with_retry(self, policy=None) -> "StreamingTable":
        """A new handle whose batch reads run under ``policy`` (a
        resilience.RetryPolicy; None = the default I/O policy): transient
        source errors cost a backoff + reopen-at-batch instead of the run.
        The policy rides on the handle, so every consumer — the fused
        streaming scan, grouping folds, the profiler — reads through it."""
        from deequ_tpu.resilience.retry import DEFAULT_IO_RETRY, RetryingBatchSource

        policy = policy if policy is not None else DEFAULT_IO_RETRY
        return StreamingTable(
            RetryingBatchSource(self.source, policy),
            self._transforms,
            self._schema,
            group_memory_budget=self.group_memory_budget,
            retry_policy=policy,
        )

    # -- materialization guards ----------------------------------------------

    def persist(self, mesh=None) -> "StreamingTable":
        raise TypeError(
            "a StreamingTable cannot be persisted to HBM — it is unbounded "
            "by design; read it into a ColumnarTable first if it fits"
        )

    def unpersist(self) -> "StreamingTable":
        return self

    def collect(self, batch_rows: Optional[int] = None) -> ColumnarTable:
        """Materialize the full stream (testing / small sources only)."""
        merged: Optional[ColumnarTable] = None
        for batch in self.batches(batch_rows=batch_rows):
            merged = batch if merged is None else merged.concat(batch)
        if merged is None:
            merged = _empty_table(self._schema)
        return merged


def _empty_table(schema: Schema) -> ColumnarTable:
    import numpy as np

    cols = []
    for f in schema:
        if f.dtype == DType.STRING:
            from deequ_tpu.data.table import Column

            cols.append(
                Column(
                    f.name, DType.STRING,
                    codes=np.empty(0, dtype=np.int32),
                    dictionary=np.empty(0, dtype=object),
                )
            )
        else:
            from deequ_tpu.data.table import Column

            cols.append(Column(f.name, f.dtype, values=np.empty(0)))
    return ColumnarTable(cols)


def is_streaming(table) -> bool:
    return bool(getattr(table, "is_streaming", False))


def stream_table(table: ColumnarTable, batch_rows: Optional[int] = None) -> StreamingTable:
    """Wrap an in-memory table as a stream (testing helper)."""
    return StreamingTable(TableBatchSource(table, batch_rows))
