"""Data ingestion: CSV / Parquet / pandas -> ColumnarTable.

The reference delegates IO to Spark; here ingestion produces the columnar,
dictionary-encoded representation the device engine consumes. CSV uses the
stdlib reader with type inference (empty fields are nulls); Parquet and
pandas interop go through pyarrow/pandas when available (both are present
in this image) and degrade with a clear error otherwise.
"""

from __future__ import annotations

import csv
from typing import Dict, List, Optional, Sequence

import numpy as np

from deequ_tpu.data.table import Column, ColumnarTable, DType, _string_column

# pyarrow CSV's default bool literal sets ('1'/'0' included). Pure
# numeric 0/1 columns never reach the bool check — the integer cast
# claims them first — so these only matter for columns MIXING word and
# digit literals, which pyarrow (and the streaming CSV source) infer as
# bool; keeping the same set preserves read_csv == stream_csv parity.
_TRUE = {"true", "True", "TRUE", "1"}
_FALSE = {"false", "False", "FALSE", "0"}


def _infer_cell(cell: str):
    if cell == "":
        return None
    return cell


def read_csv(
    path: str,
    delimiter: str = ",",
    header: bool = True,
    column_names: Optional[Sequence[str]] = None,
    infer_types: bool = True,
) -> ColumnarTable:
    """Read a CSV file into a ColumnarTable with per-column type inference
    (integral -> fractional -> boolean -> string; empty cells are null)."""
    with open(path, newline="") as f:
        reader = csv.reader(f, delimiter=delimiter)
        rows = list(reader)
    if not rows:
        return ColumnarTable([])
    if header:
        names = rows[0]
        rows = rows[1:]
    else:
        names = column_names or [f"_c{i}" for i in range(len(rows[0]))]
    columns: Dict[str, list] = {name: [] for name in names}
    for row in rows:
        for i, name in enumerate(names):
            cell = row[i] if i < len(row) else ""
            columns[name].append(_infer_cell(cell))
    out = []
    for name, raw in columns.items():
        out.append(_build_typed_column(name, raw, infer_types))
    return ColumnarTable(out)


def _build_typed_column(name: str, raw: List[Optional[str]], infer: bool) -> Column:
    non_null = [v for v in raw if v is not None]
    if infer and non_null:
        if all(_is_int(v) for v in non_null):
            values = np.array(
                [int(v) if v is not None else 0 for v in raw], dtype=np.int64
            )
            mask = np.array([v is not None for v in raw])
            return Column(name, DType.INTEGRAL, values=values, mask=mask)
        if all(_is_float(v) for v in non_null):
            values = np.array(
                [float(v) if v is not None else 0.0 for v in raw], dtype=np.float64
            )
            mask = np.array([v is not None for v in raw])
            return Column(name, DType.FRACTIONAL, values=values, mask=mask)
        if all(v in _TRUE or v in _FALSE for v in non_null):
            values = np.array(
                [v in _TRUE if v is not None else False for v in raw]
            )
            mask = np.array([v is not None for v in raw])
            return Column(name, DType.BOOLEAN, values=values, mask=mask)
    return _string_column(name, raw)


def _is_int(v: str) -> bool:
    try:
        int(v)
        return True
    except ValueError:
        return False


def _is_float(v: str) -> bool:
    try:
        float(v)
        return True
    except ValueError:
        return False


def read_parquet(path: str, columns: Optional[Sequence[str]] = None) -> ColumnarTable:
    """Read a Parquet file via pyarrow."""
    try:
        import pyarrow.parquet as pq
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "read_parquet requires pyarrow, which is not installed"
        ) from e
    table = pq.read_table(path, columns=list(columns) if columns else None)
    return from_arrow(table)


def _from_arrow_dictionary(name: str, combined) -> Optional[Column]:
    """A Column carrying the ENCODED payload straight off an Arrow
    DictionaryArray with a numeric value type — no host decode: the
    indices + dictionary + validity bitmap ride through as a
    ``ColumnChunk`` (data/table.py) and decode fuses into the scan
    program. Returns None when the engine should decode instead
    (non-numeric value type, or cardinality past the int16 code cap —
    the all-unique fallback)."""
    import pyarrow as pa

    from deequ_tpu.data.table import MAX_ENCODED_CARDINALITY, ColumnChunk

    value_type = combined.type.value_type
    if pa.types.is_integer(value_type):
        dtype, np_dtype = DType.INTEGRAL, np.int64
    elif pa.types.is_floating(value_type):
        dtype, np_dtype = DType.FRACTIONAL, np.float64
    else:
        return None
    dictionary = np.asarray(combined.dictionary, dtype=np_dtype)
    if len(dictionary) > MAX_ENCODED_CARDINALITY:
        return None
    mask = ~np.asarray(combined.is_null())
    codes = np.asarray(combined.indices.fill_null(0))
    enc = ColumnChunk.from_codes(codes, dictionary, mask=mask)
    return Column(name, dtype, encoded=enc)


def from_arrow(table) -> ColumnarTable:
    """Convert a pyarrow Table. Numeric DictionaryArray columns (Parquet
    dictionary encoding read with ``read_dictionary``) keep their encoded
    form — see ``_from_arrow_dictionary`` / docs/ingest.md."""
    import pyarrow as pa

    cols = []
    for name, column in zip(table.column_names, table.columns):
        combined = column.combine_chunks()
        pa_type = combined.type
        if pa.types.is_dictionary(pa_type):
            encoded = _from_arrow_dictionary(name, combined)
            if encoded is not None:
                cols.append(encoded)
                continue
            # decode non-encodable dictionaries and fall through to the
            # plain branches below
            combined = combined.cast(pa_type.value_type)
            pa_type = combined.type
        if pa.types.is_integer(pa_type):
            mask = ~np.asarray(combined.is_null())
            values = np.asarray(combined.fill_null(0), dtype=np.int64)
            cols.append(Column(name, DType.INTEGRAL, values=values, mask=mask))
        elif pa.types.is_floating(pa_type):
            # Arrow distinguishes null from NaN; the engine's convention
            # (matching from_pandas) is NaN == null: fold isnan into the
            # mask so valid NaNs never silently become 0.0 values.
            # +/-inf stays a valid value (as in Spark).
            arr = np.asarray(combined.fill_null(np.nan), dtype=np.float64)
            mask = ~np.isnan(arr)
            values = np.where(mask, arr, 0.0)
            cols.append(Column(name, DType.FRACTIONAL, values=values, mask=mask))
        elif pa.types.is_boolean(pa_type):
            mask = ~np.asarray(combined.is_null())
            values = np.asarray(combined.fill_null(False), dtype=np.bool_)
            cols.append(Column(name, DType.BOOLEAN, values=values, mask=mask))
        else:
            strings = [None if v is None else str(v) for v in combined.to_pylist()]
            cols.append(_string_column(name, strings))
    return ColumnarTable(cols)


def to_arrow(table: ColumnarTable):
    """Convert a ColumnarTable to a pyarrow Table (nulls preserved)."""
    import pyarrow as pa

    arrays = {}
    for name in table.column_names:
        col = table[name]
        if col.dtype == DType.STRING:
            arrays[name] = pa.array(col.to_pylist(), type=pa.string())
        else:
            values = col.values
            if col.mask.all():
                arrays[name] = pa.array(values)
            else:
                arrays[name] = pa.array(
                    values, mask=~np.asarray(col.mask, dtype=bool)
                )
    return pa.table(arrays)


def write_parquet(table: ColumnarTable, path: str, row_group_rows: int = 1 << 20) -> None:
    """Write a ColumnarTable to one Parquet file."""
    import pyarrow.parquet as pq

    pq.write_table(to_arrow(table), path, row_group_size=row_group_rows)


def write_parquet_stream(batches, path: str) -> int:
    """Write an iterator of ColumnarTable batches to one Parquet file
    without ever holding more than a batch (benchmark/data-prep helper for
    out-of-core datasets). Returns the number of rows written."""
    import pyarrow.parquet as pq

    writer = None
    rows = 0
    try:
        for batch in batches:
            arrow = to_arrow(batch)
            if writer is None:
                writer = pq.ParquetWriter(path, arrow.schema)
            writer.write_table(arrow)
            rows += batch.num_rows
    finally:
        if writer is not None:
            writer.close()
    return rows


def stream_parquet(paths, columns=None, batch_rows=None):
    """Open Parquet file(s) as a StreamingTable — the out-of-core entry
    point: analyses run over it in bounded host memory."""
    from deequ_tpu.data.source import ParquetBatchSource
    from deequ_tpu.data.streaming import StreamingTable

    return StreamingTable(ParquetBatchSource(paths, columns, batch_rows))


def stream_csv(path, columns=None, batch_rows=None, delimiter=","):
    """Open a CSV file as a StreamingTable (pyarrow's incremental C++
    parser; the file is never materialized)."""
    from deequ_tpu.data.source import CSVBatchSource
    from deequ_tpu.data.streaming import StreamingTable

    return StreamingTable(CSVBatchSource(path, columns, batch_rows, delimiter))


def from_pandas(df) -> ColumnarTable:
    """Convert a pandas DataFrame."""
    import pandas as pd

    cols = []
    for name in df.columns:
        series = df[name]
        if pd.api.types.is_integer_dtype(series.dtype):
            cols.append(
                Column(
                    str(name), DType.INTEGRAL,
                    values=series.to_numpy(dtype=np.int64),
                    mask=np.ones(len(series), dtype=np.bool_),
                )
            )
        elif pd.api.types.is_float_dtype(series.dtype):
            arr = series.to_numpy(dtype=np.float64)
            mask = ~np.isnan(arr)
            # zero only the null (NaN) slots; +/-inf stays a valid value
            cols.append(
                Column(
                    str(name), DType.FRACTIONAL,
                    values=np.where(mask, arr, 0.0), mask=mask,
                )
            )
        elif pd.api.types.is_bool_dtype(series.dtype):
            cols.append(
                Column(
                    str(name), DType.BOOLEAN,
                    values=series.to_numpy(dtype=np.bool_),
                    mask=np.ones(len(series), dtype=np.bool_),
                )
            )
        else:
            strings = [None if pd.isna(v) else str(v) for v in series]
            cols.append(_string_column(str(name), strings))
    return ColumnarTable(cols)
