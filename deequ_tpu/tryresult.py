"""A tiny Try/Success/Failure result type.

The reference stores every metric value as ``Try[T]`` (metrics/Metric.scala:30)
so that partial failure is first-class data. This module is the Python
equivalent used throughout deequ_tpu.
"""

from __future__ import annotations

from typing import Callable, Generic, TypeVar

T = TypeVar("T")
U = TypeVar("U")


class Try(Generic[T]):
    """Either a Success carrying a value or a Failure carrying an exception."""

    is_success: bool = False

    @staticmethod
    def of(fn: Callable[[], T]) -> "Try[T]":
        try:
            return Success(fn())
        except Exception as e:  # noqa: BLE001 — failure is data here
            return Failure(e)

    def get(self) -> T:
        raise NotImplementedError

    def get_or_else(self, default):
        return self.get() if self.is_success else default

    def map(self, fn: Callable[[T], U]) -> "Try[U]":
        raise NotImplementedError

    @property
    def is_failure(self) -> bool:
        return not self.is_success


class Success(Try[T]):
    is_success = True

    __slots__ = ("value",)

    def __init__(self, value: T):
        self.value = value

    def get(self) -> T:
        return self.value

    def map(self, fn: Callable[[T], U]) -> Try[U]:
        return Try.of(lambda: fn(self.value))

    def __repr__(self) -> str:
        return f"Success({self.value!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, Success) and self.value == other.value

    def __hash__(self) -> int:
        return hash(("Success", self.value))


class Failure(Try[T]):
    is_success = False

    __slots__ = ("exception",)

    def __init__(self, exception: BaseException):
        self.exception = exception

    def get(self) -> T:
        raise self.exception

    def map(self, fn) -> Try:
        return self

    def __repr__(self) -> str:
        return f"Failure({self.exception!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Failure)
            and type(self.exception) is type(other.exception)
            and str(self.exception) == str(other.exception)
        )

    def __hash__(self) -> int:
        return hash(("Failure", type(self.exception), str(self.exception)))
