"""RetryPolicy — exponential backoff + jitter + deadline for transient I/O.

Long-running stream pipelines treat failure as routine (TiLT,
arXiv:2301.12030): a TB-scale verification run will see transient storage
errors, and a single flaky read must cost one retry, not the whole run.
The policy is a value object; the three application points are

- ``retry_call`` — wrap any one-shot I/O callable (filesystem opens,
  spill-run opens);
- ``RetryingFileSystem`` — a FileSystem proxy whose every operation runs
  under the policy (repository + state-provider storage);
- ``resilient_batches`` / ``RetryingBatchSource`` — per-batch retry with
  reopen-and-fast-forward over a ``BatchSource``, plus the quarantine
  policy (``on_batch_error="skip"``) used by streaming verification runs.

Determinism: jitter draws from a policy-owned ``random.Random(seed)``, so
tests (and reproductions of production incidents) see identical sleep
schedules for identical failure sequences.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from random import Random
from typing import Callable, Iterator, List, Optional, Tuple

from deequ_tpu.exceptions import RetryExhaustedException

# errors worth retrying by default: the OS/network layer, not logic errors
DEFAULT_RETRY_ON = (OSError, TimeoutError)


class RetryTelemetry:
    """Process-wide retry accounting — retries were previously invisible
    to callers (a run that quietly slept through 40 backoffs looked
    identical to a clean one). Every RetryPolicy invocation records its
    attempts, its total backoff sleep, and the last exception seen;
    ``VerificationSuite`` snapshots the counters around each run and
    surfaces the delta as ``VerificationResult.retry_stats``."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        # one consistent meaning across both producers (RetryPolicy.call
        # and resilient_batches): invocations = retried-operation contexts
        # entered; attempts = FAILED tries observed (a clean first try is
        # not an "attempt" — millions of healthy batch reads must not
        # swamp the counters); retries = failed tries that were followed
        # by a backoff sleep; exhausted = operations abandoned past the
        # attempt/deadline budget.
        self.invocations = 0
        self.attempts = 0
        self.retries = 0
        self.backoff_seconds = 0.0  # total time spent sleeping
        self.exhausted = 0
        self.last_exception: Optional[str] = None

    def record_attempt(self) -> None:
        self.attempts += 1
        # flight-recorder seam: a FAILED I/O try is an instant event on
        # the armed recording (the clean fast path never reaches here,
        # so healthy traced runs record nothing from this layer)
        from deequ_tpu.obs.recorder import current_recorder

        rec = current_recorder()
        if rec is not None:
            rec.event("io_retry", attempts=self.attempts)

    def record_retry(self, slept: float, exc: BaseException) -> None:
        self.retries += 1
        self.backoff_seconds += slept
        self.last_exception = f"{type(exc).__name__}: {exc}"

    def record_exhausted(self, exc: BaseException) -> None:
        self.exhausted += 1
        self.last_exception = f"{type(exc).__name__}: {exc}"
        from deequ_tpu.obs.recorder import current_recorder

        rec = current_recorder()
        if rec is not None:
            rec.event(
                "io_retry_exhausted", error=f"{type(exc).__name__}: {exc}"
            )

    def snapshot(self) -> dict:
        return {
            "invocations": self.invocations,
            "attempts": self.attempts,
            "retries": self.retries,
            "backoff_seconds": self.backoff_seconds,
            "exhausted": self.exhausted,
            "last_exception": self.last_exception,
        }

    def delta_since(self, before: dict) -> dict:
        now = self.snapshot()
        out = {
            key: (
                round(now[key] - before[key], 6)
                if isinstance(now[key], float)
                else now[key] - before[key]
            )
            for key in now
            if key != "last_exception"
        }
        # the last exception is only meaningful if something failed since
        out["last_exception"] = (
            now["last_exception"]
            if (out["retries"] or out["exhausted"])
            else None
        )
        return out


RETRY_TELEMETRY = RetryTelemetry()


def _quarantinable(exc: BaseException) -> bool:
    """Errors that mean 'this batch is unreadable/undecodable' — eligible
    for quarantine under on_batch_error='skip'. I/O errors, typed
    corruption, and decoder-layer errors (pyarrow) qualify; an arbitrary
    exception is treated as a bug and propagates."""
    if isinstance(exc, DEFAULT_RETRY_ON):
        return True
    from deequ_tpu.exceptions import CorruptStateException

    if isinstance(exc, CorruptStateException):
        return True
    try:  # decode errors from the arrow readers (torn/corrupt data pages)
        import pyarrow as pa

        return isinstance(exc, pa.lib.ArrowException)
    # deequ-lint: ignore[bare-except] -- optional-dependency probe (pyarrow), not a device seam
    except Exception:  # noqa: BLE001 — pyarrow absent: nothing to match
        return False


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter and an optional wall deadline.

    Delay for attempt k (0-based) is
    ``min(base_delay * multiplier**k, max_delay)`` scaled by a jitter draw
    in ``[1 - jitter, 1]``; the whole retried operation must finish within
    ``deadline`` seconds of its first attempt (None = no deadline)."""

    max_attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline: Optional[float] = None
    retry_on: Tuple[type, ...] = DEFAULT_RETRY_ON
    seed: int = 0
    _rng: Random = field(init=False, repr=False, compare=False, hash=False)

    def __post_init__(self):
        object.__setattr__(self, "_rng", Random(self.seed))

    def delay_for(self, attempt: int) -> float:
        raw = min(
            self.base_delay * (self.multiplier ** attempt), self.max_delay
        )
        if self.jitter:
            raw *= 1.0 - self.jitter * self._rng.random()
        return raw

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retry_on)

    def call(self, fn: Callable, *args, what: str = "operation", **kwargs):
        """Run ``fn`` under the policy; raises RetryExhaustedException when
        the attempt budget or deadline runs out. Every invocation feeds
        the process-wide RETRY_TELEMETRY counters, and every FAILED try
        charges the ambient run budget (resilience/governance.py) — a
        run-level ``max_total_attempts`` bounds the composed ladder, so
        an exhausted budget raises typed from here mid-retry."""
        from deequ_tpu.resilience.governance import (
            charge_run_budget,
            run_budget_remaining,
        )

        start = time.monotonic()
        attempt = 0
        RETRY_TELEMETRY.invocations += 1
        while True:
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — filtered below
                if not self.is_retryable(e):
                    raise
                RETRY_TELEMETRY.record_attempt()
                charge_run_budget("io_retry", what=what)
                attempt += 1
                out_of_time = (
                    self.deadline is not None
                    and time.monotonic() - start >= self.deadline
                )
                if attempt >= self.max_attempts or out_of_time:
                    RETRY_TELEMETRY.record_exhausted(e)
                    raise RetryExhaustedException(what, attempt, e) from e
                delay = self.delay_for(attempt - 1)
                # never sleep past the run's wall budget: the next charge
                # would exhaust it anyway, but the sleep itself must not
                # overshoot the deadline the caller promised
                wall_left = run_budget_remaining()
                if wall_left is not None:
                    delay = min(delay, wall_left)
                RETRY_TELEMETRY.record_retry(delay, e)
                time.sleep(delay)


# conservative default for storage-layer wrapping: quick, bounded, and a
# no-op on healthy storage
DEFAULT_IO_RETRY = RetryPolicy(max_attempts=3, base_delay=0.02, max_delay=0.5)

# retrying is strictly additive behavior, but deployments may want it off
# (e.g. under a fault-injection harness testing the UNretried paths)
_default_policy: Optional[RetryPolicy] = DEFAULT_IO_RETRY


def default_retry_policy() -> Optional[RetryPolicy]:
    return _default_policy


def set_default_retry_policy(policy: Optional[RetryPolicy]) -> None:
    """Install the process-wide storage-retry policy (None disables)."""
    global _default_policy
    _default_policy = policy


def retry_call(fn: Callable, policy: Optional[RetryPolicy] = None,
               what: str = "operation"):
    """``fn()`` under ``policy`` (or the process default; no policy = one
    plain call)."""
    policy = policy if policy is not None else _default_policy
    if policy is None:
        return fn()
    return policy.call(fn, what=what)


def resolve_retry_policy(data=None, explicit: Optional[RetryPolicy] = None):
    """Policy resolution for batch reads: explicit argument > table
    attribute (``StreamingTable.with_retry``) > process default."""
    if explicit is not None:
        return explicit
    attr = getattr(data, "retry_policy", None)
    if attr is not None:
        return attr
    return _default_policy


class RetryingFileSystem:
    """FileSystem proxy running every operation under a RetryPolicy.

    ``open`` retries the open call itself; an error raised mid-read/write
    from the returned handle propagates (the caller's unit of retry is the
    whole read-or-write, e.g. ``atomic_write_bytes``)."""

    def __init__(self, inner, policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy

    def _call(self, name: str, *args, **kwargs):
        return retry_call(
            lambda: getattr(self.inner, name)(*args, **kwargs),
            self.policy,
            what=f"filesystem {name}",
        )

    def open(self, path: str, mode: str = "rb"):
        return self._call("open", path, mode)

    def exists(self, path: str) -> bool:
        return self._call("exists", path)

    def makedirs(self, path: str) -> None:
        self._call("makedirs", path)

    def listdir(self, path: str) -> List[str]:
        return self._call("listdir", path)

    def delete(self, path: str) -> None:
        self._call("delete", path)

    def rename(self, src: str, dst: str) -> None:
        self._call("rename", src, dst)

    def join(self, *parts: str) -> str:
        return self.inner.join(*parts)


def retrying_filesystem_for(path: str):
    """``filesystem_for(path)`` wrapped in the process retry policy —
    the storage resolution used by the persistence layers."""
    from deequ_tpu.data.fs import filesystem_for

    return RetryingFileSystem(filesystem_for(path), None)


# -- resilient batch iteration ----------------------------------------------


def resilient_batches(
    make_iter: Callable[[int], Iterator],
    policy: Optional[RetryPolicy],
    on_batch_error: str = "fail",
    quarantined: Optional[List[int]] = None,
    start: int = 0,
    max_consecutive_skips: int = 16,
    max_batches: Optional[int] = None,
):
    """Iterate batches with per-batch retry and optional quarantine.

    ``make_iter(i)`` must return a fresh iterator positioned at batch
    index ``i`` (``BatchSource.batches_from``) — deterministic batch
    boundaries are the caller's contract, which every built-in source
    satisfies for a fixed ``batch_rows``. Yields ``(index, batch)``.

    On a retryable error the iterator is reopened at the failing index
    after backoff (fast-forward is the source's job; the default
    ``batches_from`` islice implementation re-decodes skipped batches,
    sources with native seeks override it). When retries exhaust:
    ``on_batch_error="fail"`` re-raises (RetryExhaustedException),
    ``"skip"`` records the index in ``quarantined`` and resumes at the
    next batch — a poisoned batch costs its rows, not the run.

    ``max_consecutive_skips`` bounds quarantine's optimism: storage that
    is PERMANENTLY dead fails every index, and skipping forever would
    never reach end-of-stream — past this many back-to-back quarantines
    with no successful read between them, the pass fails instead.

    ``max_batches`` (when the caller knows the batch count from source
    metadata) distinguishes 'batch cur is unreadable' from 'the END-OF-
    STREAM probe errored': an error at an index past the last real batch
    ends the iteration cleanly instead of quarantining phantom indices
    or failing a run whose data was fully read.
    """
    if on_batch_error not in ("fail", "skip"):
        raise ValueError(
            f"on_batch_error must be 'fail' or 'skip', got {on_batch_error!r}"
        )
    from deequ_tpu.resilience.governance import (
        charge_run_budget,
        run_budget_remaining,
    )

    cur = start
    attempts = 0
    consecutive_skips = 0
    RETRY_TELEMETRY.invocations += 1
    t0 = time.monotonic()
    while True:
        it = make_iter(cur)
        try:
            while True:
                try:
                    batch = next(it)
                except StopIteration:
                    return
                yield cur, batch
                cur += 1
                attempts = 0
                consecutive_skips = 0
                t0 = time.monotonic()
        except BaseException as e:  # noqa: BLE001 — filtered below
            if max_batches is not None and cur >= max_batches:
                # every real batch was read; this error came from the
                # end-of-stream probe, not from data
                return
            # a RetryExhaustedException means an inner retry layer (e.g. a
            # source wrapped by with_retry -> RetryingBatchSource) already
            # spent its attempt budget on this batch: don't multiply
            # retries — treat the batch as exhausted here and now
            already_exhausted = isinstance(e, RetryExhaustedException)
            retryable = (
                not already_exhausted
                and policy is not None
                and policy.is_retryable(e)
            )
            skippable = on_batch_error == "skip" and (
                already_exhausted or _quarantinable(e)
            )
            if not retryable and not skippable:
                raise
            attempts += 1
            # telemetry: a FAILED read is an attempt (same meaning as
            # RetryPolicy.call — the clean fast path never counts), and
            # every failed read charges the ambient run budget too: a
            # stream of N batches retries against ONE global
            # max_total_attempts, not N per-batch budgets
            RETRY_TELEMETRY.record_attempt()
            charge_run_budget("io_retry", batch=cur)
            # non-retryable-but-skippable errors quarantine IMMEDIATELY:
            # the policy's retry_on filter said backoff cannot help here
            out_of_budget = (
                already_exhausted
                or not retryable
                or policy is None
                or attempts >= policy.max_attempts
            )
            if policy is not None and policy.deadline is not None:
                out_of_budget = out_of_budget or (
                    time.monotonic() - t0 >= policy.deadline
                )
            if out_of_budget:
                if on_batch_error == "skip":
                    consecutive_skips += 1
                    if consecutive_skips > max_consecutive_skips:
                        RETRY_TELEMETRY.record_exhausted(e)
                        raise RetryExhaustedException(
                            f"{consecutive_skips} consecutive batches "
                            f"unreadable (through batch {cur}) — the source "
                            f"looks permanently dead, not patchily flaky",
                            attempts,
                            e,
                        ) from e
                    if quarantined is not None:
                        quarantined.append(cur)
                    cur += 1
                    attempts = 0
                    t0 = time.monotonic()
                    continue
                RETRY_TELEMETRY.record_exhausted(e)
                raise RetryExhaustedException(
                    f"batch {cur} read", attempts, e
                ) from e
            delay = policy.delay_for(attempts - 1)
            # cap the backoff at the run's remaining wall budget (same
            # rationale as RetryPolicy.call)
            wall_left = run_budget_remaining()
            if wall_left is not None:
                delay = min(delay, wall_left)
            RETRY_TELEMETRY.record_retry(delay, e)
            time.sleep(delay)


class RetryingBatchSource:
    """BatchSource wrapper: every batch read runs under a RetryPolicy
    (reopen-and-fast-forward on transient errors). Plugs in anywhere a
    source does — the fused streaming scan, grouping folds, the profiler —
    because the retrying happens inside ``batches``/``batches_from``."""

    def __init__(self, inner, policy: Optional[RetryPolicy] = None):
        self.inner = inner
        self.policy = policy if policy is not None else DEFAULT_IO_RETRY

    @property
    def schema(self):
        return self.inner.schema

    @property
    def num_rows(self):
        return self.inner.num_rows

    @property
    def _batch_rows(self):
        return getattr(self.inner, "_batch_rows", None)

    def batches(self, columns=None, batch_rows=None):
        yield from self.batches_from(0, columns=columns, batch_rows=batch_rows)

    def _inner_from(self, start, columns, batch_rows):
        if hasattr(self.inner, "batches_from"):
            return self.inner.batches_from(
                start, columns=columns, batch_rows=batch_rows
            )
        # duck-typed sources that only implement batches(): the base
        # protocol's islice fallback works unbound on any of them
        from deequ_tpu.data.source import BatchSource

        return BatchSource.batches_from(
            self.inner, start, columns=columns, batch_rows=batch_rows
        )

    def batches_from(self, start: int = 0, columns=None, batch_rows=None):
        for _idx, batch in resilient_batches(
            lambda i: self._inner_from(i, columns, batch_rows),
            self.policy,
            on_batch_error="fail",
            start=start,
        ):
            yield batch
