"""OS-level write seams + the crashpoint matrix over every durable store.

``resilience/faults.py`` injects faults at the *FileSystem operation*
level (an open that raises, a writer that tears on close). This module
goes one layer deeper: it models the five ways a single durable write
can die at the *OS* level, and drives each seam at **every byte
boundary** of the write against every durable store in the system —
the request ledger, repository segments, the control-plane registry,
stream checkpoints, and (round 20) the windowed-verification state
store — asserting the store's documented recovery
contract uniformly (typed detection, last-whole-frame/previous-version
semantics, ``.corrupt`` forensic sidecars, never silent loss).

The write seams (``WRITE_SEAMS``):

- ``enospc``          — the disk fills mid-write: a prefix lands in the
                        temp file, ``write`` raises ``OSError(ENOSPC)``.
- ``short_write``     — a lying stack: write+fsync+close all report
                        success but only a prefix is durable. The commit
                        rename proceeds, so the DESTINATION is torn —
                        the one seam only checksums can catch.
- ``fsync_raises``    — fsync returns an error (lost write): a prefix is
                        durable, the writer sees ``OSError(EIO)`` before
                        the rename, so the destination keeps its
                        previous complete version.
- ``crash_before_fsync`` — the process dies after writing, before fsync:
                        a torn temp file survives, nothing was renamed,
                        and no cleanup code ever ran.
- ``crash_at_rename`` — the process dies at the commit point: a COMPLETE
                        temp file survives, the destination is old.

Crashes are modelled by ``SimulatedCrash`` deriving from
``BaseException``: best-effort ``except Exception`` layers (checkpoint
saves, cleanup paths) must NOT absorb a process death, and after a
crash the filesystem freezes — ``delete``/``rename`` silently no-op, so
``atomic_write_bytes``'s temp-file cleanup leaves exactly the litter a
real crash would.

The request ledger appends raw frames to a local file (no FileSystem
indirection, fsync-per-frame), so its matrix column is driven by the
equivalent physical outcome: the appended frame truncated at every byte
boundary (``torn_tail``), which is what any of the crash seams leaves
on disk for an append-only file.

``run_crashpoint_matrix`` sweeps seams x byte boundaries x stores; each
surviving cell increments the ``crashpoints_survived`` counter and any
violated invariant raises ``CrashpointViolation`` naming the exact
(store, seam, cut) cell that broke.
"""

from __future__ import annotations

import errno
import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple

from deequ_tpu.data.fs import (
    FileSystem,
    InMemoryFileSystem,
    register_filesystem,
)
from deequ_tpu.exceptions import (
    CorruptStateException,
    RetryExhaustedException,
)
from deequ_tpu.resilience.retry import (
    RetryPolicy,
    default_retry_policy,
    set_default_retry_policy,
)

WRITE_SEAMS = (
    "enospc",
    "short_write",
    "fsync_raises",
    "crash_before_fsync",
    "crash_at_rename",
)

#: single attempt, no backoff sleeps: the matrix asserts the UNretried
#: recovery paths (and a thousand cells must not sleep through backoff)
ONE_SHOT_RETRY = RetryPolicy(max_attempts=1, base_delay=0.0)


class SimulatedCrash(BaseException):
    """Process death at a write seam. Derives from ``BaseException`` on
    purpose: a crash must sail through every best-effort ``except
    Exception`` (checkpoint saves, cleanup handlers) exactly as a real
    SIGKILL would."""

    def __init__(self, seam: str, path: str):
        super().__init__(f"simulated crash at seam {seam!r} writing {path}")
        self.seam = seam
        self.path = path


class CrashpointViolation(AssertionError):
    """One matrix cell broke its store's recovery contract."""

    def __init__(self, store: str, seam: str, cut: int, detail: str):
        super().__init__(
            f"crashpoint violation: store={store} seam={seam} "
            f"cut_byte={cut}: {detail}"
        )
        self.store = store
        self.seam = seam
        self.cut = cut
        self.detail = detail


class _SeamWriter:
    """Write handle that buffers everything and applies the owning
    filesystem's seam at the configured byte cut. Exposes ``fsync()`` so
    ``_fsync_if_possible`` routes durability through the seam (the
    fsync-raises / crash-before-fsync trigger point)."""

    def __init__(self, fs: "WriteSeamFileSystem", path: str):
        self._fs = fs
        self._path = path
        self._buf = bytearray()
        self._closed = False

    def write(self, data) -> int:
        fs = self._fs
        self._buf += data
        if fs.seam == "enospc" and len(self._buf) > fs.at_byte:
            fs.fired = True
            self._commit(fs.at_byte)
            raise OSError(
                errno.ENOSPC, "no space left on device (injected)"
            )
        return len(data)

    def flush(self) -> None:
        pass

    def fsync(self) -> None:
        fs = self._fs
        if fs.seam == "fsync_raises":
            fs.fired = True
            self._commit(fs.at_byte)
            raise OSError(errno.EIO, "fsync reported lost write (injected)")
        if fs.seam == "crash_before_fsync":
            fs.fired = True
            fs.crashed = True
            self._commit(fs.at_byte)
            raise SimulatedCrash("crash_before_fsync", self._path)
        # short_write IS the lying-fsync seam: report success, persist
        # only the cut prefix at close. Other seams are durable here.

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        fs = self._fs
        if fs.crashed:
            return  # nothing after a crash runs
        if fs.seam == "short_write" and len(self._buf) > fs.at_byte:
            fs.fired = True
            self._commit(fs.at_byte)
            return
        self._commit(len(self._buf))
        fs.last_write_len = len(self._buf)

    def _commit(self, n: int) -> None:
        # deequ-lint: ignore[durable-write] -- this IS the seam simulator: it materializes exactly the prefix the injected fault would leave durable
        with self._fs.inner.open(self._path, "wb") as f:
            f.write(bytes(self._buf[:n]))

    def __enter__(self) -> "_SeamWriter":
        return self

    def __exit__(self, *exc) -> None:
        # a seam that fired inside the with-body already decided what is
        # durable; closing again on unwind must not re-commit
        if exc[0] is None:
            self.close()
        else:
            self._closed = True


class WriteSeamFileSystem(FileSystem):
    """FileSystem proxy that applies ONE write seam at ONE byte cut to
    write-mode opens, then freezes (``crashed``) if the seam was a
    process death: subsequent ``delete``/``rename`` silently no-op, so
    in-flight cleanup handlers leave the same litter a real crash
    would. ``seam=None`` is a pure recorder (used to measure a store's
    write length for the byte grid)."""

    def __init__(
        self,
        inner: FileSystem,
        seam: Optional[str] = None,
        at_byte: int = 0,
        path_substr: Optional[str] = None,
    ):
        if seam is not None and seam not in WRITE_SEAMS:
            raise ValueError(
                f"seam must be one of {WRITE_SEAMS} or None, got {seam!r}"
            )
        self.inner = inner
        self.seam = seam
        self.at_byte = int(at_byte)
        self.path_substr = path_substr
        self.fired = False
        self.crashed = False
        self.last_write_len = 0

    def _matches(self, path: str) -> bool:
        return self.path_substr is None or self.path_substr in path

    def open(self, path: str, mode: str = "rb"):
        if "w" in mode and "b" in mode and self._matches(path):
            return _SeamWriter(self, path)
        return self.inner.open(path, mode)

    def exists(self, path: str) -> bool:
        return self.inner.exists(path)

    def makedirs(self, path: str) -> None:
        self.inner.makedirs(path)

    def listdir(self, path: str) -> List[str]:
        return self.inner.listdir(path)

    def delete(self, path: str) -> None:
        if self.crashed:
            return  # crashed processes do not clean up their temp files
        self.inner.delete(path)

    def rename(self, src: str, dst: str) -> None:
        if self.crashed:
            return
        if self.seam == "crash_at_rename" and self._matches(src):
            self.fired = True
            self.crashed = True
            raise SimulatedCrash("crash_at_rename", src)
        self.inner.rename(src, dst)

    def join(self, *parts: str) -> str:
        return self.inner.join(*parts)


# -- crashfs:// mount point ----------------------------------------------
#
# Stores resolve their FileSystem from the path scheme, so the matrix
# mounts the per-cell filesystem (plain for baseline/verify, seamed for
# the attempt) behind one scheme and hands stores crashfs:// paths.

_CRASHFS: Dict[str, Optional[FileSystem]] = {"fs": None}


def _crashfs_factory(path: str) -> FileSystem:
    fs = _CRASHFS["fs"]
    if fs is None:
        raise LookupError(
            f"crashfs:// not mounted (resolving {path!r} outside a "
            "crashpoint-matrix cell)"
        )
    return fs


register_filesystem("crashfs", _crashfs_factory)


def _mount(fs: Optional[FileSystem]) -> None:
    _CRASHFS["fs"] = fs


#: errors a dying durable write may legitimately surface to its caller.
#: Anything else escaping an attempt is an UNTYPED leak and fails the
#: cell. SimulatedCrash is listed explicitly (BaseException).
TYPED_ATTEMPT_ERRORS = (
    OSError,
    CorruptStateException,
    RetryExhaustedException,
    SimulatedCrash,
)


class _FsStoreAdapter:
    """One durable store driven through the crashfs:// mount. Subclasses
    define ``baseline`` (prior durable state, written through a healthy
    filesystem), ``attempt`` (the ONE durable write the seam kills), and
    ``verify`` (reboot view: fresh store over the bare inner filesystem,
    asserting the recovery contract)."""

    name = "store"
    seams: Tuple[str, ...] = WRITE_SEAMS

    def baseline(self) -> None:
        raise NotImplementedError

    def attempt(self) -> None:
        raise NotImplementedError

    def verify(self, inner, seam, cut, length, err) -> None:
        raise NotImplementedError

    # -- driver ----------------------------------------------------------

    def measure_write_len(self) -> int:
        """Dry-run the attempt against a recorder to size the byte grid."""
        inner = InMemoryFileSystem()
        _mount(inner)
        self.baseline()
        probe = WriteSeamFileSystem(inner)
        _mount(probe)
        self.attempt()
        _mount(None)
        if probe.last_write_len <= 0:
            raise CrashpointViolation(
                self.name, "measure", -1,
                "attempt() performed no durable write",
            )
        return probe.last_write_len

    def run_cell(self, seam: str, cut: int, length: int) -> None:
        inner = InMemoryFileSystem()
        _mount(inner)
        self.baseline()
        seamed = WriteSeamFileSystem(inner, seam, cut)
        _mount(seamed)
        err: Optional[BaseException] = None
        try:
            self.attempt()
        except TYPED_ATTEMPT_ERRORS as e:
            err = e
        except BaseException as e:  # noqa: BLE001 — untyped leak = violation
            raise CrashpointViolation(
                self.name, seam, cut,
                f"attempt leaked untyped {type(e).__name__}: {e}",
            ) from e
        finally:
            _mount(None)
        _mount(inner)
        try:
            self.verify(inner, seam, cut, length, err)
        except CrashpointViolation:
            raise
        except BaseException as e:  # noqa: BLE001 — reboot must not fail untyped
            raise CrashpointViolation(
                self.name, seam, cut,
                f"verify after reboot raised {type(e).__name__}: {e}",
            ) from e
        finally:
            _mount(None)

    def run_matrix(self, stride: int = 1) -> Dict[str, Any]:
        from deequ_tpu.obs.registry import CRASHPOINTS_SURVIVED

        length = self.measure_write_len()
        by_seam: Dict[str, int] = {}
        for seam in self.seams:
            if seam == "crash_at_rename":
                cuts = [length]  # the write completed; the cut is moot
            else:
                cuts = list(range(0, length + 1, max(int(stride), 1)))
                if cuts[-1] != length:
                    cuts.append(length)  # always include the healthy cell
            for cut in cuts:
                self.run_cell(seam, cut, length)
                CRASHPOINTS_SURVIVED.inc()
            by_seam[seam] = len(cuts)
        return {
            "write_len": length,
            "cells": sum(by_seam.values()),
            "by_seam": by_seam,
        }


def _new_write_expected(seam: str, cut: int, length: int) -> bool:
    """Whether the attempted write must be durably visible after reboot:
    only when the seam never actually fired (cut past the payload) or
    the torn commit happened to cover the whole payload."""
    return seam in ("enospc", "short_write") and cut >= length


class RepositorySegmentAdapter(_FsStoreAdapter):
    """Columnar metrics repository: segment files are checksummed and
    committed by rename; a torn TAIL segment quarantines to a
    ``.corrupt`` sidecar under ``on_torn_segment='recover'`` while every
    prior segment stays live."""

    name = "repository_segment"
    path = "crashfs://repo"

    @staticmethod
    def _result(date: int):
        from deequ_tpu.analyzers import Completeness, Size
        from deequ_tpu.analyzers.runner import AnalyzerContext
        from deequ_tpu.metrics import DoubleMetric, Entity
        from deequ_tpu.repository import AnalysisResult, ResultKey
        from deequ_tpu.tryresult import Success

        mm = {
            Completeness("col_a"): DoubleMetric(
                Entity.COLUMN, "Completeness", "col_a",
                Success(0.25 * date),
            ),
            Size(): DoubleMetric(
                Entity.DATASET, "Size", "*", Success(float(100 + date))
            ),
        }
        return AnalysisResult(ResultKey(date), AnalyzerContext(mm))

    def _repo(self):
        from deequ_tpu.repository.columnar import ColumnarMetricsRepository

        return ColumnarMetricsRepository(
            self.path, on_torn_segment="recover", retry=ONE_SHOT_RETRY
        )

    def baseline(self) -> None:
        self._repo().save(self._result(1))

    def attempt(self) -> None:
        self._repo().save(self._result(2))

    def verify(self, inner, seam, cut, length, err) -> None:
        from deequ_tpu.repository import ResultKey

        repo = self._repo()
        r1 = repo.load_by_key(ResultKey(1))
        if r1 is None:
            raise CrashpointViolation(
                self.name, seam, cut, "baseline segment lost"
            )
        if len(r1.analyzer_context.metric_map) != 2:
            raise CrashpointViolation(
                self.name, seam, cut, "baseline result decoded incomplete"
            )
        r2 = repo.load_by_key(ResultKey(2))
        if _new_write_expected(seam, cut, length):
            if r2 is None:
                raise CrashpointViolation(
                    self.name, seam, cut,
                    "healthy-cut write missing after reboot",
                )
        elif r2 is not None:
            raise CrashpointViolation(
                self.name, seam, cut,
                "torn/failed segment readable after reboot "
                "(should be absent or quarantined)",
            )
        if seam == "short_write" and cut < length:
            names = inner.listdir(self.path)
            if not any(".corrupt" in n for n in names):
                raise CrashpointViolation(
                    self.name, seam, cut,
                    f"torn committed segment not quarantined (saw {names})",
                )


class ControlRegistryAdapter(_FsStoreAdapter):
    """Control-plane check registry: single checksummed JSON state file,
    atomically replaced on every mutation. Its recovery posture is
    raise-typed (``CorruptStateException``), never silently reset."""

    name = "control_registry"
    path = "crashfs://ctrl"

    def _registry(self):
        from deequ_tpu.control.registry import CheckRegistry

        return CheckRegistry(self.path, retry=ONE_SHOT_RETRY)

    @staticmethod
    def _candidate(reg, n: int) -> None:
        reg.register_candidate(
            f"chk_{n}", tenant="t1", column="col_a", rule="CompleteIf",
            code=f"hasCompleteness(col_a, >= 0.{n})",
            description=f"candidate {n}", current_value="1.0",
        )

    def baseline(self) -> None:
        self._candidate(self._registry(), 1)

    def attempt(self) -> None:
        self._candidate(self._registry(), 2)

    def verify(self, inner, seam, cut, length, err) -> None:
        torn_commit = seam == "short_write" and cut < length
        try:
            reg = self._registry()
        except CorruptStateException:
            if torn_commit:
                return  # torn committed state detected typed: contract held
            raise CrashpointViolation(
                self.name, seam, cut,
                "registry state unreadable though the commit rename "
                "never ran",
            )
        if torn_commit:
            raise CrashpointViolation(
                self.name, seam, cut,
                "torn committed registry state loaded without typed error",
            )
        if reg.get("chk_1") is None:
            raise CrashpointViolation(
                self.name, seam, cut, "baseline candidate lost"
            )
        has_new = reg.get("chk_2") is not None
        if has_new != _new_write_expected(seam, cut, length):
            raise CrashpointViolation(
                self.name, seam, cut,
                f"attempted candidate visibility wrong (present={has_new})",
            )


class StreamCheckpointAdapter(_FsStoreAdapter):
    """Streaming checkpoints: atomic + checksummed, with fallback — a
    damaged newest checkpoint is skipped in favor of its predecessor,
    never fatal (worst case the run restarts the interval)."""

    name = "stream_checkpoint"
    path = "crashfs://ckpt"
    fingerprint = "vfsmatrix|fp"

    def _ckpt(self):
        from deequ_tpu.resilience.checkpoint import StreamCheckpointer

        return StreamCheckpointer(self.path, keep=4, retry=ONE_SHOT_RETRY)

    def baseline(self) -> None:
        from deequ_tpu.resilience.checkpoint import StreamCheckpoint

        if not self._ckpt().save(self.fingerprint, StreamCheckpoint(8)):
            raise CrashpointViolation(
                self.name, "baseline", -1,
                "baseline checkpoint save failed on a healthy filesystem",
            )

    def attempt(self) -> None:
        from deequ_tpu.resilience.checkpoint import StreamCheckpoint

        self._ckpt().save(self.fingerprint, StreamCheckpoint(16))

    def verify(self, inner, seam, cut, length, err) -> None:
        got = self._ckpt().load_latest(self.fingerprint)
        if got is None:
            raise CrashpointViolation(
                self.name, seam, cut,
                "no checkpoint recoverable (baseline must survive)",
            )
        want = 16 if _new_write_expected(seam, cut, length) else 8
        if got.batch_index != want:
            raise CrashpointViolation(
                self.name, seam, cut,
                f"resumed from batch {got.batch_index}, expected {want}",
            )


class WindowStateAdapter(_FsStoreAdapter):
    """Window-state store (deequ_tpu/windows/state.py): pane stacks +
    watermark + the exactly-once close fence, atomic + checksummed with
    predecessor fallback — the FIFTH durable store (round 20). The
    matrix asserts the checkpoint posture: a snapshot torn by a crash
    falls back to its predecessor (a resumed stream replays the
    interval and its fence suppresses the replayed closes), and the
    attempted snapshot is visible exactly when the write physically
    completed — a half-visible fence would either re-emit closed
    windows (fence lost) or silently drop them (fence from the torn
    future)."""

    name = "window_state"
    path = "crashfs://wstate"
    fingerprint = "vfsmatrix|window|fp"

    def _store(self):
        from deequ_tpu.windows.state import WindowStateStore

        return WindowStateStore(self.path, keep=4, retry=ONE_SHOT_RETRY)

    @staticmethod
    def _state(batch_index: int):
        from deequ_tpu.windows.state import WindowState

        return WindowState(
            batch_index=batch_index,
            watermark=float(batch_index),
            closed_through=float(batch_index) - 10.0,
            late_rows=batch_index,
            emitted=[float(batch_index) - 10.0],
            panes={float(batch_index): {"0:n": float(batch_index)}},
        )

    def baseline(self) -> None:
        if not self._store().save(self.fingerprint, self._state(8)):
            raise CrashpointViolation(
                self.name, "baseline", -1,
                "baseline window-state save failed on a healthy filesystem",
            )

    def attempt(self) -> None:
        self._store().save(self.fingerprint, self._state(16))

    def verify(self, inner, seam, cut, length, err) -> None:
        got = self._store().load_latest(self.fingerprint)
        if got is None:
            raise CrashpointViolation(
                self.name, seam, cut,
                "no window state recoverable (baseline must survive)",
            )
        want = 16 if _new_write_expected(seam, cut, length) else 8
        if got.batch_index != want:
            raise CrashpointViolation(
                self.name, seam, cut,
                f"resumed window state from batch {got.batch_index}, "
                f"expected {want}",
            )
        if got.closed_through != float(want) - 10.0:
            raise CrashpointViolation(
                self.name, seam, cut,
                f"exactly-once close fence drifted: recovered "
                f"{got.closed_through}, expected {float(want) - 10.0}",
            )


class RequestLedgerAdapter:
    """Request ledger: append-only frames, fsync-per-frame, raw local
    file I/O. Every crash seam leaves the same physical outcome for an
    append — the new frame truncated at some byte — so its matrix
    column is the ``torn_tail`` sweep: the appended frame cut at every
    byte, asserting last-whole-frame recovery, the counter-suffixed
    ``.corrupt`` sidecar, and zero loss of prior records."""

    name = "request_ledger"
    seams: Tuple[str, ...] = ("torn_tail",)

    @staticmethod
    def _accept(led, accept_id: str, epoch: int) -> None:
        led.append_accept(
            accept_id, tenant={"tables": accept_id}, digest=f"d-{accept_id}",
            slo_cls="batch", deadline_ms=None, weight=1.0,
            deadline_left_s=None, work=("data", "checks", "analyzers"),
            epoch=epoch,
        )

    def _materialize(self) -> Tuple[bytes, bytes]:
        """(baseline ledger bytes, the one appended frame's bytes)."""
        from deequ_tpu.serve.ledger import RequestLedger

        with tempfile.TemporaryDirectory() as tmp:
            led = RequestLedger(tmp)
            self._accept(led, "a1", 1)
            self._accept(led, "a2", 1)
            led.append_resolve("a1", epoch=1)
            led.close()
            with open(led.path, "rb") as f:
                base = f.read()
            led2 = RequestLedger(tmp)
            self._accept(led2, "a3", 1)
            led2.close()
            with open(led2.path, "rb") as f:
                frame = f.read()[len(base):]
        return base, frame

    def run_cell(self, base: bytes, frame: bytes, cut: int) -> None:
        from deequ_tpu.serve.ledger import (
            CORRUPT_SUFFIX,
            LEDGER_FILENAME,
            RequestLedger,
        )

        with tempfile.TemporaryDirectory() as tmp:
            led_path = os.path.join(tmp, LEDGER_FILENAME)
            # deequ-lint: ignore[durable-write] -- cell fixture: materializing the intentionally-torn post-crash file under test
            with open(led_path, "wb") as f:
                f.write(base + frame[:cut])
            try:
                led = RequestLedger(tmp, mode="recover")
            except BaseException as e:  # noqa: BLE001 — recovery must not raise
                raise CrashpointViolation(
                    self.name, "torn_tail", cut,
                    f"recovery raised {type(e).__name__}: {e}",
                ) from e
            try:
                live = led.outstanding()
                if "a2" not in live:
                    raise CrashpointViolation(
                        self.name, "torn_tail", cut,
                        "prior outstanding accept lost",
                    )
                if "a1" in live:
                    raise CrashpointViolation(
                        self.name, "torn_tail", cut,
                        "resolved accept resurrected",
                    )
                whole = cut == len(frame)
                if ("a3" in live) != whole:
                    raise CrashpointViolation(
                        self.name, "torn_tail", cut,
                        f"torn-frame visibility wrong (cut={cut}, "
                        f"frame={len(frame)})",
                    )
                sidecar = led.path + CORRUPT_SUFFIX
                if 0 < cut < len(frame):
                    if not os.path.exists(sidecar):
                        raise CrashpointViolation(
                            self.name, "torn_tail", cut,
                            "torn tail not quarantined to sidecar",
                        )
                    if led.torn_tail_bytes != cut:
                        raise CrashpointViolation(
                            self.name, "torn_tail", cut,
                            f"quarantined {led.torn_tail_bytes} bytes, "
                            f"expected {cut}",
                        )
                elif os.path.exists(sidecar):
                    raise CrashpointViolation(
                        self.name, "torn_tail", cut,
                        "clean-boundary recovery produced a sidecar",
                    )
            finally:
                led.close()

    def run_matrix(self, stride: int = 1) -> Dict[str, Any]:
        from deequ_tpu.obs.registry import CRASHPOINTS_SURVIVED

        base, frame = self._materialize()
        cuts = list(range(0, len(frame) + 1, max(int(stride), 1)))
        if cuts[-1] != len(frame):
            cuts.append(len(frame))
        for cut in cuts:
            self.run_cell(base, frame, cut)
            CRASHPOINTS_SURVIVED.inc()
        return {
            "write_len": len(frame),
            "cells": len(cuts),
            "by_seam": {"torn_tail": len(cuts)},
        }


def default_adapters() -> List[Any]:
    return [
        RequestLedgerAdapter(),
        RepositorySegmentAdapter(),
        ControlRegistryAdapter(),
        StreamCheckpointAdapter(),
        WindowStateAdapter(),
    ]


def run_crashpoint_matrix(
    adapters: Optional[List[Any]] = None, stride: int = 1
) -> Dict[str, Any]:
    """Sweep every write seam at every byte boundary (``stride`` > 1
    subsamples the grid for quick runs; the healthy full-length cell is
    always included) across every durable store. Raises
    ``CrashpointViolation`` on the first broken cell; returns a per-
    store summary. Runs with retries disabled (single attempt) so the
    UNretried recovery paths are what is being asserted."""
    adapters = default_adapters() if adapters is None else adapters
    previous = default_retry_policy()
    set_default_retry_policy(ONE_SHOT_RETRY)
    try:
        stores = {a.name: a.run_matrix(stride=stride) for a in adapters}
    finally:
        set_default_retry_policy(previous)
        _mount(None)
    return {
        "stores": stores,
        "cells": sum(s["cells"] for s in stores.values()),
        "survived": sum(s["cells"] for s in stores.values()),
    }
