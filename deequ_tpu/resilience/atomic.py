"""Crash-safe persistence primitives: write-temp-fsync-rename + checksums.

A metrics repository or state file that tears mid-write must never be
half-readable: readers either see the previous complete version (atomic
rename) or detect damage loudly (checksum envelope -> typed
CorruptStateException) instead of surfacing a raw JSON/struct error from
arbitrary garbage. Native engines isolate storage faults the same way
rather than failing the query (Flare, arXiv:1703.08219).

The checksum envelope is ``DQX1 | crc32(u32) | length(i64) | payload``;
``has_checksum`` distinguishes enveloped files from legacy raw payloads so
pre-resilience files keep loading.
"""

from __future__ import annotations

import itertools
import os
import struct
import zlib
from typing import Optional

from deequ_tpu.exceptions import CorruptStateException

CHECKSUM_MAGIC = b"DQX1"

_u32 = struct.Struct("<I")
_i64 = struct.Struct("<q")

# process-unique temp suffixes: pid guards cross-process collisions, the
# counter guards same-process concurrent writers on one path
_tmp_counter = itertools.count()


def wrap_checksum(payload: bytes) -> bytes:
    """payload -> checksummed envelope bytes."""
    return (
        CHECKSUM_MAGIC
        + _u32.pack(zlib.crc32(payload) & 0xFFFFFFFF)
        + _i64.pack(len(payload))
        + payload
    )


def has_checksum(data: bytes) -> bool:
    return data[:4] == CHECKSUM_MAGIC


def unwrap_checksum(data: bytes, what: str) -> bytes:
    """Envelope bytes -> payload; CorruptStateException on any damage
    (bad magic, truncation, crc mismatch)."""
    if not has_checksum(data):
        raise CorruptStateException(what, "missing checksum envelope")
    if len(data) < 16:
        raise CorruptStateException(what, "truncated envelope header")
    (crc,) = _u32.unpack_from(data, 4)
    (length,) = _i64.unpack_from(data, 8)
    payload = data[16:]
    if len(payload) != length:
        raise CorruptStateException(
            what, f"torn write: expected {length} payload bytes, "
            f"found {len(payload)}"
        )
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise CorruptStateException(what, "checksum mismatch")
    return payload


def _fsync_if_possible(handle) -> None:
    """Flush + fsync ``handle`` before the commit rename. A handle that
    exposes its own ``fsync()`` (fault-injection seams, remote-store
    writers) routes through it, and its failures PROPAGATE — as do
    real-fd ``os.fsync``/``flush`` failures (ENOSPC surfaces at flush, a
    lying fsync at fsync): a durability fault must fail the write typed
    while the rename is still unreached, so the destination keeps its
    previous complete version. Only handles with no fd at all
    (in-memory / object-store writers) skip the fsync — rename still
    gives all-or-nothing visibility there."""
    fsync_hook = getattr(handle, "fsync", None)
    if callable(fsync_hook):
        handle.flush()
        fsync_hook()
        return
    try:
        fd = handle.fileno()
    except (AttributeError, ValueError, OSError):
        try:
            handle.flush()
        except (AttributeError, ValueError):
            pass  # in-memory / object-store handles have no fd; rename
            # still gives all-or-nothing visibility there
        return
    handle.flush()
    os.fsync(fd)  # deequ-lint: ignore[durable-write] -- this IS the shared helper's fsync leg; every durable writer routes here


def quarantine_path(fs, path: str, suffix: str = ".corrupt") -> str:
    """First unused quarantine-sidecar name for ``path``: ``path +
    suffix``, then ``.corrupt.1``, ``.corrupt.2``, … Recovery code
    moves damaged bytes aside as forensic evidence; a SECOND torn-write
    recovery in the same directory must never overwrite the first
    sidecar (``os.replace`` clobbers silently). Pass ``fs=None`` for
    raw-``os`` callers (the request ledger's append path)."""
    exists = os.path.exists if fs is None else fs.exists
    candidate = path + suffix
    n = 0
    while exists(candidate):
        n += 1
        candidate = f"{path}{suffix}.{n}"
    return candidate


def atomic_write_bytes(
    fs, path: str, data: bytes, retry=None, what: Optional[str] = None
) -> None:
    """Write ``data`` to ``path`` via temp-file + fsync + rename on the
    given FileSystem: concurrent/crashed readers see either the old
    complete file or the new complete file, never a prefix. Runs under
    ``retry`` (a RetryPolicy, or the process default when None)."""
    from deequ_tpu.resilience.retry import retry_call

    what = what or f"write {path}"
    tmp = f"{path}.tmp.{os.getpid()}.{next(_tmp_counter)}"

    def attempt() -> None:
        # deequ-lint: ignore[durable-write] -- this IS the shared helper: the temp-file write the commit rename below makes atomic
        with fs.open(tmp, "wb") as f:
            f.write(data)
            _fsync_if_possible(f)
        fs.rename(tmp, path)

    try:
        retry_call(attempt, retry, what=what)
    except BaseException:
        try:
            fs.delete(tmp)
        # deequ-lint: ignore[bare-except] -- best-effort tmp-file cleanup after the durable write already succeeded/failed typed
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass
        raise


def atomic_write_text(fs, path: str, text: str, retry=None) -> None:
    atomic_write_bytes(fs, path, text.encode("utf-8"), retry=retry)


def read_checksummed(fs, path: str, what: str, retry=None) -> bytes:
    """Read + validate a checksummed file; legacy files (no envelope)
    return their raw bytes unchanged."""
    from deequ_tpu.resilience.retry import retry_call

    def attempt() -> bytes:
        with fs.open(path, "rb") as f:
            return f.read()

    data = retry_call(attempt, retry, what=f"read {path}")
    if has_checksum(data):
        return unwrap_checksum(data, what)
    return data
