"""Deterministic chaos engine — seeded fault schedules + invariant oracles.

The fault ladder is six rungs deep (I/O retry -> quarantine -> OOM bisect
-> encoded demote -> mesh reshard -> CPU fallback) and, until now, every
injector seam was exercised one at a time by hand-written tests. The only
credible way to trust the ladder under COMBINED faults is to fuzz it:
generate a seeded, replayable :class:`ChaosSchedule` that scripts every
existing injector seam into one timeline —

- ``scan``  — device faults at the scan engine's execute seam
  (``FaultInjectingScanHook``: oom / compile / lost / hang, optionally
  pinned to one mesh member the way per-chip XLA failures name chips);
- ``batch`` — transient/permanent batch-read faults
  (``FlakyBatchSource`` + ``FaultSchedule``);
- ``staging`` — slow reads stalling the ingest/staging pipeline
  (``FaultSchedule.delay_seconds``);
- ``fs``    — seeded I/O faults on the checkpoint filesystem
  (``FaultInjectingFileSystem``; the schedule's fs event also switches
  the run to checkpointed mode so the persistence seam is in play);
- ``worker`` — fleet-tier worker faults (round 12): scripted
  death / stall / rejoin of serving workers in a
  :class:`~deequ_tpu.serve.fleet.VerificationFleet`. A schedule with
  any worker event runs the FLEET scenario instead of the streaming
  one: the same batch partition becomes per-tenant suites submitted in
  waves to a 4-worker fleet, with the events applied between waves.
  Two PROCESS-fleet kinds ride the same seam (round 17): ``kill9``
  (a REAL ``kill -9`` on a worker process of a ledger-backed
  :class:`~deequ_tpu.serve.pfleet.ProcessFleet` — loss surfaces as
  transport EOF, failover must re-dispatch bit-identically) and
  ``coord_kill9`` (the COORDINATOR dies mid-wave and a fresh one
  resumes off the durable request ledger, onto the original futures).
  A third kind, ``partition`` (round 18), is the SPLIT-BRAIN seam:
  the coordinator is stalled-not-dead — a fresh coordinator resumes
  off the ledger while the old incarnation stays alive with live
  workers, then wakes mid-resume and tries to keep serving. The epoch
  fence (serve/lease.py) must refuse every zombie dispatch typed
  (``StaleEpochException``), with zero double-resolutions and the
  completed results bit-identical. Any schedule with those kinds runs
  the process-fleet scenario;
- ``load``  — overload faults (round 15, the admission tier): scripted
  OPEN-LOOP SPIKES (a flood tenant bursts tight-deadline best_effort
  submissions mid-wave, no pacing) and SLOW-TENANT stalls (the worker
  a tenant routes to wedges briefly — queue depth builds, deadlines
  expire) over the same 4-worker fleet scenario, with every wave
  submission carrying a real SLO class (t0 critical, t1/t2 standard,
  t3 best_effort). No worker dies: the seam fuzzes admission control,
  the deadline-aware fair queue, and the brownout ladder, not
  failover;
- ``window`` — continuous-verification faults (round 20, the windowed
  streaming tier, deequ_tpu/windows): scripted LATE BURSTS (a slab of
  a batch's rows rewound behind the stream's watermark — the typed
  late-routing seam), DISORDER SPIKES (event-time jitter inside a
  batch), KILLS mid-window (the stream objects are dropped and
  resumed from the checksummed window-state store, replaying the
  checkpoint interval), RESUME REPLAYS (a DOUBLE kill-and-resume —
  the same closes replay twice through the exactly-once fence) and
  OVERLOAD spikes (the hub's brownout level rises, demoting late
  closes of non-critical streams to typed ``window_shed`` records).
  A schedule with any window event runs the STREAM scenario: three
  SLO-classed windowed streams (critical / standard / best_effort)
  folding seeded event-time batches, checked against a fault-free
  windowed reference over the SAME (late-burst/disorder-modified)
  batch timeline —

run one governed verification under it (``on_batch_error="skip"``,
``on_device_error="fallback"``, a `RunPolicy` budget), and then check the
system's OWN cross-cutting invariants as oracles:

1. typed outcome — the run returns a result or raises from the
   MetricCalculationException taxonomy; never a raw error;
2. termination — wall clock bounded by ``run_deadline`` (+ slack for
   host overhead);
3. bit-identity-or-degraded — every successful metric equals, bit for
   bit, the fault-free reference over exactly the rows the result claims
   verified (full table, or total minus quarantined batches minus
   ``unverified_row_ranges``); failure metrics must be typed;
4. row accounting — unverified ranges well-formed, batch-aligned, and
   disjoint from quarantined batches;
5. fetch contract — device fetches never exceed scan passes (the PR-4
   one-fetch discipline under the fault ladder);
6. HBM ledger — ``total_resident_bytes()`` returns to zero;
7. ledger consistency — quarantined batches all trace to injected
   faults; the run budget's total equals the sum of its per-rung
   charges; its ``io_retry`` charges equal the run's retry-telemetry
   attempts;
8. exactly-once futures (worker seam) — every future the fleet accepted
   resolves exactly once (a result or a typed error): none orphaned by
   a dead worker, none double-resolved by a stalled worker waking after
   its requests failed over (``VerificationFuture.resolve_count``);
9. exactly-once under overload (load seam) — every future the fleet
   ACCEPTED (admission refusals raise typed at submit and mint no
   future) still resolves exactly once, where an in-queue deadline
   SHED — a typed ``DeadlineExceededException`` on the original
   future — counts as a resolution: overload may change a request's
   outcome, never orphan or double-resolve it;
10. no priority inversion (load seam) — no ``critical`` request is
   shed while a same-plan ``best_effort`` request DISPATCHED on the
   same worker: a best_effort that resolved successfully before a
   co-queued critical's shed popped while that critical still waited,
   which the class-tiered queue's strict priority forbids;
11. exactly-once window closes (window seam) — every window the
   fault-free reference closes is, in the chaos run, emitted EXACTLY
   once (bit-identical metrics, kills/replays included) or shed TYPED
   (non-critical streams, only under a scripted overload spike);
   nothing emits twice through any number of kill-and-resume cycles,
   the critical stream's close set never shrinks, watermarks never
   regress, and a scripted late burst shows up in the typed late
   ledgers (dropped counts / quarantined side-output ranges), never
   in a closed window's rows.

Worker-seam schedules check oracles 1/2/3/5/8 (the streaming-specific
row-accounting and fetch/ledger oracles have no fleet analogue — a
tenant's suite either completes bit-identically after failover or
rejects typed); load-seam schedules check 1/2/3/9/10; window-seam
schedules check 1/2 plus oracle 11.

A failing schedule is reduced by :func:`shrink_schedule` — classic
delta debugging (ddmin) over the event list, re-running the oracles per
candidate — to a minimal reproducer serializable as a JSON fixture
(``tests/fixtures/chaos/``) that tier-1 replays bit-identically.
``simulate_drift=True`` deliberately perturbs the results of a faulted
run (a stand-in for a ladder bug that breaks recovery bit-identity), so
the oracle->shrink loop itself is testable end to end.

CLI::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m deequ_tpu.resilience.chaos --soak --n 200

runs N seeded schedules and exits nonzero on any oracle violation.
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from random import Random
from typing import Callable, Dict, List, Optional, Tuple

#: scenario geometry — small enough that one schedule runs in ~a second
#: on the 8-virtual-device CPU mesh, large enough for several batches
N_ROWS = 1600
BATCH_ROWS = 400
TABLE_SEED = 11

#: injected hangs sleep this long, then RAISE (hang_release="error") —
#: self-terminating, so chaos runs need no per-call device watchdog. A
#: tight per-call deadline on this loaded CPU emulation fires spuriously
#: on healthy 8-device dispatches, and the abandoned worker then
#: deadlocks the shared collective thread pool against the next dispatch
#: (a CPU-backend artifact; disjoint device sets run independently on
#: real hardware). Termination is still bounded: the run budget's
#: attempt-level watchdog covers genuinely-stuck attempts.
HANG_SECONDS = 0.6

#: wall-clock slack the termination oracle grants over run_deadline
#: (host-side packing/trace work is not budget-preemptible)
TERMINATION_SLACK = 2.0

_SCAN_KINDS = ("oom", "compile", "lost", "hang")
_SEAMS = ("scan", "batch", "staging", "fs", "worker", "load", "window")

#: fleet scenario geometry (worker seam): the scenario table splits into
#: one slice per tenant, each submitted once per wave; worker events
#: apply WHILE a wave is in flight (submitted, not yet gathered), so
#: "mid-load" is scripted, not racy. Slice sizes are deliberately
#: UNEQUAL: the fleet routes by (schema, analyzers, rows), and equal
#: slices would share one digest — every tenant on one worker, the
#: other three untouchable by any schedule.
FLEET_N_WORKERS = 4
FLEET_WAVES = 3
FLEET_TENANT_ROWS = (250, 350, 450, 550)  # sums to N_ROWS
_WORKER_KINDS = ("death", "stall", "rejoin")

#: process-fleet scenario (round 17, kill -9 seam): fewer waves than
#: the in-process fleet — every worker is a real spawned process
#: (fork + import + per-process compiles), so each wave costs real
#: wall-clock; the scripted kills are the expensive part being tested
PFLEET_WAVES = 2
#: worker-seam kinds that select the PROCESS-fleet scenario
_PWORKER_KINDS = ("kill9", "rejoin", "coord_kill9", "partition")
_PWORKER_ONLY_KINDS = ("kill9", "coord_kill9", "partition")

#: fleet membership knobs for the scenario: a heartbeat probe every
#: 50ms, a worker declared lost after 0.3s of silence
FLEET_HEARTBEAT = 0.05
FLEET_STALL_TIMEOUT = 0.3

#: scripted worker stalls wedge the worker thread this long — longer
#: than FLEET_STALL_TIMEOUT, so membership declares the worker lost and
#: failover runs while it sleeps; when it wakes, its late resolutions
#: are dropped (oracle 8 watches the count)
WORKER_STALL_SECONDS = 0.8

#: load-seam (round 15) scenario geometry: the same 4-tenant slices,
#: each wave submission carrying an SLO class — t0 is the critical
#: tenant (generous deadline: it must survive anything the seam
#: scripts), t1/t2 standard, t3 best_effort with a deadline tight
#: enough that scripted stalls expire it in-queue
LOAD_TENANT_SLO = (
    ("critical", 20_000.0),
    ("standard", 10_000.0),
    ("standard", 10_000.0),
    ("best_effort", 1_500.0),
)
_LOAD_KINDS = ("spike", "slow_tenant")
#: spike submissions (the flood tenant's open-loop burst) are
#: best_effort with a deadline this tight — under the stall-built queue
#: most of a burst expires pre-dispatch, which is the point
LOAD_SPIKE_DEADLINE_MS = 500.0
#: per-worker queue bound for the load scenario: small enough that a
#: scripted burst reaches admission pressure (class budgets, brownout)
LOAD_MAX_PENDING = 24

#: window-seam (round 20) scenario geometry: three SLO-classed windowed
#: streams over seeded event-time batches — tumbling 10s windows,
#: watermark lag 2s, batches spanning 5s of event time each. The
#: best_effort deadline is tight enough that ordinary close lateness
#: (up to ~one batch span + lag) sheds it under a scripted overload
#: spike; standard sheds only on the latest closes; critical never
#: sheds by class. The standard stream runs the side_output late
#: policy so a late burst exercises the quarantine route too.
WINDOW_N_BATCHES = 12
WINDOW_BATCH_ROWS = 24
WINDOW_BATCH_SPAN_S = 5.0
WINDOW_SIZE_S = 10.0
WINDOW_LAG_S = 2.0
WINDOW_STREAM_SLO = (
    ("w_crit", "critical", 20_000.0, "drop"),
    ("w_std", "standard", 4_000.0, "side_output"),
    ("w_be", "best_effort", 400.0, "drop"),
)
_WINDOW_KINDS = (
    "late_burst", "disorder_spike", "kill", "resume_replay", "overload",
)


def _fast_retry():
    from deequ_tpu.resilience.retry import RetryPolicy

    return RetryPolicy(max_attempts=3, base_delay=0.0005, max_delay=0.002)


# -- schedule ----------------------------------------------------------------


@dataclass(frozen=True)
class ChaosSchedule:
    """One seeded, serializable fault timeline over the fixed scenario.

    ``events`` is a list of plain dicts (see the module docstring's seam
    catalog) — the unit the shrinker removes. Two runs of the same
    schedule inject the identical fault pattern (``FaultSchedule`` /
    ``FaultInjectingScanHook`` are pure functions of (seed, operation
    sequence)), which is what makes shrunk reproducers replayable."""

    seed: int
    events: Tuple[dict, ...] = ()
    run_deadline: float = 20.0
    max_total_attempts: int = 12
    on_budget_exhausted: str = "degrade"

    @property
    def n_batches(self) -> int:
        return (N_ROWS + BATCH_ROWS - 1) // BATCH_ROWS

    def with_events(self, events) -> "ChaosSchedule":
        return ChaosSchedule(
            seed=self.seed,
            events=tuple(dict(e) for e in events),
            run_deadline=self.run_deadline,
            max_total_attempts=self.max_total_attempts,
            on_budget_exhausted=self.on_budget_exhausted,
        )

    # -- (de)serialization — the fixture format --------------------------

    def to_dict(self) -> dict:
        return {
            "seed": self.seed,
            "events": [dict(e) for e in self.events],
            "run_deadline": self.run_deadline,
            "max_total_attempts": self.max_total_attempts,
            "on_budget_exhausted": self.on_budget_exhausted,
        }

    def to_json(self) -> str:
        # math.inf serializes as the JSON extension literal Infinity,
        # which json.loads round-trips — permanent faults survive disk
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @staticmethod
    def from_dict(raw: dict) -> "ChaosSchedule":
        return ChaosSchedule(
            seed=int(raw["seed"]),
            events=tuple(dict(e) for e in raw.get("events", ())),
            run_deadline=float(raw.get("run_deadline", 20.0)),
            max_total_attempts=int(raw.get("max_total_attempts", 12)),
            on_budget_exhausted=str(
                raw.get("on_budget_exhausted", "degrade")
            ),
        )

    @staticmethod
    def from_json(text: str) -> "ChaosSchedule":
        return ChaosSchedule.from_dict(json.loads(text))

    # -- generation ------------------------------------------------------

    @staticmethod
    def generate(seed: int) -> "ChaosSchedule":
        """Seeded schedule: 1-4 events drawn across the four seams, a
        run budget sized so that most schedules complete but heavy ones
        exhaust it (both outcomes are oracle-checked)."""
        from deequ_tpu.resilience.faults import FaultSchedule

        rng = Random(seed)
        n_batches = (N_ROWS + BATCH_ROWS - 1) // BATCH_ROWS
        events: List[dict] = []
        for _ in range(1 + rng.randrange(3)):
            seam = rng.choice(("scan", "scan", "batch", "batch", "fs"))
            if seam == "scan":
                kind = rng.choice(_SCAN_KINDS)
                times = (
                    FaultSchedule.PERMANENT
                    if rng.random() < 0.15
                    else 1 + rng.randrange(3)
                )
                device = (
                    rng.randrange(8) if rng.random() < 0.3 else None
                )
                events.append(
                    {
                        "seam": "scan",
                        "scan": rng.randrange(n_batches),
                        "kind": kind,
                        "times": times,
                        "device": device,
                    }
                )
            elif seam == "batch":
                times = (
                    FaultSchedule.PERMANENT
                    if rng.random() < 0.25
                    else 1 + rng.randrange(2)
                )
                events.append(
                    {
                        "seam": "batch",
                        "index": rng.randrange(n_batches),
                        "times": times,
                    }
                )
            else:
                events.append(
                    {"seam": "fs", "rate": round(0.05 + rng.random() * 0.1, 3)}
                )
        if rng.random() < 0.25:
            events.append(
                {
                    "seam": "staging",
                    "seconds": round(0.002 + rng.random() * 0.01, 4),
                    "rate": round(0.2 + rng.random() * 0.5, 3),
                }
            )
        return ChaosSchedule(
            seed=seed,
            events=tuple(events),
            run_deadline=20.0,
            max_total_attempts=6 + rng.randrange(9),
            on_budget_exhausted=(
                "raise" if rng.random() < 0.15 else "degrade"
            ),
        )

    @staticmethod
    def generate_worker(seed: int) -> "ChaosSchedule":
        """Seeded WORKER-seam schedule (the fleet scenario): scripted
        death / stall / rejoin events over the waves. Events are drawn
        in wave order (application order), tracking which workers are
        down so rejoins target actually-dead workers and at least one
        survivor always remains — a zero-survivor fleet is a separate
        typed-error path pinned by the fleet tests, not a fuzz target
        (every schedule here must have somewhere to fail over TO)."""
        rng = Random(seed)
        events: List[dict] = []
        down: set = set()
        for wave in range(FLEET_WAVES):
            if rng.random() >= 0.7 and events:
                continue
            up = [w for w in range(FLEET_N_WORKERS) if w not in down]
            kinds = []
            if len(up) > 1:
                # death and stall both retire the worker (a scripted
                # stall outlasts the membership timeout by design)
                kinds += ["death", "death", "stall"]
            if down:
                kinds += ["rejoin", "rejoin"]
            if not kinds:
                continue
            kind = rng.choice(kinds)
            if kind == "rejoin":
                worker = rng.choice(sorted(down))
                down.discard(worker)
            else:
                worker = rng.choice(up)
                down.add(worker)
            events.append(
                {
                    "seam": "worker",
                    "kind": kind,
                    "worker": worker,
                    "wave": wave,
                }
            )
        if not events:
            events.append(
                {"seam": "worker", "kind": "death",
                 "worker": rng.randrange(FLEET_N_WORKERS), "wave": 1}
            )
        # generous deadline: the fleet scenario pays per-worker program
        # compiles (4 distinct tenant shapes) before steady state
        return ChaosSchedule(
            seed=seed, events=tuple(events), run_deadline=30.0,
        )

    @staticmethod
    def generate_pworker(seed: int) -> "ChaosSchedule":
        """Seeded PROCESS-fleet schedule (the kill -9 seam): scripted
        ``kill9`` (real SIGKILL on a worker process), ``rejoin``, and
        at most one COORDINATOR event over the waves — ``coord_kill9``
        (coordinator death + ledger-backed resume) or ``partition``
        (split brain: the old coordinator survives as a zombie and
        must be epoch-fenced). Same survivor discipline as
        :meth:`generate_worker` — every schedule must leave somewhere
        to fail over TO. A coordinator event resets the down-set: the
        resumed coordinator spawns a full fresh fleet."""
        rng = Random(seed)
        events: List[dict] = []
        down: set = set()
        used_coord = False
        for wave in range(PFLEET_WAVES):
            if events and rng.random() < 0.5:
                continue
            up = [w for w in range(FLEET_N_WORKERS) if w not in down]
            kinds: List[str] = []
            if len(up) > 1:
                kinds += ["kill9", "kill9"]
            if down:
                kinds += ["rejoin"]
            if not used_coord:
                kinds += ["coord_kill9", "partition"]
            if not kinds:
                continue
            kind = rng.choice(kinds)
            if kind in ("coord_kill9", "partition"):
                used_coord = True
                down = set()
                events.append(
                    {"seam": "worker", "kind": kind, "wave": wave}
                )
                continue
            if kind == "rejoin":
                worker = rng.choice(sorted(down))
                down.discard(worker)
            else:
                worker = rng.choice(up)
                down.add(worker)
            events.append(
                {"seam": "worker", "kind": kind, "worker": worker,
                 "wave": wave}
            )
        if not events:
            events.append(
                {"seam": "worker", "kind": "kill9",
                 "worker": rng.randrange(FLEET_N_WORKERS),
                 "wave": PFLEET_WAVES - 1}
            )
        # process spawns + per-process compiles dominate the wall clock
        return ChaosSchedule(
            seed=seed, events=tuple(events), run_deadline=90.0,
        )

    @staticmethod
    def generate_load(seed: int) -> "ChaosSchedule":
        """Seeded LOAD-seam schedule (round 15): scripted open-loop
        spikes and slow-tenant stalls over the SLO-classed fleet
        scenario. Spikes name the tenant whose table floods (sharing
        its routing digest — so a spike on t0 co-queues best_effort
        floods with critical wave traffic, exactly what the
        no-priority-inversion oracle watches); slow_tenant events
        wedge the named tenant's placed worker briefly (queue depth
        builds, deadlines expire — load, not death: membership stays
        off and nothing fails over)."""
        rng = Random(seed)
        events: List[dict] = []
        for wave in range(FLEET_WAVES):
            if events and rng.random() < 0.3:
                continue
            tenant = rng.randrange(len(FLEET_TENANT_ROWS))
            roll = rng.random()
            if roll < 0.6:
                # a stall first: the wedge is what turns a burst into
                # queue depth (an unwedged CPU worker drains a spike
                # before any deadline can expire)
                events.append({
                    "seam": "load", "kind": "slow_tenant", "wave": wave,
                    "tenant": tenant,
                    "seconds": round(0.3 + rng.random() * 0.5, 3),
                })
            if roll >= 0.3:
                events.append({
                    "seam": "load", "kind": "spike", "wave": wave,
                    "tenant": tenant,
                    "burst": 6 + rng.randrange(12),
                })
        if not events:
            events.append({
                "seam": "load", "kind": "spike", "wave": 1,
                "tenant": rng.randrange(len(FLEET_TENANT_ROWS)),
                "burst": 8,
            })
        return ChaosSchedule(
            seed=seed, events=tuple(events), run_deadline=30.0,
        )

    @staticmethod
    def generate_window(seed: int) -> "ChaosSchedule":
        """Seeded WINDOW-seam schedule (round 20, the continuous
        windowed-verification tier): scripted late bursts, disorder
        spikes, mid-window kills (resume from the window-state store),
        resume replays (a DOUBLE kill — the same closes replay twice
        through the exactly-once fence) and overload spikes over the
        three-stream scenario. Data events (late_burst/disorder) draw
        batches >= 2 so the stream's watermark has actually advanced —
        a burst into a fresh stream is not late at all. At most one
        overload spike per schedule (the shed oracle wants an
        unambiguous window of legitimacy)."""
        rng = Random(seed)
        events: List[dict] = []
        used_overload = False
        for _ in range(1 + rng.randrange(3)):
            kind = rng.choice(
                ("late_burst", "late_burst", "disorder_spike", "kill",
                 "kill", "resume_replay", "overload")
            )
            if kind == "late_burst":
                events.append({
                    "seam": "window", "kind": "late_burst",
                    "batch": 2 + rng.randrange(WINDOW_N_BATCHES - 2),
                    "stream": rng.choice(
                        [s for s, _c, _d, _p in WINDOW_STREAM_SLO]
                    ),
                    "rows": 4 + rng.randrange(8),
                    "rewind_s": round(12.0 + rng.random() * 10.0, 3),
                })
            elif kind == "disorder_spike":
                events.append({
                    "seam": "window", "kind": "disorder_spike",
                    "batch": 2 + rng.randrange(WINDOW_N_BATCHES - 2),
                    "stream": rng.choice(
                        [s for s, _c, _d, _p in WINDOW_STREAM_SLO]
                    ),
                    "jitter_s": round(1.0 + rng.random() * 4.0, 3),
                })
            elif kind in ("kill", "resume_replay"):
                events.append({
                    "seam": "window", "kind": kind,
                    "batch": 1 + rng.randrange(WINDOW_N_BATCHES - 1),
                })
            elif not used_overload:
                used_overload = True
                events.append({
                    "seam": "window", "kind": "overload",
                    "batch": 1 + rng.randrange(WINDOW_N_BATCHES - 2),
                    "level": 1 + rng.randrange(2),
                    "batches": 2 + rng.randrange(4),
                })
        if not events:
            events.append({
                "seam": "window", "kind": "kill",
                "batch": WINDOW_N_BATCHES // 2,
            })
        return ChaosSchedule(
            seed=seed, events=tuple(events), run_deadline=30.0,
        )


# -- scenario ----------------------------------------------------------------


def _build_table():
    """Deterministic scenario table. Values are INTEGER-valued floats so
    every fold sum is exact in f64 regardless of merge order — the
    bit-identity oracle then holds across any chunking/bisection path
    the ladder takes."""
    import numpy as np

    from deequ_tpu.data.table import Column, ColumnarTable, DType

    rng = np.random.default_rng(TABLE_SEED)
    n = N_ROWS
    val = rng.integers(0, 1000, n).astype(np.float64)
    val_mask = np.ones(n, dtype=np.bool_)
    val_mask[rng.integers(0, n, n // 50)] = False
    cat = rng.integers(0, 8, n)
    return ColumnarTable(
        [
            Column(
                "id", DType.INTEGRAL,
                values=np.arange(n, dtype=np.int64),
                mask=np.ones(n, dtype=np.bool_),
            ),
            Column("val", DType.FRACTIONAL, values=val, mask=val_mask),
            Column(
                "cat", DType.INTEGRAL, values=cat,
                mask=np.ones(n, dtype=np.bool_),
            ),
        ]
    )


def _analyzers():
    """Analyzers whose fold algebra is EXACTLY associative on this
    integer-valued table (sums below 2^53, min/max, HLL register max):
    bit-identity then holds across ANY chunking/bisection/reshard path
    the ladder takes, which is what oracle 3 asserts. Welford-moment
    analyzers (StandardDeviation's (n, avg, m2) merge) are deliberately
    excluded — their merge is partition-sensitive at ulp scale by
    design (docs/numerics.md), so they cannot promise bit-identity
    across a bisected re-chunk and would fuzz the oracle."""
    from deequ_tpu.analyzers import (
        ApproxCountDistinct,
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Size,
        Sum,
    )

    return [
        Size(),
        Completeness("val"),
        Mean("val"),
        Minimum("val"),
        Maximum("val"),
        ApproxCountDistinct("cat"),
        Sum("cat"),
    ]


def _check():
    from deequ_tpu.checks import Check, CheckLevel

    return Check(CheckLevel.ERROR, "chaos scenario").has_size(
        lambda s: s >= 0
    )


def _batch_slices(table, indices):
    """The scenario's batch partition: batch i = rows
    [i*BATCH_ROWS, min((i+1)*BATCH_ROWS, N_ROWS))."""
    import numpy as np

    out = []
    for i in indices:
        lo, hi = i * BATCH_ROWS, min((i + 1) * BATCH_ROWS, N_ROWS)
        idx = np.arange(lo, hi)
        out.append(
            type(table)([table[c].take(idx) for c in table.column_names])
        )
    return out


def _metric_rows(result) -> Dict[str, tuple]:
    """str(analyzer) -> ("ok", float) | ("fail", ExceptionTypeName)."""
    out = {}
    for analyzer, metric in result.metrics.items():
        if metric.value.is_success:
            out[str(analyzer)] = ("ok", metric.value.get())
        else:
            out[str(analyzer)] = (
                "fail", type(metric.value.exception).__name__,
            )
    return out


#: fault-free reference metrics per batch subset: the reference is a
#: pure, deterministic function of the fixed scenario and the batch
#: indices it covers (replay-determinism is separately asserted by the
#: fixture corpus), so a 200-schedule soak computes each distinct
#: partition once instead of once per schedule
_REF_CACHE: Dict[Tuple[int, ...], Dict[str, tuple]] = {}


def _reference_metrics(batches, num_rows, cache_key=None) -> Dict[str, tuple]:
    """Fault-free metrics over exactly ``batches`` through the SAME
    resilient per-batch pipeline the chaos run uses, so fold order — and
    therefore bits — match. Runs inside its own fault_state_scope;
    memoized per ``cache_key`` (the covered batch indices)."""
    from deequ_tpu.data.source import GeneratorBatchSource
    from deequ_tpu.data.streaming import StreamingTable
    from deequ_tpu.resilience.governance import fault_state_scope
    from deequ_tpu.verification import VerificationSuite

    if cache_key is not None and cache_key in _REF_CACHE:
        return _REF_CACHE[cache_key]
    if not batches:
        return {}
    schema = batches[0].schema
    source = GeneratorBatchSource(
        schema, lambda: iter(list(batches)), num_rows=num_rows
    )
    with fault_state_scope():
        result = VerificationSuite.do_verification_run(
            StreamingTable(source),
            [_check()],
            _analyzers(),
            on_batch_error="skip",
            on_device_error="fallback",
            retry_policy=_fast_retry(),
        )
    out = _metric_rows(result)
    if cache_key is not None:
        _REF_CACHE[cache_key] = out
    return out


# -- the run -----------------------------------------------------------------


@dataclass
class ChaosReport:
    """One schedule's run + oracle verdicts."""

    schedule: ChaosSchedule
    outcome: str  # "identical" | "degraded" | "exception:<Type>"
    violations: List[str] = field(default_factory=list)
    elapsed: float = 0.0
    metrics: Dict[str, tuple] = field(default_factory=dict)
    skipped: List[int] = field(default_factory=list)
    unverified: List[tuple] = field(default_factory=list)
    run_budget: dict = field(default_factory=dict)
    retry_stats: dict = field(default_factory=dict)
    scan_delta: dict = field(default_factory=dict)
    #: failed-I/O-try delta read THROUGH the unified obs registry
    #: (deequ_tpu/obs/registry — the read-through "retry" section):
    #: oracle 7 compares the budget's io_retry charges against this,
    #: proving the round-11 unification didn't fork the counters
    retry_observed: Optional[int] = None
    injected: List[tuple] = field(default_factory=list)
    resident_after: int = 0
    drifted: bool = False
    #: worker-seam (fleet scenario) future accounting — oracle 8's
    #: evidence: accepted / resolved-exactly-once / orphaned /
    #: multi-resolved counts plus the dropped late resolutions
    fleet: Dict[str, int] = field(default_factory=dict)
    #: load-seam per-future records (oracle 9/10's evidence): one dict
    #: per ACCEPTED submission — wave, tenant, SLO class, the worker it
    #: actually landed on, submit/resolve stamps, and the outcome
    #: ("ok" | "shed" | "fail:<Type>")
    load_records: List[dict] = field(default_factory=list)
    #: window-seam per-close records (oracle 11's evidence): one dict
    #: per window CLOSE observed across every resume — stream, SLO
    #: class, [start, end), and the outcome
    #: ("emitted" | "suppressed" | "shed")
    windows: List[dict] = field(default_factory=list)

    @property
    def failing(self) -> bool:
        return bool(self.violations)


def _install_fs_events(events, seed):
    """Register the ``chaosfs://`` scheme backed by a fault-injecting
    in-memory filesystem when the schedule has fs events. Returns
    (checkpoint_path, fs_schedule, restore_fn)."""
    from deequ_tpu.data.fs import _REGISTRY, register_filesystem
    from deequ_tpu.data.fs import InMemoryFileSystem
    from deequ_tpu.resilience.faults import (
        FaultInjectingFileSystem,
        FaultSchedule,
    )

    rates = [e["rate"] for e in events if e.get("seam") == "fs"]
    if not rates:
        return None, None, lambda: None
    fs_schedule = FaultSchedule(seed=seed, error_rate=max(rates))
    fs = FaultInjectingFileSystem(InMemoryFileSystem(), fs_schedule)
    prev = _REGISTRY.get("chaosfs")
    register_filesystem("chaosfs", lambda path: fs)

    def restore():
        if prev is None:
            _REGISTRY.pop("chaosfs", None)
        else:
            _REGISTRY["chaosfs"] = prev

    return "chaosfs://chaos/ck", fs_schedule, restore


def run_schedule(
    schedule: ChaosSchedule, simulate_drift: bool = False
) -> ChaosReport:
    """Run one schedule end to end: fault-free reference, chaos run under
    the composed injectors + run budget, then every invariant oracle.
    A schedule with any ``worker`` event runs the FLEET scenario
    (:func:`_run_worker_schedule`) instead of the streaming one.

    ``simulate_drift=True`` is the deliberately-broken-ladder mode: when
    any fault was injected, the run's successful metrics are perturbed
    by one ulp-scale epsilon before oracle checking — simulating a
    recovery path that silently loses bit-identity — so the oracles (and
    the shrinker on top of them) can be shown to catch a real ladder
    regression."""
    if any(e.get("seam") == "window" for e in schedule.events):
        return _run_window_schedule(
            schedule, simulate_drift=simulate_drift
        )
    if any(e.get("seam") == "load" for e in schedule.events):
        return _run_load_schedule(schedule, simulate_drift=simulate_drift)
    if any(
        e.get("seam") == "worker"
        and e.get("kind") in _PWORKER_ONLY_KINDS
        for e in schedule.events
    ):
        return _run_pworker_schedule(
            schedule, simulate_drift=simulate_drift
        )
    if any(e.get("seam") == "worker" for e in schedule.events):
        return _run_worker_schedule(schedule, simulate_drift=simulate_drift)
    from deequ_tpu.data.source import TableBatchSource
    from deequ_tpu.data.streaming import StreamingTable
    from deequ_tpu.ops.device_policy import install_scan_fault_hook
    from deequ_tpu.ops.scan_engine import SCAN_STATS, total_resident_bytes
    from deequ_tpu.resilience.faults import (
        FaultInjectingScanHook,
        FaultSchedule,
        FlakyBatchSource,
    )
    from deequ_tpu.resilience.governance import fault_state_scope
    from deequ_tpu.verification import VerificationSuite

    table = _build_table()
    n_batches = schedule.n_batches

    # fault-free reference over the full batch partition (same pipeline,
    # same fold order; memoized — every schedule shares it)
    ref = _reference_metrics(
        _batch_slices(table, range(n_batches)), N_ROWS,
        cache_key=tuple(range(n_batches)),
    )

    # compose the schedule's events into the injector seams
    batch_fail = {
        ("batch", int(e["index"])): float(e["times"])
        for e in schedule.events
        if e["seam"] == "batch"
    }
    staging = [e for e in schedule.events if e["seam"] == "staging"]
    batch_schedule = FaultSchedule(
        seed=schedule.seed,
        fail=batch_fail,
        delay_seconds=max((e["seconds"] for e in staging), default=0.0),
        delay_rate=max((e["rate"] for e in staging), default=1.0),
    )
    scan_faults = {}
    for e in schedule.events:
        if e["seam"] != "scan":
            continue
        scan_faults[int(e["scan"])] = (
            e["kind"],
            float(e["times"]),
            None if e.get("device") is None else int(e["device"]),
        )
    # hang_release="error": a hung call eventually surfaces UNAVAILABLE
    # instead of silently dispatching its stale program — on the CPU
    # test backend an abandoned worker's late mesh dispatch would
    # deadlock the shared collective thread pool against the resharded
    # mesh (see FaultInjectingScanHook docs)
    hook = FaultInjectingScanHook(
        scan_faults, hang_seconds=HANG_SECONDS, relative=True,
        hang_release="error",
    )
    ckpt, fs_schedule, restore_fs = _install_fs_events(
        schedule.events, schedule.seed
    )

    stream = StreamingTable(
        FlakyBatchSource(
            TableBatchSource(table, BATCH_ROWS), batch_schedule
        )
    )

    result = None
    exc: Optional[BaseException] = None
    from deequ_tpu.obs.registry import REGISTRY

    try:
        with fault_state_scope():
            install_scan_fault_hook(hook)
            # ledger capture goes THROUGH the unified registry (its
            # "scan"/"retry" sections are read-through views over
            # SCAN_STATS / RETRY_TELEMETRY): oracle 7 checking deltas
            # of THIS snapshot proves the unification didn't fork the
            # counters. Captured inside fault_state_scope — the scope
            # resets RETRY_TELEMETRY on entry and restores it on exit,
            # so the delta must bracket the run, not the scope.
            reg_before = REGISTRY.snapshot()
            t0 = time.monotonic()
            try:
                result = VerificationSuite.do_verification_run(
                    stream,
                    [_check()],
                    _analyzers(),
                    on_batch_error="skip",
                    on_device_error="fallback",
                    retry_policy=_fast_retry(),
                    checkpoint=ckpt,
                    run_deadline=schedule.run_deadline,
                    max_total_attempts=schedule.max_total_attempts,
                    on_budget_exhausted=schedule.on_budget_exhausted,
                )
            # deequ-lint: ignore[bare-except] -- the chaos driver's whole job is to observe ANY outcome; oracle 1 re-checks that it was typed
            except Exception as e:  # noqa: BLE001
                exc = e
            elapsed = time.monotonic() - t0
            reg_after = REGISTRY.snapshot()
    finally:
        # even a BaseException escaping the run (KeyboardInterrupt) must
        # not leave the fault-injecting chaosfs:// scheme registered
        restore_fs()
    scan_before = reg_before["scan"]
    scan_after = reg_after["scan"]

    injected = list(hook.injected) + list(batch_schedule.injected)
    if fs_schedule is not None:
        injected += list(fs_schedule.injected)

    report = ChaosReport(
        schedule=schedule,
        outcome=(
            f"exception:{type(exc).__name__}"
            if exc is not None
            else (
                "degraded"
                if (result.skipped_batches or result.unverified_row_ranges)
                else "identical"
            )
        ),
        elapsed=elapsed,
        metrics=_metric_rows(result) if result is not None else {},
        skipped=list(result.skipped_batches) if result is not None else [],
        unverified=(
            [tuple(r) for r in result.unverified_row_ranges]
            if result is not None
            else []
        ),
        run_budget=dict(result.run_budget) if result is not None else {},
        retry_stats=dict(result.retry_stats) if result is not None else {},
        retry_observed=(
            reg_after["retry"]["attempts"] - reg_before["retry"]["attempts"]
        ),
        scan_delta={
            k: scan_after[k] - scan_before[k]
            for k in (
                "scan_passes",
                "device_fetches",
                "budget_charges",
                "budget_exhaustions",
            )
        },
        injected=injected,
        resident_after=total_resident_bytes(),
    )

    if simulate_drift and injected and report.metrics:
        # deliberately-broken-ladder mode: nudge every successful metric
        # the way a recovery path that re-reads rows (or drops them)
        # would — the bit-identity oracle must catch this
        report.drifted = True
        report.metrics = {
            k: ("ok", v + 1e-9) if status == "ok" else (status, v)
            for k, (status, v) in report.metrics.items()
        }

    report.violations = _check_oracles(report, ref, exc, table)
    return report


# -- the fleet scenario (worker seam) ----------------------------------------


def _tenant_slices(table):
    """The fleet scenario's tenants: the scenario table split into
    ``FLEET_TENANT_ROWS``-sized slices (unequal on purpose — distinct
    row counts give distinct routing digests, so the tenants spread
    across the ring; see the geometry comment)."""
    import numpy as np

    out, lo = [], 0
    for rows in FLEET_TENANT_ROWS:
        idx = np.arange(lo, lo + rows)
        out.append(
            type(table)([table[c].take(idx) for c in table.column_names])
        )
        lo += rows
    return out


#: healthy per-tenant reference metrics, memoized across schedules (a
#: pure function of the fixed scenario slice)
_FLEET_REF_CACHE: Dict[int, Dict[str, tuple]] = {}


def _fleet_reference(tenant: int, table) -> Dict[str, tuple]:
    """Fault-free reference for one tenant: a direct per-tenant
    ``VerificationSuite`` run under the single-device view — the serial
    twin the serving layer's coalesced==serial contract (tier-1 `serve`)
    already pins bit-identical, and the fleet's failover re-dispatch
    must reproduce bit-for-bit (plans are deterministic)."""
    from deequ_tpu.parallel.mesh import use_mesh
    from deequ_tpu.resilience.governance import fault_state_scope
    from deequ_tpu.verification import VerificationSuite

    if tenant in _FLEET_REF_CACHE:
        return _FLEET_REF_CACHE[tenant]
    with fault_state_scope(), use_mesh(None):
        result = VerificationSuite.do_verification_run(
            table, [_check()], _analyzers()
        )
    out = _metric_rows(result)
    _FLEET_REF_CACHE[tenant] = out
    return out


def _apply_worker_event(fleet, event: dict) -> None:
    kind, worker = event["kind"], int(event["worker"])
    if kind == "death":
        fleet.kill_worker(worker, reason="chaos schedule")
    elif kind == "stall":
        fleet.stall_worker(worker, WORKER_STALL_SECONDS)
    elif kind == "rejoin":
        fleet.rejoin_worker(worker)
    else:
        raise ValueError(f"unknown worker event kind {kind!r}")


def _run_worker_schedule(
    schedule: ChaosSchedule, simulate_drift: bool = False
) -> ChaosReport:
    """The worker-seam scenario: ``FLEET_WAVES`` waves of per-tenant
    suites over a ``FLEET_N_WORKERS`` fleet, the schedule's worker
    events applied while their wave is in flight (submitted, not yet
    gathered), then oracles 1/2/3 + fetch contract + 8 — the
    streaming-specific row-accounting/ledger oracles have no fleet
    analogue (a tenant's suite either completes bit-identically after
    failover or rejects typed)."""
    from deequ_tpu.obs.registry import REGISTRY
    from deequ_tpu.serve.fleet import VerificationFleet

    table = _build_table()
    tenants = _tenant_slices(table)
    ref = {t: _fleet_reference(t, tbl) for t, tbl in enumerate(tenants)}

    by_wave: Dict[int, List[dict]] = {}
    for e in schedule.events:
        if e.get("seam") == "worker":
            by_wave.setdefault(int(e.get("wave", 0)), []).append(e)

    applied: List[tuple] = []
    gathered: List[tuple] = []  # (wave, tenant, future)
    exc: Optional[BaseException] = None
    reg_before = REGISTRY.snapshot()
    t0 = time.monotonic()
    # the scenario fleet: shared-compile-cache workers (see
    # FleetConfig.distinct_devices) so a steady-state dispatch is
    # milliseconds and FLEET_STALL_TIMEOUT cleanly separates "busy"
    # from "scripted stall"; the monitor arms only AFTER the warmup
    # wave + prewarm below — cold compiles would otherwise read as
    # stalls and every schedule would cascade into total fleet loss
    fleet = VerificationFleet(
        n_workers=FLEET_N_WORKERS,
        heartbeat_interval=FLEET_HEARTBEAT,
        stall_timeout=FLEET_STALL_TIMEOUT,
        distinct_devices=False,
        monitor=False,
    )
    try:
        warmup = [
            fleet.submit(
                tbl, [_check()],
                required_analyzers=_analyzers(), tenant=f"t{t}",
            )
            for t, tbl in enumerate(tenants)
        ]
        for future in warmup:
            future.result(timeout=schedule.run_deadline)
        fleet.prewarm()
        fleet.membership.start()
        for wave in range(FLEET_WAVES):
            wave_futures = []
            for t, tbl in enumerate(tenants):
                future = fleet.submit(
                    tbl, [_check()],
                    required_analyzers=_analyzers(), tenant=f"t{t}",
                )
                wave_futures.append((t, future))
            # the wave is in flight: apply this wave's scripted events
            for e in by_wave.get(wave, ()):
                _apply_worker_event(fleet, e)
                applied.append(
                    ("worker", e["kind"], int(e["worker"]), wave)
                )
            for t, future in wave_futures:
                gathered.append((wave, t, future))
                try:
                    future.result(timeout=schedule.run_deadline)
                # deequ-lint: ignore[bare-except] -- the chaos driver observes ANY per-future outcome; oracle 1 re-checks that it was typed
                except Exception:  # noqa: BLE001
                    pass
    # deequ-lint: ignore[bare-except] -- a submit on an all-dead fleet (or any driver error) becomes the report's outcome; oracle 1 checks it is typed
    except Exception as e:  # noqa: BLE001
        exc = e
    finally:
        fleet.stop(drain=True)
    elapsed = time.monotonic() - t0
    reg_after = REGISTRY.snapshot()

    metrics: Dict[str, tuple] = {}
    for wave, t, future in gathered:
        prefix = f"w{wave}/t{t}"
        if future._error is not None:
            metrics[prefix] = ("fail", type(future._error).__name__)
        elif future._result is not None:
            for name, row in _metric_rows(future._result).items():
                metrics[f"{prefix}/{name}"] = row
    rejected = sum(
        1 for _, _, f in gathered if f.done() and f._error is not None
    )
    scan_before, scan_after = reg_before["scan"], reg_after["scan"]
    report = ChaosReport(
        schedule=schedule,
        outcome=(
            f"exception:{type(exc).__name__}" if exc is not None
            else ("degraded" if rejected else "identical")
        ),
        elapsed=elapsed,
        metrics=metrics,
        scan_delta={
            k: scan_after[k] - scan_before[k]
            for k in ("scan_passes", "device_fetches")
        },
        injected=applied,
        fleet={
            "accepted": len(gathered),
            "resolved_once": sum(
                1 for _, _, f in gathered
                if f.done() and f.resolve_count == 1
            ),
            "orphaned": sum(1 for _, _, f in gathered if not f.done()),
            "multi_resolved": sum(
                1 for _, _, f in gathered if f.resolve_count > 1
            ),
            "late_resolutions": sum(
                f.late_resolutions for _, _, f in gathered
            ),
            "rejected": rejected,
            "workers_lost": fleet.workers_lost,
            "requests_redispatched": fleet.requests_redispatched,
        },
    )

    if simulate_drift and applied and report.metrics:
        report.drifted = True
        report.metrics = {
            k: ("ok", v + 1e-9) if status == "ok" else (status, v)
            for k, (status, v) in report.metrics.items()
        }

    report.violations = _check_worker_oracles(report, ref, exc)
    return report


def _check_worker_oracles(
    report: ChaosReport, ref: Dict[int, Dict[str, tuple]], exc
) -> List[str]:
    """The worker-seam oracle subset (see the module docstring)."""
    from deequ_tpu.exceptions import MetricCalculationException

    v: List[str] = []
    schedule = report.schedule

    # 1. typed outcome — the driver-level exception AND every rejected
    # future must come from the taxonomy
    if exc is not None and not isinstance(exc, MetricCalculationException):
        v.append(f"untyped outcome: {type(exc).__name__}: {exc}")
    for key, row in report.metrics.items():
        if row[0] == "fail" and not (
            row[1].endswith("Exception") or row[1].endswith("Error")
        ):
            v.append(f"future {key}: suspicious failure type {row[1]}")

    # 2. termination
    if report.elapsed > schedule.run_deadline * 1.5 + TERMINATION_SLACK:
        v.append(
            f"termination: {report.elapsed:.2f}s exceeded "
            f"run_deadline={schedule.run_deadline:g}s (+slack)"
        )

    # 8. exactly-once futures: every accepted future resolves exactly
    # once — none orphaned by a dead worker, none double-resolved by a
    # stalled worker waking after failover
    fl = report.fleet
    if fl.get("orphaned"):
        v.append(
            f"exactly-once: {fl['orphaned']} of {fl['accepted']} accepted "
            "futures never resolved (orphaned by a lost worker)"
        )
    if fl.get("multi_resolved"):
        v.append(
            f"exactly-once: {fl['multi_resolved']} futures applied more "
            "than one resolution"
        )
    if fl.get("resolved_once", 0) + fl.get("orphaned", 0) != fl.get(
        "accepted", 0
    ):
        v.append(
            "exactly-once: resolved_once + orphaned != accepted "
            f"({fl})"
        )

    # 8b. split-brain fencing (partition seam): every dispatch a zombie
    # coordinator attempted after losing the lease must have been
    # refused typed — zero stale-epoch effects reach the system
    if fl.get("zombie_unfenced"):
        v.append(
            f"fencing: {fl['zombie_unfenced']} zombie dispatches were "
            "ACCEPTED after a partition (epoch fence failed)"
        )
    n_partitions = sum(
        1 for row in report.injected if row[1] == "partition"
    )
    if n_partitions and fl.get("zombie_fenced", 0) < n_partitions:
        v.append(
            f"fencing: {n_partitions} partition(s) applied but only "
            f"{fl.get('zombie_fenced', 0)} zombie dispatches were fenced"
        )

    # fetch contract: the serving path's one-fetch-per-coalesced-batch
    # discipline bounds fetches by scan passes, failover included
    if report.scan_delta.get("device_fetches", 0) > report.scan_delta.get(
        "scan_passes", 0
    ):
        v.append(
            "fetch contract: "
            f"{report.scan_delta['device_fetches']} fetches > "
            f"{report.scan_delta['scan_passes']} scan passes"
        )

    # 3. bit-identity: every future that resolved with a result must
    # equal the tenant's healthy serial reference bit for bit —
    # re-dispatched or not (plans are deterministic)
    for key, (status, value) in report.metrics.items():
        if status != "ok":
            continue
        _, t_part, name = key.split("/", 2)
        exp = ref[int(t_part[1:])].get(name)
        if exp is None:
            v.append(f"metric {key}: no reference value")
        elif exp[0] != "ok":
            v.append(
                f"metric {key}: reference failed ({exp[1]}) but fleet "
                "run succeeded"
            )
        elif not _bit_identical(value, exp[1]):
            v.append(
                f"metric {key}: {value!r} != healthy reference "
                f"{exp[1]!r} (failover must be bit-identical)"
            )
    return v


# -- the process-fleet scenario (kill -9 seam, round 17) ---------------------


def _apply_pworker_event(state: dict, event: dict, resume_map) -> None:
    """One scripted process-fleet event, while its wave is in flight.
    ``kill9`` is a REAL SIGKILL on the worker process (the loss signal
    is transport EOF, exactly like host death); ``coord_kill9``
    abandons the coordinator object wholesale — what SIGKILL does to
    its threads, sockets, and ledger handle — and resumes a FRESH
    :class:`~deequ_tpu.serve.pfleet.ProcessFleet` off the durable
    ledger, onto the original futures (``resume_map``); ``partition``
    is the split-brain seam: the old coordinator is NOT abandoned — it
    survives with live workers while the fresh one resumes, then wakes
    mid-resume and attempts another dispatch, which the epoch fence
    must refuse typed (zombie accounting feeds the fencing oracle)."""
    from deequ_tpu.serve.pfleet import ProcessFleet

    kind = event["kind"]
    fleet = state["fleet"]
    if kind == "kill9":
        fleet.kill_worker(int(event["worker"]), reason="chaos kill -9")
    elif kind == "rejoin":
        fleet.rejoin_worker(int(event["worker"]))
    elif kind == "partition":
        from deequ_tpu.exceptions import StaleEpochException

        state["workers_lost"] += fleet.workers_lost
        state["redispatched"] += fleet.requests_redispatched
        # the zombie stays fully alive: threads, worker processes,
        # ledger handle — only the LEASE decides who owns the epoch
        state["zombies"].append(fleet)
        state["fleet"] = ProcessFleet(
            n_workers=FLEET_N_WORKERS,
            transport=state["transport"],
            ledger_dir=state["ledger_dir"],
            heartbeat_interval=FLEET_HEARTBEAT,
            stall_timeout=FLEET_STALL_TIMEOUT,
            monitor=False,
            resume_futures=resume_map(),
        )
        state["resumed"] += len(state["fleet"].resumed)
        # the zombie wakes mid-resume and tries to keep serving: its
        # dispatch must be refused by the epoch fence, not accepted
        try:
            state["zombies"][-1].submit(
                state["probe"], [_check()],
                required_analyzers=_analyzers(), tenant="t0",
            )
            state["zombie_unfenced"] += 1
        except StaleEpochException:
            state["zombie_fenced"] += 1
    elif kind == "coord_kill9":
        # the old incarnation's loss counters must survive the swap —
        # the report accounts for the whole timeline, not one fleet
        state["workers_lost"] += fleet.workers_lost
        state["redispatched"] += fleet.requests_redispatched
        fleet.abandon()
        state["fleet"] = ProcessFleet(
            n_workers=FLEET_N_WORKERS,
            transport=state["transport"],
            ledger_dir=state["ledger_dir"],
            heartbeat_interval=FLEET_HEARTBEAT,
            stall_timeout=FLEET_STALL_TIMEOUT,
            monitor=False,
            resume_futures=resume_map(),
        )
        state["resumed"] += len(state["fleet"].resumed)
    else:
        raise ValueError(f"unknown pworker event kind {kind!r}")


def _run_pworker_schedule(
    schedule: ChaosSchedule, simulate_drift: bool = False
) -> ChaosReport:
    """The PROCESS-fleet scenario (kill -9 seam): ``PFLEET_WAVES``
    waves of per-tenant suites over a ledger-backed
    :class:`~deequ_tpu.serve.pfleet.ProcessFleet` of REAL worker
    processes. ``kill9`` events SIGKILL a worker mid-wave — failover
    must re-dispatch its in-flight tenants bit-identically onto
    survivors; a ``coord_kill9`` kills the COORDINATOR mid-wave and
    resumes a fresh one off the durable request ledger, onto the
    original futures. Oracle 8 (exactly-once) then holds across BOTH
    process boundaries: no future orphaned by a dead worker OR a dead
    coordinator, none double-resolved by the ledger replay (the
    first-resolution-wins gate)."""
    import shutil
    import tempfile

    from deequ_tpu.obs.registry import REGISTRY
    from deequ_tpu.serve.pfleet import ProcessFleet

    table = _build_table()
    tenants = _tenant_slices(table)
    ref = {t: _fleet_reference(t, tbl) for t, tbl in enumerate(tenants)}

    by_wave: Dict[int, List[dict]] = {}
    for e in schedule.events:
        if e.get("seam") == "worker":
            by_wave.setdefault(int(e.get("wave", 0)), []).append(e)

    applied: List[tuple] = []
    gathered: List[tuple] = []  # (wave, tenant, future)
    all_futures: List = []
    exc: Optional[BaseException] = None
    ledger_dir = tempfile.mkdtemp(prefix="deequ-chaos-ledger-")
    state = {
        "fleet": None,
        "ledger_dir": ledger_dir,
        "transport": "proc",
        "workers_lost": 0,
        "redispatched": 0,
        "resumed": 0,
        # split-brain (partition) accounting: the surviving old
        # coordinators, and how their post-partition dispatches fared
        "zombies": [],
        "zombie_fenced": 0,
        "zombie_unfenced": 0,
        "probe": tenants[0],
    }

    def resume_map():
        # the driver survived the coordinator: resume onto the
        # ORIGINAL futures. Ids missing here (resolved in the race
        # window before the kill) are already tombstoned — the replay
        # skips them entirely
        return {
            f.accept_id: f for f in all_futures
            if not f.done() and getattr(f, "accept_id", None)
        }

    reg_before = REGISTRY.snapshot()
    t0 = time.monotonic()
    # monitor off: SIGKILL loss surfaces as transport EOF through the
    # receiver thread, which is immediate and deterministic — the
    # membership monitor's probe cadence would only add replay jitter
    state["fleet"] = ProcessFleet(
        n_workers=FLEET_N_WORKERS,
        transport="proc",
        ledger_dir=ledger_dir,
        heartbeat_interval=FLEET_HEARTBEAT,
        stall_timeout=FLEET_STALL_TIMEOUT,
        monitor=False,
    )
    try:
        # warmup wave: every worker process compiles its placed tenant
        # shapes before any scripted kill, then prewarm ships the hot
        # fingerprints fleet-wide so failover lands on warm survivors
        warmup = [
            state["fleet"].submit(
                tbl, [_check()],
                required_analyzers=_analyzers(), tenant=f"t{t}",
            )
            for t, tbl in enumerate(tenants)
        ]
        for future in warmup:
            future.result(timeout=schedule.run_deadline)
        state["fleet"].prewarm()
        for wave in range(PFLEET_WAVES):
            wave_futures = []
            for t, tbl in enumerate(tenants):
                future = state["fleet"].submit(
                    tbl, [_check()],
                    required_analyzers=_analyzers(), tenant=f"t{t}",
                )
                wave_futures.append((t, future))
                all_futures.append(future)
            # the wave is in flight: apply this wave's scripted events
            for e in by_wave.get(wave, ()):
                _apply_pworker_event(state, e, resume_map)
                applied.append(
                    ("worker", e["kind"], int(e.get("worker", -1)), wave)
                )
            for t, future in wave_futures:
                gathered.append((wave, t, future))
                try:
                    future.result(timeout=schedule.run_deadline)
                # deequ-lint: ignore[bare-except] -- the chaos driver observes ANY per-future outcome; oracle 1 re-checks that it was typed
                except Exception:  # noqa: BLE001
                    pass
    # deequ-lint: ignore[bare-except] -- a submit on an all-dead fleet (or any driver error) becomes the report's outcome; oracle 1 checks it is typed
    except Exception as e:  # noqa: BLE001
        exc = e
    finally:
        try:
            state["fleet"].stop(drain=True)
        finally:
            for zombie in state["zombies"]:
                try:
                    zombie.stop(drain=False)
                # deequ-lint: ignore[bare-except] -- zombie teardown is best-effort: a fenced coordinator's workers may already be gone
                except Exception:  # noqa: BLE001
                    pass
            shutil.rmtree(ledger_dir, ignore_errors=True)
    elapsed = time.monotonic() - t0
    reg_after = REGISTRY.snapshot()

    metrics: Dict[str, tuple] = {}
    for wave, t, future in gathered:
        prefix = f"w{wave}/t{t}"
        if future._error is not None:
            metrics[prefix] = ("fail", type(future._error).__name__)
        elif future._result is not None:
            for name, row in _metric_rows(future._result).items():
                metrics[f"{prefix}/{name}"] = row
    rejected = sum(
        1 for _, _, f in gathered if f.done() and f._error is not None
    )
    # scan deltas are coordinator-side only (the worker processes keep
    # their own registries): both stay 0 here, so the fetch-contract
    # oracle holds trivially — cross-process fetch accounting is the
    # worker tests' job, not the chaos driver's
    scan_before, scan_after = reg_before["scan"], reg_after["scan"]
    final = state["fleet"]
    report = ChaosReport(
        schedule=schedule,
        outcome=(
            f"exception:{type(exc).__name__}" if exc is not None
            else ("degraded" if rejected else "identical")
        ),
        elapsed=elapsed,
        metrics=metrics,
        scan_delta={
            k: scan_after[k] - scan_before[k]
            for k in ("scan_passes", "device_fetches")
        },
        injected=applied,
        fleet={
            "accepted": len(gathered),
            "resolved_once": sum(
                1 for _, _, f in gathered
                if f.done() and f.resolve_count == 1
            ),
            "orphaned": sum(1 for _, _, f in gathered if not f.done()),
            "multi_resolved": sum(
                1 for _, _, f in gathered if f.resolve_count > 1
            ),
            "late_resolutions": sum(
                f.late_resolutions for _, _, f in gathered
            ),
            "rejected": rejected,
            "workers_lost": state["workers_lost"] + final.workers_lost,
            "requests_redispatched": (
                state["redispatched"] + final.requests_redispatched
            ),
            "resumed": state["resumed"],
            "zombie_fenced": state["zombie_fenced"],
            "zombie_unfenced": state["zombie_unfenced"],
        },
    )

    if simulate_drift and applied and report.metrics:
        report.drifted = True
        report.metrics = {
            k: ("ok", v + 1e-9) if status == "ok" else (status, v)
            for k, (status, v) in report.metrics.items()
        }

    report.violations = _check_worker_oracles(report, ref, exc)
    return report


# -- the load scenario (overload seam, round 15) -----------------------------


def _apply_load_event(fleet, event: dict, submit_flood, applied) -> None:
    """One scripted load event, while its wave is in flight. ``spike``
    bursts open-loop flood submissions (no pacing, no gathering until
    the wave gathers); ``slow_tenant`` wedges the named tenant's PLACED
    worker briefly — queue pressure, not death (membership is off)."""
    kind = event["kind"]
    tenant = int(event["tenant"])
    if kind == "spike":
        for i in range(int(event["burst"])):
            submit_flood(tenant, i)
        applied.append(("load", "spike", tenant, int(event["burst"])))
    elif kind == "slow_tenant":
        seconds = float(event["seconds"])
        wid = fleet.route_of_tenant(tenant)
        if wid is not None:
            # the worker wedges at its NEXT batch take and the wave's
            # gather rides it out — anything queued behind the wedge
            # (this wave's traffic, a following spike) waits, and
            # tight-deadline requests expire in-queue while it sleeps
            fleet.stall_worker(wid, seconds)
        applied.append(("load", "slow_tenant", tenant, seconds))
    else:
        raise ValueError(f"unknown load event kind {kind!r}")


def _run_load_schedule(
    schedule: ChaosSchedule, simulate_drift: bool = False
) -> ChaosReport:
    """The load-seam scenario: the 4-tenant fleet waves with every
    submission carrying a real SLO class (:data:`LOAD_TENANT_SLO`),
    the schedule's spikes/stalls applied while their wave is in flight,
    then oracles 1/2/3/9/10. Admission refusals are TYPED submit-time
    outcomes (no future minted — counted, not gathered); in-queue
    deadline sheds are typed resolutions on accepted futures (oracle 9
    counts them as such)."""
    from deequ_tpu.exceptions import (
        DeadlineExceededException,
        ServiceOverloadedException,
    )
    from deequ_tpu.obs.registry import REGISTRY
    from deequ_tpu.serve.admission import Slo
    from deequ_tpu.serve.fleet import VerificationFleet

    table = _build_table()
    tenants = _tenant_slices(table)
    ref = {t: _fleet_reference(t, tbl) for t, tbl in enumerate(tenants)}

    by_wave: Dict[int, List[dict]] = {}
    for e in schedule.events:
        if e.get("seam") == "load":
            by_wave.setdefault(int(e.get("wave", 0)), []).append(e)

    records: List[dict] = []
    applied: List[tuple] = []
    refused = {cls: 0 for cls, _ in set(LOAD_TENANT_SLO)}
    exc: Optional[BaseException] = None
    reg_before = REGISTRY.snapshot()
    t0 = time.monotonic()
    # membership stays OFF: a scripted stall here is queue pressure the
    # admission tier must absorb, not a death for failover to mop up
    fleet = VerificationFleet(
        n_workers=FLEET_N_WORKERS,
        heartbeat_interval=FLEET_HEARTBEAT,
        stall_timeout=FLEET_STALL_TIMEOUT,
        distinct_devices=False,
        monitor=False,
        worker_knobs={
            "max_pending": LOAD_MAX_PENDING,
            "coalesce_window": 0.01,
        },
    )

    def route_of_tenant(t: int):
        # the digest must match the SUBMISSIONS' (checks included —
        # route_digest folds the check's analyzers in), or the stall
        # wedges a different worker than the tenant's traffic queues on
        return fleet.route(
            tenants[t], [_check()], required_analyzers=_analyzers()
        )

    fleet.route_of_tenant = route_of_tenant

    def submit(wave: int, t: int, cls: str, deadline_ms, tenant_name,
               kind: str):
        """One SLO-classed submission; records the ACTUAL worker it
        landed on (spill included) for the inversion oracle."""
        try:
            future = fleet.submit(
                tenants[t], [_check()],
                required_analyzers=_analyzers(), tenant=tenant_name,
                slo=Slo(deadline_ms=deadline_ms, cls=cls),
            )
        except ServiceOverloadedException as e:
            refused[cls] = refused.get(cls, 0) + 1
            records.append({
                "wave": wave, "tenant": t, "cls": cls, "kind": kind,
                "outcome": f"refused:{type(e).__name__}",
                "worker": None, "future": None,
            })
            return
        with fleet._lock:
            asg = fleet._assignments.get(future)
        records.append({
            "wave": wave, "tenant": t, "cls": cls, "kind": kind,
            "outcome": None,
            "worker": asg.worker if asg is not None else None,
            "future": future,
        })

    def submit_flood(t: int, i: int):
        submit(
            wave, t, "best_effort", LOAD_SPIKE_DEADLINE_MS,
            f"flood-t{t}-{i}", "spike",
        )

    try:
        # warmup wave: no deadlines, standard class — pays the compile
        # storms so scripted waves measure the admission tier, not XLA
        warmup = [
            fleet.submit(
                tbl, [_check()],
                required_analyzers=_analyzers(), tenant=f"t{t}",
                slo=Slo(cls="standard"),
            )
            for t, tbl in enumerate(tenants)
        ]
        for future in warmup:
            future.result(timeout=schedule.run_deadline)
        fleet.prewarm()
        for wave in range(FLEET_WAVES):
            wave_start = len(records)
            wave_events = by_wave.get(wave, ())
            # slow-tenant stalls apply BEFORE the wave submits: the
            # worker must already be wedged when traffic arrives, or an
            # instantaneous burst coalesces into one batch and drains
            # before the wedge takes effect (real overload is arrival
            # outpacing a slow server, not a fast server seeing a blip)
            for e in wave_events:
                if e["kind"] == "slow_tenant":
                    _apply_load_event(fleet, e, submit_flood, applied)
            # a beat for the idle worker to consume the wedge before
            # the wave queues behind it (deterministic ordering, not a
            # race: the un-wedged path is also correct, just unloaded)
            if any(e["kind"] == "slow_tenant" for e in wave_events):
                time.sleep(0.12)
            # class-priority submission order (critical first): the
            # inversion oracle's soundness leans on a critical having
            # been submitted BEFORE any best_effort it is compared to
            for t, (cls, deadline_ms) in enumerate(LOAD_TENANT_SLO):
                submit(wave, t, cls, deadline_ms, f"t{t}", "wave")
            for e in wave_events:
                if e["kind"] != "slow_tenant":
                    _apply_load_event(fleet, e, submit_flood, applied)
            for rec in records[wave_start:]:
                if rec["future"] is None:
                    continue
                try:
                    rec["future"].result(timeout=schedule.run_deadline)
                # deequ-lint: ignore[bare-except] -- the chaos driver observes ANY per-future outcome; oracles 1/9 re-check typedness and exactly-once
                except Exception:  # noqa: BLE001
                    pass
    # deequ-lint: ignore[bare-except] -- a driver-level error becomes the report's outcome; oracle 1 checks it is typed
    except Exception as e:  # noqa: BLE001
        exc = e
    finally:
        fleet.stop(drain=True)
    elapsed = time.monotonic() - t0
    reg_after = REGISTRY.snapshot()

    metrics: Dict[str, tuple] = {}
    sheds = {cls: 0 for cls, _ in LOAD_TENANT_SLO}
    for i, rec in enumerate(records):
        future = rec.pop("future")
        if future is None:
            continue  # refused at submit; outcome already recorded
        rec["submitted_at"] = future.submitted_at
        rec["resolved_at"] = future.resolved_at
        rec["resolve_count"] = future.resolve_count
        if not future.done():
            rec["outcome"] = "orphaned"
        elif isinstance(future._error, DeadlineExceededException):
            rec["outcome"] = "shed"
            sheds[rec["cls"]] = sheds.get(rec["cls"], 0) + 1
        elif future._error is not None:
            rec["outcome"] = f"fail:{type(future._error).__name__}"
        else:
            rec["outcome"] = "ok"
            prefix = f"w{rec['wave']}/{rec['kind']}{i}/t{rec['tenant']}"
            for name, row in _metric_rows(future._result).items():
                metrics[f"{prefix}/{name}"] = row

    accepted = [r for r in records if "resolve_count" in r]
    serve_b = reg_before.get("serve", {})
    serve_a = reg_after.get("serve", {})

    def serve_delta(key):
        b, a = serve_b.get(key) or {}, serve_a.get(key) or {}
        return {cls: a.get(cls, 0) - b.get(cls, 0) for cls in a}

    report = ChaosReport(
        schedule=schedule,
        outcome=(
            f"exception:{type(exc).__name__}" if exc is not None
            else (
                "degraded"
                if any(r["outcome"] != "ok" for r in records)
                else "identical"
            )
        ),
        elapsed=elapsed,
        metrics=metrics,
        injected=applied,
        load_records=records,
        fleet={
            "accepted": len(accepted),
            "resolved_once": sum(
                1 for r in accepted
                if r["outcome"] != "orphaned" and r["resolve_count"] == 1
            ),
            "orphaned": sum(
                1 for r in accepted if r["outcome"] == "orphaned"
            ),
            "multi_resolved": sum(
                1 for r in accepted if r["resolve_count"] > 1
            ),
            "shed": sum(sheds.values()),
            "shed_by_class": sheds,
            "refused": sum(refused.values()),
            "shed_counters": serve_delta("shed_by_class"),
            "admission_rejected_counters": serve_delta(
                "admission_rejected_by_class"
            ),
        },
    )

    if simulate_drift and applied and report.metrics:
        report.drifted = True
        report.metrics = {
            k: ("ok", v + 1e-9) if status == "ok" else (status, v)
            for k, (status, v) in report.metrics.items()
        }

    report.violations = _check_load_oracles(report, ref, exc)
    return report


def _check_load_oracles(
    report: ChaosReport, ref: Dict[int, Dict[str, tuple]], exc
) -> List[str]:
    """The load-seam oracle subset: 1 (typed), 2 (termination), 3
    (bit-identity of every COMPLETED result), 9 (exactly-once with shed
    counting as a typed resolution), 10 (no priority inversion)."""
    from deequ_tpu.exceptions import MetricCalculationException

    v: List[str] = []
    schedule = report.schedule

    # 1. typed outcome — driver exception, every rejected future, and
    # every admission refusal must come from the taxonomy
    if exc is not None and not isinstance(exc, MetricCalculationException):
        v.append(f"untyped outcome: {type(exc).__name__}: {exc}")
    for rec in report.load_records:
        out = rec["outcome"] or ""
        for tag in ("fail:", "refused:"):
            if out.startswith(tag):
                name = out[len(tag):]
                if not (
                    name.endswith("Exception") or name.endswith("Error")
                ):
                    v.append(
                        f"load future w{rec['wave']}/t{rec['tenant']}: "
                        f"suspicious {tag[:-1]} type {name}"
                    )

    # 2. termination
    if report.elapsed > schedule.run_deadline * 1.5 + TERMINATION_SLACK:
        v.append(
            f"termination: {report.elapsed:.2f}s exceeded "
            f"run_deadline={schedule.run_deadline:g}s (+slack)"
        )

    # 9. exactly-once under overload: every ACCEPTED future resolved
    # exactly once — a shed IS a typed resolution; none orphaned, none
    # double-resolved
    fl = report.fleet
    if fl.get("orphaned"):
        v.append(
            f"exactly-once: {fl['orphaned']} of {fl['accepted']} "
            "accepted futures never resolved under overload"
        )
    if fl.get("multi_resolved"):
        v.append(
            f"exactly-once: {fl['multi_resolved']} futures applied "
            "more than one resolution under overload"
        )
    if fl.get("resolved_once", 0) != fl.get("accepted", 0) - fl.get(
        "orphaned", 0
    ):
        v.append(f"exactly-once: accounting mismatch ({fl})")

    # 10. no priority inversion: a critical shed on worker w while a
    # best_effort submitted no earlier DISPATCHED on w before the shed
    # means the class-tiered queue popped past a waiting critical
    for c in report.load_records:
        if c["cls"] != "critical" or c["outcome"] != "shed":
            continue
        if c.get("worker") is None or c.get("resolved_at") is None:
            continue
        for b in report.load_records:
            if (
                b["cls"] == "best_effort"
                and b["outcome"] == "ok"
                and b.get("worker") == c["worker"]
                and b.get("resolved_at") is not None
                and b["submitted_at"] >= c["submitted_at"]
                and b["resolved_at"] < c["resolved_at"]
            ):
                v.append(
                    "priority inversion: critical request "
                    f"(w{c['wave']}/t{c['tenant']}) shed on worker "
                    f"{c['worker']} while best_effort "
                    f"(w{b['wave']}/{b['kind']}/t{b['tenant']}) "
                    "submitted after it dispatched there first"
                )

    # 3. bit-identity of every COMPLETED result: overload changes WHICH
    # requests run, never how
    for key, (status, value) in report.metrics.items():
        if status != "ok":
            continue
        t_part = key.split("/")[2]
        exp = ref[int(t_part[1:])].get(key.split("/", 3)[3])
        if exp is None:
            v.append(f"metric {key}: no reference value")
        elif exp[0] != "ok":
            v.append(
                f"metric {key}: reference failed ({exp[1]}) but the "
                "overloaded run succeeded"
            )
        elif not _bit_identical(value, exp[1]):
            v.append(
                f"metric {key}: {value!r} != unloaded serial reference "
                f"{exp[1]!r} (overload must never degrade computation)"
            )
    return v


# -- window scenario (round 20) ----------------------------------------------


def _window_analyzers():
    """The pane-fold analyzer set: every family the windowed engine's
    device fold supports (windows/engine.SUPPORTED_ANALYZERS), on
    integer-valued data so sums are exact and the per-window
    bit-identity half of oracle 11 holds across any kill/replay path."""
    from deequ_tpu.analyzers import (
        Completeness,
        Maximum,
        Mean,
        Minimum,
        Size,
        Sum,
    )

    return [
        Size(), Completeness("v"), Mean("v"), Minimum("v"), Maximum("v"),
        Sum("v"),
    ]


def _window_batches(schedule: ChaosSchedule) -> Dict[str, List[dict]]:
    """Per-stream event-time batch timelines with the schedule's DATA
    events (late_burst / disorder_spike) already applied — a pure
    function of the schedule, so the fault-free reference folds the
    SAME timeline and oracle 11's bit-identity is meaningful."""
    import numpy as np

    out: Dict[str, List[dict]] = {}
    for si, (sid, _cls, _dl, _pol) in enumerate(WINDOW_STREAM_SLO):
        rng = np.random.default_rng(schedule.seed * 7 + si)
        batches = []
        for b in range(WINDOW_N_BATCHES):
            lo = b * WINDOW_BATCH_SPAN_S
            ts = np.sort(
                rng.uniform(lo, lo + WINDOW_BATCH_SPAN_S, WINDOW_BATCH_ROWS)
            )
            v = np.floor(rng.uniform(-50.0, 51.0, WINDOW_BATCH_ROWS))
            v[rng.random(WINDOW_BATCH_ROWS) < 0.08] = np.nan
            batches.append({"ts": ts, "v": v})
        out[sid] = batches
    for e in schedule.events:
        if e.get("seam") != "window":
            continue
        sid = e.get("stream")
        b = int(e.get("batch", -1))
        if sid not in out or not (0 <= b < WINDOW_N_BATCHES):
            continue
        batch = out[sid][b]
        if e["kind"] == "late_burst":
            k = min(int(e.get("rows", 4)), WINDOW_BATCH_ROWS)
            ts = batch["ts"].copy()
            ts[:k] -= float(e.get("rewind_s", 12.0))
            batch["ts"] = ts
        elif e["kind"] == "disorder_spike":
            rng = np.random.default_rng(schedule.seed * 31 + b)
            batch["ts"] = batch["ts"] + rng.uniform(
                -float(e.get("jitter_s", 2.0)),
                float(e.get("jitter_s", 2.0)),
                WINDOW_BATCH_ROWS,
            )
    return out


def _window_spec_policy(stream_policy: str):
    from deequ_tpu.windows.spec import WatermarkPolicy, WindowSpec

    return (
        WindowSpec(WINDOW_SIZE_S, WINDOW_SIZE_S, time_column="ts"),
        WatermarkPolicy(WINDOW_LAG_S, stream_policy),
    )


def _window_reference(
    batch_map: Dict[str, List[dict]],
) -> Dict[str, Dict[float, dict]]:
    """Fault-free windowed reference: the same batch timelines through
    fresh streams — no kills, no state store, no overload. Returns
    stream id -> window end -> {"start", "metrics"} for every emitted
    close (the reference emits EVERY window: nothing sheds)."""
    from deequ_tpu.windows.engine import WindowedStream

    ref: Dict[str, Dict[float, dict]] = {}
    for sid, _cls, _dl, pol in WINDOW_STREAM_SLO:
        spec, policy = _window_spec_policy(pol)
        stream = WindowedStream(
            sid, _window_analyzers(), checks=[_check()],
            spec=spec, policy=policy, batch_rows=WINDOW_BATCH_ROWS,
        )
        closes = []
        for batch in batch_map[sid]:
            closes += stream.process_batch(batch)
        closes += stream.flush()
        ref[sid] = {
            c.end: {"start": c.start, "metrics": _metric_rows(c.result)}
            for c in closes
            if c.emitted
        }
    return ref


def _run_window_schedule(
    schedule: ChaosSchedule, simulate_drift: bool = False
) -> ChaosReport:
    """The window-seam scenario: three SLO-classed windowed streams
    fold the schedule's batch timelines through a StreamHub while the
    schedule scripts kills (resume from the window-state store),
    double-kill resume replays, and overload spikes; then oracle 11 +
    1/2. Each driver tick delivers one batch per stream; a freshly
    resumed stream catches up from its own ``next_batch_index``, so a
    replayed interval flows through the SAME per-batch path (and its
    already-emitted closes must hit the exactly-once fence)."""
    import tempfile

    from deequ_tpu.serve.admission import Slo
    from deequ_tpu.windows.service import StreamHub

    t0 = time.monotonic()
    report = ChaosReport(schedule=schedule, outcome="identical")
    batch_map = _window_batches(schedule)
    ref = _window_reference(batch_map)

    kills: Dict[int, int] = {}
    overloads: List[Tuple[int, int, int]] = []
    for e in schedule.events:
        if e.get("seam") != "window":
            continue
        if e["kind"] == "kill":
            kills[int(e["batch"])] = max(kills.get(int(e["batch"]), 0), 1)
        elif e["kind"] == "resume_replay":
            kills[int(e["batch"])] = 2
        elif e["kind"] == "overload":
            overloads.append((
                int(e["batch"]), int(e.get("level", 1)),
                int(e.get("batches", 2)),
            ))

    cls_of = {sid: cls for sid, cls, _dl, _pol in WINDOW_STREAM_SLO}
    closes_seen: List[dict] = []
    exc: Optional[BaseException] = None
    resumes = 0
    wm_regressions = 0
    final_state: Dict[str, dict] = {}

    with tempfile.TemporaryDirectory() as state_root:

        def new_hub() -> StreamHub:
            hub = StreamHub(state_root=state_root, checkpoint_every=2)
            for sid, cls, deadline_ms, pol in WINDOW_STREAM_SLO:
                spec, policy = _window_spec_policy(pol)
                hub.register_stream(
                    sid, _window_analyzers(), checks=[_check()],
                    slo=Slo(deadline_ms=deadline_ms, cls=cls),
                    spec=spec, policy=policy,
                    batch_rows=WINDOW_BATCH_ROWS,
                )
            return hub

        def record(sid: str, closes) -> None:
            for c in closes:
                closes_seen.append({
                    "stream": sid, "cls": cls_of[sid],
                    "start": c.start, "end": c.end,
                    "outcome": (
                        "emitted" if c.emitted
                        else "suppressed" if c.suppressed
                        else "shed"
                    ),
                    "metrics": (
                        _metric_rows(c.result) if c.emitted else None
                    ),
                })

        def feed_until(hub: StreamHub, tick: int, wm_seen: dict) -> None:
            """Deliver every batch <= ``tick`` a stream has not folded
            yet (one per tick in steady state; the catch-up replay
            after a resume)."""
            nonlocal wm_regressions
            for sid in hub.stream_ids:
                stream = hub.stream(sid)
                while stream.next_batch_index <= tick:
                    i = stream.next_batch_index
                    record(sid, hub.process_batch(sid, batch_map[sid][i]))
                    wm = stream.watermark
                    if wm < wm_seen.get(sid, float("-inf")):
                        wm_regressions += 1
                    wm_seen[sid] = wm

        hub = new_hub()
        wm_seen: Dict[str, float] = {}
        level_until = -1
        try:
            for tick in range(WINDOW_N_BATCHES):
                for (at, level, span) in overloads:
                    if at == tick:
                        hub.set_overload(level)
                        level_until = tick + span
                if tick == level_until:
                    hub.set_overload(0)
                feed_until(hub, tick, wm_seen)
                for _ in range(kills.get(tick, 0)):
                    # SIGKILL equivalent: the process state is GONE —
                    # only the window-state store survives
                    level = hub.overload_level
                    del hub
                    hub = new_hub()
                    hub.set_overload(level)
                    resumes += 1
                    wm_seen = {}
            feed_until(hub, WINDOW_N_BATCHES - 1, wm_seen)
            for sid in hub.stream_ids:
                record(sid, hub.stream(sid).flush())
                stream = hub.stream(sid)
                final_state[sid] = {
                    "late_rows": stream.late_rows,
                    "side_ranges": len(stream.side_ranges),
                    "sheds": len(stream.sheds),
                    "emitted": len(stream.emitted_windows),
                }
        # deequ-lint: ignore[bare-except] -- the chaos driver's whole job is to observe ANY outcome; oracle 1 re-checks that it was typed
        except BaseException as e:  # noqa: BLE001
            exc = e

    report.elapsed = time.monotonic() - t0
    report.windows = closes_seen
    emitted = [c for c in closes_seen if c["outcome"] == "emitted"]
    sheds = [c for c in closes_seen if c["outcome"] == "shed"]
    if simulate_drift and schedule.events and emitted:
        # deliberately-broken-resume mode: one emitted metric drifts by
        # one ulp — the bit-identity half of oracle 11 must catch it
        for c in emitted:
            for name, (status, value) in c["metrics"].items():
                if status == "ok" and isinstance(value, float) and value:
                    c["metrics"][name] = (
                        "ok", math.nextafter(value, math.inf)
                    )
                    report.drifted = True
                    break
            if report.drifted:
                break
    for c in emitted:
        for name, row in c["metrics"].items():
            report.metrics[f"w/{c['stream']}/{c['end']:g}/{name}"] = row
    report.fleet = {
        "emitted": len(emitted),
        "suppressed": sum(
            1 for c in closes_seen if c["outcome"] == "suppressed"
        ),
        "sheds": len(sheds),
        "resumes": resumes,
        "wm_regressions": wm_regressions,
        "late_rows": sum(s["late_rows"] for s in final_state.values()),
        "side_ranges": sum(
            s["side_ranges"] for s in final_state.values()
        ),
    }
    if exc is not None:
        report.outcome = f"exception:{type(exc).__name__}"
    elif sheds or report.fleet["suppressed"]:
        report.outcome = "degraded"
    report.violations = _check_window_oracles(report, ref, exc)
    return report


def _check_window_oracles(
    report: ChaosReport, ref: Dict[str, Dict[float, dict]], exc
) -> List[str]:
    """Oracle 11 (+ 1/2): every reference window emitted exactly once
    bit-identically or shed typed; critical never sheds; sheds only
    under a scripted overload spike; watermarks never regress; a
    scripted late burst lands in the typed late ledgers."""
    from deequ_tpu.exceptions import MetricCalculationException

    v: List[str] = []
    schedule = report.schedule

    # 1. typed outcome
    if exc is not None and not isinstance(exc, MetricCalculationException):
        v.append(f"untyped outcome: {type(exc).__name__}: {exc}")

    # 2. termination
    if report.elapsed > schedule.run_deadline * 1.5 + TERMINATION_SLACK:
        v.append(
            f"termination: {report.elapsed:.2f}s exceeded "
            f"run_deadline={schedule.run_deadline:g}s (+slack)"
        )
    if exc is not None:
        return v  # the rest of oracle 11 compares a COMPLETED run

    # 11. exactly-once window closes
    per_stream: Dict[str, Dict[str, List[dict]]] = {}
    for c in report.windows:
        per_stream.setdefault(c["stream"], {}).setdefault(
            c["outcome"], []
        ).append(c)
    had_overload = any(
        e.get("seam") == "window" and e.get("kind") == "overload"
        for e in schedule.events
    )
    for sid, expected in ref.items():
        buckets = per_stream.get(sid, {})
        emitted = buckets.get("emitted", [])
        shed = buckets.get("shed", [])
        emitted_ends = [c["end"] for c in emitted]
        if len(emitted_ends) != len(set(emitted_ends)):
            dupes = sorted(
                e for e in set(emitted_ends)
                if emitted_ends.count(e) > 1
            )
            v.append(
                f"exactly-once: stream {sid} emitted window(s) {dupes} "
                "more than once across kill-and-resume"
            )
        shed_ends = {c["end"] for c in shed}
        if set(emitted_ends) & shed_ends:
            v.append(
                f"exactly-once: stream {sid} both emitted and shed "
                f"window(s) {sorted(set(emitted_ends) & shed_ends)}"
            )
        covered = set(emitted_ends) | shed_ends
        if covered != set(expected):
            v.append(
                f"close completeness: stream {sid} covered "
                f"{sorted(covered)} but the fault-free reference closes "
                f"{sorted(expected)}"
            )
        cls = next(
            c for s, c, _d, _p in WINDOW_STREAM_SLO if s == sid
        )
        if cls == "critical" and shed:
            v.append(
                f"shed discipline: critical stream {sid} shed "
                f"{sorted(shed_ends)} — critical closes on deadline "
                "whatever the overload level"
            )
        if shed and not had_overload:
            v.append(
                f"shed discipline: stream {sid} shed {sorted(shed_ends)} "
                "with no overload event in the schedule"
            )
        # bit-identity of every emitted close against the reference
        for c in emitted:
            exp = expected.get(c["end"])
            if exp is None:
                continue  # already reported by completeness
            for name, row in (c["metrics"] or {}).items():
                want = exp["metrics"].get(name)
                if want is None:
                    v.append(
                        f"window {sid}/{c['end']:g}: metric {name} has "
                        "no reference value"
                    )
                elif row[0] != want[0] or (
                    row[0] == "ok" and not _bit_identical(row[1], want[1])
                ):
                    v.append(
                        f"window {sid}/{c['end']:g}: metric {name} "
                        f"{row!r} != fault-free reference {want!r}"
                    )

    # watermark monotonicity (within each stream incarnation)
    if report.fleet.get("wm_regressions"):
        v.append(
            f"watermark: {report.fleet['wm_regressions']} regression(s) "
            "observed — the close fence must be monotone"
        )

    # typed late routing: a scripted late burst must land in the late
    # ledgers (dropped counts / quarantined side-output ranges)
    had_burst = any(
        e.get("seam") == "window"
        and e.get("kind") == "late_burst"
        and int(e.get("batch", 0)) >= 2
        for e in schedule.events
    )
    if had_burst and not (
        report.fleet.get("late_rows") or report.fleet.get("side_ranges")
    ):
        v.append(
            "late routing: a scripted late burst left no trace in the "
            "typed late ledgers (late_rows / side-output ranges)"
        )
    return v


# -- oracles -----------------------------------------------------------------


def _check_oracles(
    report: ChaosReport, ref: Dict[str, tuple], exc, table
) -> List[str]:
    from deequ_tpu.exceptions import MetricCalculationException

    v: List[str] = []
    schedule = report.schedule

    # 1. typed outcome
    if exc is not None and not isinstance(exc, MetricCalculationException):
        v.append(
            f"untyped outcome: {type(exc).__name__}: {exc}"
        )

    # 2. termination within the run deadline (+ host slack)
    if report.elapsed > schedule.run_deadline * 1.5 + TERMINATION_SLACK:
        v.append(
            f"termination: {report.elapsed:.2f}s exceeded "
            f"run_deadline={schedule.run_deadline:g}s (+slack)"
        )

    # 5. HBM ledger returns to zero (nothing persisted may survive a
    # chaos run; bisection/fallback evictions must balance the ledger)
    if report.resident_after != 0:
        v.append(
            f"hbm ledger: {report.resident_after} resident bytes after "
            "the run"
        )

    if exc is not None:
        return v  # the remaining oracles compare a RESULT

    n_batches = schedule.n_batches

    # 4. row accounting: unverified ranges well-formed + batch-aligned,
    # quarantined indices valid, and the two never overlap
    skipped_rows = set()
    for i in report.skipped:
        if not (0 <= i < n_batches):
            v.append(f"quarantine: skipped batch {i} out of range")
            continue
        skipped_rows.update(
            range(i * BATCH_ROWS, min((i + 1) * BATCH_ROWS, N_ROWS))
        )
    if len(set(report.skipped)) != len(report.skipped):
        v.append("quarantine: duplicate skipped indices")
    unverified_rows = set()
    prev_stop = -1
    for start, stop in sorted(report.unverified):
        if not (0 <= start < stop <= N_ROWS):
            v.append(f"row accounting: malformed range ({start}, {stop})")
            continue
        if start < prev_stop:
            v.append("row accounting: overlapping unverified ranges")
        prev_stop = stop
        if start % BATCH_ROWS != 0:
            v.append(
                f"row accounting: range start {start} not batch-aligned"
            )
        unverified_rows.update(range(start, stop))
    if skipped_rows & unverified_rows:
        v.append(
            "row accounting: quarantined rows double-counted as "
            "unverified"
        )

    # 7a. quarantine consistency: every skipped batch traces to an
    # injected fault on that index
    injected_batches = {
        key[1]
        for (kind, key, _attempt) in (
            t for t in report.injected if len(t) == 3 and t[0] == "ioerror"
        )
        if isinstance(key, tuple) and key and key[0] == "batch"
    }
    for i in report.skipped:
        if i not in injected_batches:
            v.append(
                f"quarantine: batch {i} skipped without an injected fault"
            )

    # 7b. budget ledger consistency
    budget = report.run_budget
    if budget:
        charges = dict(budget.get("charges") or {})
        if budget.get("attempts") != sum(charges.values()):
            v.append(
                f"budget ledger: attempts={budget.get('attempts')} != "
                f"sum(charges)={sum(charges.values())}"
            )
        cap = budget.get("max_total_attempts")
        if (
            cap is not None
            and budget.get("exhausted") is None
            and budget.get("attempts", 0) > cap
        ):
            v.append("budget ledger: over cap without exhaustion")
        io_charged = charges.get("io_retry", 0)
        # read through the unified registry (report.retry_observed =
        # the registry "retry" section's attempts delta): if the
        # round-11 unification had forked the counters, the registry
        # view would drift from the budget ledger and this trips
        io_observed = (
            report.retry_observed
            if report.retry_observed is not None
            else report.retry_stats.get("attempts", 0)
        )
        if io_charged != io_observed:
            v.append(
                f"budget ledger: io_retry charges ({io_charged}) != "
                f"retry telemetry attempts ({io_observed})"
            )
        if report.retry_observed is not None and (
            report.retry_observed != report.retry_stats.get("attempts", 0)
        ):
            v.append(
                "budget ledger: registry retry view "
                f"({report.retry_observed}) != result.retry_stats "
                f"({report.retry_stats.get('attempts', 0)}) — the "
                "unified registry forked the counters"
            )

    # 6. fetch contract: at most one device->host fetch per scan pass
    # (the PR-4 discipline, preserved by every ladder rung)
    if report.scan_delta.get("device_fetches", 0) > report.scan_delta.get(
        "scan_passes", 0
    ):
        v.append(
            "fetch contract: "
            f"{report.scan_delta['device_fetches']} fetches > "
            f"{report.scan_delta['scan_passes']} scan passes"
        )

    # 3. bit-identity or exact degradation: successful metrics must equal
    # the fault-free reference over EXACTLY the verified rows; failure
    # metrics must be typed
    verified_batches = [
        i
        for i in range(n_batches)
        if i not in set(report.skipped)
        and not (
            unverified_rows
            & set(range(i * BATCH_ROWS, min((i + 1) * BATCH_ROWS, N_ROWS)))
        )
    ]
    if len(verified_batches) == n_batches:
        expected = ref
    else:
        surviving = _batch_slices(table, verified_batches)
        expected = _reference_metrics(
            surviving, sum(b.num_rows for b in surviving),
            cache_key=tuple(verified_batches),
        )
    for name, (status, value) in report.metrics.items():
        if status == "fail":
            # typed-failure names come from the taxonomy; anything else
            # leaked an unclassified error into a metric
            if not (
                value.endswith("Exception") or value.endswith("Error")
            ):
                v.append(f"metric {name}: suspicious failure type {value}")
            continue
        exp = expected.get(name)
        if exp is None:
            v.append(f"metric {name}: no reference value")
        elif exp[0] != "ok":
            v.append(
                f"metric {name}: reference failed ({exp[1]}) but chaos "
                "run succeeded"
            )
        elif not _bit_identical(value, exp[1]):
            v.append(
                f"metric {name}: {value!r} != reference {exp[1]!r} over "
                f"verified rows (batches {verified_batches})"
            )
    return v


def _bit_identical(a, b) -> bool:
    if isinstance(a, float) and isinstance(b, float):
        if math.isnan(a) and math.isnan(b):
            return True
        return a == b and math.copysign(1.0, a) == math.copysign(1.0, b)
    return a == b


# -- shrinker ----------------------------------------------------------------


def shrink_schedule(
    schedule: ChaosSchedule,
    failing: Optional[Callable[[ChaosSchedule], bool]] = None,
    simulate_drift: bool = False,
    max_runs: int = 48,
) -> Tuple[ChaosSchedule, int]:
    """Delta-debug a failing schedule down to a minimal reproducer.

    Classic ddmin over the event list: repeatedly try removing chunks of
    events, keeping any reduction that still fails the oracles (the
    ``failing`` predicate; default = ``run_schedule`` reports >= 1
    violation). Deterministic injection makes every candidate replayable,
    so the minimum found is a real reproducer, not a flake. Returns
    (minimal schedule, oracle runs spent)."""
    if failing is None:
        def failing(s: ChaosSchedule) -> bool:
            return run_schedule(s, simulate_drift=simulate_drift).failing

    runs = 1
    if not failing(schedule):
        return schedule, runs  # nothing to shrink
    events = list(schedule.events)
    granularity = 2
    while len(events) >= 2 and runs < max_runs:
        chunk = max(1, math.ceil(len(events) / granularity))
        reduced = False
        for lo in range(0, len(events), chunk):
            candidate = events[:lo] + events[lo + chunk:]
            if not candidate:
                continue
            runs += 1
            if failing(schedule.with_events(candidate)):
                events = candidate
                granularity = max(granularity - 1, 2)
                reduced = True
                break
            if runs >= max_runs:
                break
        if not reduced:
            if granularity >= len(events):
                break
            granularity = min(granularity * 2, len(events))
    return schedule.with_events(events), runs


# -- soak --------------------------------------------------------------------


def soak(
    n: int = 200,
    seed0: int = 0,
    simulate_drift: bool = False,
    verbose: bool = True,
    worker: bool = False,
    load: bool = False,
    window: bool = False,
) -> dict:
    """Run ``n`` seeded schedules; returns a summary with every failing
    seed and its shrunk reproducer. The CI entry point
    (``python -m deequ_tpu.resilience.chaos --soak``); ``worker=True``
    (CLI ``--worker``) soaks worker-seam schedules over the fleet
    scenario instead of the streaming one; ``load=True`` (CLI
    ``--load``) soaks load-seam schedules (scripted spikes +
    slow-tenant stalls under oracles 1/2/3/9/10); ``window=True``
    (CLI ``--window``) soaks window-seam schedules (round 20: late
    bursts, disorder, kill-and-resume, overload sheds under oracle
    11)."""
    import sys

    outcomes: Dict[str, int] = {}
    failures = []
    t0 = time.monotonic()
    if window:
        generate = ChaosSchedule.generate_window
    elif load:
        generate = ChaosSchedule.generate_load
    elif worker:
        generate = ChaosSchedule.generate_worker
    else:
        generate = ChaosSchedule.generate
    for seed in range(seed0, seed0 + n):
        schedule = generate(seed)
        report = run_schedule(schedule, simulate_drift=simulate_drift)
        outcomes[report.outcome] = outcomes.get(report.outcome, 0) + 1
        if report.failing:
            shrunk, runs = shrink_schedule(
                schedule, simulate_drift=simulate_drift
            )
            failures.append(
                {
                    "seed": seed,
                    "violations": report.violations,
                    "shrunk": shrunk.to_dict(),
                    "shrink_runs": runs,
                }
            )
            if verbose:
                print(
                    f"seed {seed}: FAIL {report.violations} "
                    f"(shrunk to {len(shrunk.events)} events)",
                    file=sys.stderr,
                )
        elif verbose and (seed - seed0) % 20 == 0:
            print(
                f"seed {seed}: {report.outcome} "
                f"({report.elapsed:.2f}s)",
                file=sys.stderr,
            )
    return {
        "schedules": n,
        "outcomes": outcomes,
        "failures": failures,
        "wall_seconds": round(time.monotonic() - t0, 2),
    }


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m deequ_tpu.resilience.chaos",
        description="deterministic chaos soak over the fault ladder",
    )
    parser.add_argument("--soak", action="store_true", help="run N seeds")
    parser.add_argument("--n", type=int, default=200)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--drift-sim", action="store_true",
        help="deliberately break bit-identity (oracle self-test: every "
        "faulted schedule must FAIL and shrink)",
    )
    parser.add_argument(
        "--replay", type=str, default=None,
        help="replay one schedule fixture (JSON path)",
    )
    parser.add_argument(
        "--worker", action="store_true",
        help="soak worker-seam schedules (fleet scenario: scripted "
        "worker death/stall/rejoin under oracles 1/2/3/fetch/8)",
    )
    parser.add_argument(
        "--load", action="store_true",
        help="soak load-seam schedules (round 15: scripted open-loop "
        "spikes + slow-tenant stalls over the SLO-classed fleet "
        "scenario under oracles 1/2/3/9/10 — exactly-once incl. typed "
        "sheds, no priority inversion)",
    )
    parser.add_argument(
        "--window", action="store_true",
        help="soak window-seam schedules (round 20: late bursts, "
        "disorder spikes, mid-window kill-and-resume and overload "
        "sheds over the three-stream windowed scenario under oracle "
        "11 — exactly-once bit-identical closes, typed late routing, "
        "critical streams never shed)",
    )
    args = parser.parse_args(argv)

    if args.replay:
        with open(args.replay) as f:
            schedule = ChaosSchedule.from_json(f.read())
        report = run_schedule(schedule)
        print(
            json.dumps(
                {
                    "outcome": report.outcome,
                    "violations": report.violations,
                    "elapsed": round(report.elapsed, 3),
                    "injected": [list(t) for t in report.injected],
                }
            )
        )
        return 1 if report.failing else 0

    n = args.n if args.soak else 20
    summary = soak(
        n=n, seed0=args.seed, simulate_drift=args.drift_sim,
        worker=args.worker, load=args.load, window=args.window,
    )
    print(json.dumps(summary, indent=2, default=str))
    if args.drift_sim:
        # self-test mode: every schedule that injected something must
        # have been CAUGHT — zero failures means the oracles went blind
        ok = len(summary["failures"]) > 0
        print(
            "drift-sim: oracles "
            + ("caught the broken ladder" if ok else "MISSED the drift"),
            file=sys.stderr,
        )
        return 0 if ok else 1
    return 1 if summary["failures"] else 0


if __name__ == "__main__":
    import os
    import sys

    code = main()
    sys.stdout.flush()
    sys.stderr.flush()
    # skip interpreter teardown: abandoned watchdog threads (hung-call
    # detection leaves them parked by design) can segfault inside XLA's
    # destructors at exit, turning a clean soak into a bogus nonzero
    os._exit(code)
