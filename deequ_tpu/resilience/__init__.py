"""Resilience layer — retry/backoff, crash-safe persistence, fault
injection, and checkpoint/resume for streaming verification.

deequ's founding philosophy treats metric failure as data
(``tryresult.py``, ``exceptions.py``); this package extends the same
philosophy down to the I/O and streaming layers, where TB-scale runs meet
transient reads, torn writes, and mid-stream crashes:

- :mod:`deequ_tpu.resilience.retry` — ``RetryPolicy`` (exponential
  backoff + jitter + deadline), filesystem/batch-source retry wrappers,
  and the quarantine-aware ``resilient_batches`` iterator;
- :mod:`deequ_tpu.resilience.atomic` — write-temp-fsync-rename plus
  checksum envelopes; corruption surfaces as ``CorruptStateException``;
- :mod:`deequ_tpu.resilience.checkpoint` — periodic persistence of the
  streaming runner's fold stacks so a killed run resumes bit-identically
  from its last checkpoint;
- :mod:`deequ_tpu.resilience.faults` — the deterministic seeded
  fault-injection harness (``FaultInjectingFileSystem``,
  ``FlakyBatchSource``, and the device-fault ``FaultInjectingScanHook``)
  the resilience test suites drive;
- :mod:`deequ_tpu.resilience.governance` — run-level fault governance:
  ``RunPolicy``/``RunBudget`` (one deadline/attempt ledger for the whole
  composed ladder, with graceful degradation to partial results) and
  ``fault_state_scope`` (isolation of the process-wide fault
  singletons);
- :mod:`deequ_tpu.resilience.chaos` — the deterministic chaos engine:
  seeded fault SCHEDULES composing every injector seam into one
  timeline, invariant oracles checked after each run, and a
  delta-debugging shrinker producing minimal replayable reproducers
  (``python -m deequ_tpu.resilience.chaos --soak``).

Device-side fault tolerance (the XLA error taxonomy, OOM chunk
bisection, the CPU fallback, and the compute watchdog) lives in
``deequ_tpu/exceptions.py`` + ``deequ_tpu/ops/device_policy.py`` +
``ops/scan_engine.py:run_scan``; this package supplies its injection
harness and shares the quarantine/checkpoint machinery it composes with.
"""

from deequ_tpu.exceptions import (  # noqa: F401 — canonical home is exceptions
    CorruptStateException,
    DeviceCompileException,
    DeviceException,
    DeviceHangException,
    DeviceLostException,
    DeviceOOMException,
    MeshDegradedException,
    PeerLostException,
    RetryExhaustedException,
    classify_device_error,
    implicated_devices,
)
from deequ_tpu.resilience.atomic import (
    atomic_write_bytes,
    atomic_write_text,
    has_checksum,
    read_checksummed,
    unwrap_checksum,
    wrap_checksum,
)
from deequ_tpu.resilience.checkpoint import (
    StreamCheckpoint,
    StreamCheckpointer,
    run_fingerprint,
)
from deequ_tpu.exceptions import (  # noqa: F401 — canonical home is exceptions
    RunBudgetExhaustedException,
)
from deequ_tpu.resilience.governance import (
    RunBudget,
    RunPolicy,
    charge_run_budget,
    current_run_budget,
    default_max_total_attempts,
    default_run_deadline,
    fault_state_scope,
    resolve_run_policy,
    run_budget_scope,
)
from deequ_tpu.resilience.faults import (
    FaultInjectingFileSystem,
    FaultInjectingScanHook,
    FaultSchedule,
    FlakyBatchSource,
    InjectedDeviceError,
    InjectedIOError,
)
from deequ_tpu.resilience.retry import (
    DEFAULT_IO_RETRY,
    RETRY_TELEMETRY,
    RetryingBatchSource,
    RetryingFileSystem,
    RetryPolicy,
    RetryTelemetry,
    default_retry_policy,
    resilient_batches,
    resolve_retry_policy,
    retry_call,
    set_default_retry_policy,
)

__all__ = [
    "CorruptStateException",
    "DeviceException",
    "DeviceOOMException",
    "DeviceCompileException",
    "DeviceLostException",
    "DeviceHangException",
    "MeshDegradedException",
    "PeerLostException",
    "classify_device_error",
    "implicated_devices",
    "RetryExhaustedException",
    "RunBudgetExhaustedException",
    "RunBudget",
    "RunPolicy",
    "run_budget_scope",
    "current_run_budget",
    "charge_run_budget",
    "resolve_run_policy",
    "default_run_deadline",
    "default_max_total_attempts",
    "fault_state_scope",
    "RETRY_TELEMETRY",
    "RetryTelemetry",
    "RetryPolicy",
    "DEFAULT_IO_RETRY",
    "default_retry_policy",
    "set_default_retry_policy",
    "retry_call",
    "resolve_retry_policy",
    "resilient_batches",
    "RetryingFileSystem",
    "RetryingBatchSource",
    "atomic_write_bytes",
    "atomic_write_text",
    "wrap_checksum",
    "unwrap_checksum",
    "has_checksum",
    "read_checksummed",
    "StreamCheckpoint",
    "StreamCheckpointer",
    "run_fingerprint",
    "FaultSchedule",
    "FaultInjectingFileSystem",
    "FaultInjectingScanHook",
    "FlakyBatchSource",
    "InjectedIOError",
    "InjectedDeviceError",
]
