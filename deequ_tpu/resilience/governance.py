"""Run-level fault governance — ONE budget for the whole composed ladder.

PRs 2/3/5/8 grew a deep resilience ladder (I/O retry -> batch quarantine
-> OOM bisection -> encoded demotion -> mesh reshard -> CPU fallback),
but every rung governs itself: each seam has its own attempt counter and
deadline, and nothing bounds what the COMPOSITION may spend. A run that
hits faults on several seams at once can legally burn minutes in nested
retries while every individual policy stays within its local budget —
exactly what a serving-scale deployment promising p99 latency (the Flare
amortization argument, arXiv:1703.08219) cannot afford.

This module is the one global ledger:

- :class:`RunPolicy` — the value object (``run_deadline`` wall seconds,
  ``max_total_attempts``, ``on_budget_exhausted``); built explicitly,
  via ``VerificationRunBuilder.with_run_budget(...)``, via
  ``run_scan(run_deadline=..., max_total_attempts=...)``, or process-wide
  through ``DEEQU_TPU_RUN_DEADLINE`` / ``DEEQU_TPU_RUN_ATTEMPTS``;
- :class:`RunBudget` — an ARMED policy (start time + charge ledger).
  Every ladder rung charges it: ``resilience/retry.py`` charges failed
  I/O tries, ``ops/scan_engine.py:run_scan`` charges bisections,
  demotions, reshards, and fallback transitions. A clean first try never
  charges — healthy runs spend nothing (the <1% bench contract,
  ``measure_governance_overhead``);
- :func:`run_budget_scope` — the ambient slot the charge sites resolve,
  so a streaming run's hundred per-batch scans all draw on ONE budget
  instead of paying per batch;
- :func:`fault_state_scope` — snapshot/reset/restore of the process-wide
  fault singletons (``DEVICE_HEALTH``, ``MESH_HEALTH``,
  ``RETRY_TELEMETRY``) plus the installed scan fault hook, so chaos runs
  and tests cannot leak quarantine state or counters into each other.

Exhaustion is TYPED and immediate: the first charge past the budget
raises :class:`~deequ_tpu.exceptions.RunBudgetExhaustedException`. Under
``on_budget_exhausted="degrade"`` (default) the verification layers
convert it into a partial result — failure metrics for what could not
finish plus exact ``unverified_row_ranges`` (the PR-5 partial-result
surface) — instead of raising or hanging; ``"raise"`` propagates it.
When the budget carries a wall deadline, ``run_scan`` additionally caps
the device watchdog at the REMAINING budget, so even a hung device call
terminates (typed) within ``run_deadline``.
"""

from __future__ import annotations

import copy
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Optional

from deequ_tpu.exceptions import RunBudgetExhaustedException

#: the two exhaustion policies (mirrors on_batch_error / on_device_error)
_EXHAUST_MODES = ("degrade", "raise")


def default_run_deadline() -> Optional[float]:
    """Process-wide run wall deadline (seconds) from
    ``DEEQU_TPU_RUN_DEADLINE`` (envcfg registry); unset/empty/0 disables
    it, malformed values raise typed ``EnvConfigError`` — a deployment
    that thinks it is governed must not silently run ungoverned."""
    from deequ_tpu.envcfg import env_value

    return env_value("DEEQU_TPU_RUN_DEADLINE")


def default_max_total_attempts() -> Optional[int]:
    """Process-wide attempt budget from ``DEEQU_TPU_RUN_ATTEMPTS``
    (envcfg registry); unset/empty/0 disables it, malformed values raise
    typed."""
    from deequ_tpu.envcfg import env_value

    return env_value("DEEQU_TPU_RUN_ATTEMPTS")


@dataclass(frozen=True)
class RunPolicy:
    """Run-level fault-budget policy (value object; ``arm()`` starts the
    clock). ``max_total_attempts`` bounds FAILURE-driven attempts across
    every rung of the composed ladder — a clean first try is free, the
    same accounting rule RetryTelemetry uses — and ``run_deadline``
    bounds the run's wall clock from arming."""

    run_deadline: Optional[float] = None
    max_total_attempts: Optional[int] = None
    on_budget_exhausted: str = "degrade"

    def __post_init__(self):
        if self.on_budget_exhausted not in _EXHAUST_MODES:
            raise ValueError(
                f"on_budget_exhausted must be one of {_EXHAUST_MODES}, "
                f"got {self.on_budget_exhausted!r}"
            )
        if self.run_deadline is not None and self.run_deadline <= 0:
            raise ValueError("run_deadline must be positive seconds")
        if self.max_total_attempts is not None and self.max_total_attempts < 0:
            raise ValueError("max_total_attempts must be >= 0")

    def arm(self) -> "RunBudget":
        return RunBudget(self)


class RunBudget:
    """One armed RunPolicy: the charge ledger every ladder rung draws on.

    ``charge(kind)`` is the only spending primitive — it increments the
    total and the per-kind ledger, mirrors into
    ``ScanStats.budget_charges``, and raises
    ``RunBudgetExhaustedException`` the moment the total passes
    ``max_total_attempts`` or the wall clock passes ``run_deadline``.
    Once exhausted, EVERY subsequent charge re-raises — a nested retry
    loop that catches the first raise cannot keep spending."""

    def __init__(self, policy: RunPolicy):
        self.policy = policy
        self.started = time.monotonic()
        self.attempts = 0
        self.charges: Dict[str, int] = {}
        self.exhausted_reason: Optional[str] = None

    # -- clock -----------------------------------------------------------

    def elapsed_seconds(self) -> float:
        return time.monotonic() - self.started

    def remaining_seconds(self) -> Optional[float]:
        """Wall budget left (None when no deadline is set; never
        negative). run_scan caps the device watchdog at this, so a hung
        call converts to a typed DeviceHangException before the run is
        past its deadline."""
        if self.policy.run_deadline is None:
            return None
        return max(self.policy.run_deadline - self.elapsed_seconds(), 0.0)

    # -- spending --------------------------------------------------------

    def charge(self, kind: str, **detail) -> None:
        """Spend one attempt of ``kind`` ('io_retry' | 'oom_bisect' |
        'encoded_demote' | 'mesh_reshard' | 'cpu_fallback' |
        'coalesce_retry' | 'worker_failover' | 'deadline_shed' | ...);
        raises typed when this charge exhausts the budget (or it already
        was exhausted)."""
        self.attempts += 1
        self.charges[kind] = self.charges.get(kind, 0) + 1
        try:
            from deequ_tpu.ops.scan_engine import SCAN_STATS

            SCAN_STATS.budget_charges += 1
        except ImportError:  # charge sites can run before the engine loads
            pass
        # flight-recorder seam: every charge is an instant event on the
        # armed recording (the "which rung ate the budget" timeline);
        # disarmed cost is one integer check
        from deequ_tpu.obs.recorder import current_recorder

        rec = current_recorder()
        if rec is not None:
            rec.event(
                "budget_charge", charge_kind=kind, attempts=self.attempts,
            )
        reason = self.exhausted_reason
        if reason is None:
            cap = self.policy.max_total_attempts
            if cap is not None and self.attempts > cap:
                reason = "max_total_attempts"
            elif (
                self.policy.run_deadline is not None
                and self.elapsed_seconds() >= self.policy.run_deadline
            ):
                reason = "run_deadline"
        if reason is not None:
            self._exhaust(reason, kind, detail)

    def _exhaust(self, reason: str, kind: str, detail: dict) -> None:
        first = self.exhausted_reason is None
        self.exhausted_reason = reason
        if first:
            try:
                from deequ_tpu.ops.scan_engine import SCAN_STATS

                SCAN_STATS.budget_exhaustions += 1
            except ImportError:
                pass
        raise RunBudgetExhaustedException(
            reason,
            ledger=self.snapshot(),
            degraded=self.policy.on_budget_exhausted == "degrade",
            detail=(
                f"last charge kind={kind!r} "
                f"attempts={self.attempts}"
                + (
                    f"/{self.policy.max_total_attempts}"
                    if self.policy.max_total_attempts is not None
                    else ""
                )
                + f" elapsed={self.elapsed_seconds():.3f}s"
                + (
                    f"/{self.policy.run_deadline:g}s"
                    if self.policy.run_deadline is not None
                    else ""
                )
            ),
        )

    def snapshot(self) -> dict:
        """Point-in-time ledger copy (lands on
        ``VerificationResult.run_budget``)."""
        return {
            "run_deadline": self.policy.run_deadline,
            "max_total_attempts": self.policy.max_total_attempts,
            "on_budget_exhausted": self.policy.on_budget_exhausted,
            "attempts": self.attempts,
            "charges": dict(self.charges),
            "elapsed_seconds": round(self.elapsed_seconds(), 6),
            "exhausted": self.exhausted_reason,
        }


# -- ambient budget ----------------------------------------------------------

# THREAD-LOCAL, not a module global: concurrent governed runs (the
# serving-layer shape) must not cross-charge each other's ledgers, and a
# watchdog worker ABANDONED by _governed_attempt keeps executing — with
# a global slot its late charges would land on whatever budget a LATER
# run installed. Thread-locality means the zombie keeps charging its own
# (exhausted) ledger, which re-raises and kills it. The cost is that
# budgets don't flow into spawned threads implicitly; the two engine
# seams that run governed work on worker threads (_governed_attempt,
# _prefetch) re-install the budget explicitly via run_budget_scope.
_AMBIENT = threading.local()


def current_run_budget() -> Optional[RunBudget]:
    """This thread's ambient RunBudget (None = ungoverned)."""
    return getattr(_AMBIENT, "budget", None)


@contextmanager
def run_budget_scope(budget: Optional[RunBudget]):
    """Install ``budget`` as the ambient run budget for the block (on
    THIS thread). Every charge site inside — per-batch scans of a
    streaming run, nested retry wrappers, ladder rungs — draws on this
    one ledger. Worker threads spawned inside the block must re-enter
    the scope with the same budget (the engine's governed-attempt and
    prefetch seams do)."""
    prev = getattr(_AMBIENT, "budget", None)
    _AMBIENT.budget = budget
    try:
        yield budget
    finally:
        _AMBIENT.budget = prev


def resolve_run_policy(
    run_deadline: Optional[float] = None,
    max_total_attempts: Optional[int] = None,
    on_budget_exhausted: Optional[str] = None,
) -> Optional[RunPolicy]:
    """Arguments > env vars > ungoverned (None). The resolution every
    governed entry point (run_scan, do_verification_run) applies."""
    deadline = (
        float(run_deadline)
        if run_deadline is not None
        else default_run_deadline()
    )
    attempts = (
        int(max_total_attempts)
        if max_total_attempts is not None
        else default_max_total_attempts()
    )
    if on_budget_exhausted is not None:
        mode = on_budget_exhausted
    else:
        from deequ_tpu.envcfg import env_value

        mode = env_value("DEEQU_TPU_ON_BUDGET_EXHAUSTED")
    if deadline is None and attempts is None:
        if on_budget_exhausted is not None:
            raise ValueError(
                "on_budget_exhausted was set without a budget to govern: "
                "pass run_deadline and/or max_total_attempts"
            )
        return None
    return RunPolicy(
        run_deadline=deadline,
        max_total_attempts=attempts,
        on_budget_exhausted=mode,
    )


def charge_run_budget(kind: str, **detail) -> None:
    """Charge the ambient budget, if any (the retry layer's one-liner)."""
    budget = current_run_budget()
    if budget is not None:
        budget.charge(kind, **detail)


def try_charge(budget: Optional[RunBudget], kind: str, **detail) -> bool:
    """Charge ``budget`` (None = ungoverned, a no-op) swallowing
    exhaustion: the serving admission tier's shape — a request being
    SHED (``kind="deadline_shed"``: its in-queue deadline expired, or a
    fleet failover found it expired) is already getting a typed terminal
    outcome, so the charge is ledger bookkeeping, not control flow — an
    exhausted budget must not replace the shed's
    ``DeadlineExceededException`` with a budget error. Returns False
    when the charge exhausted (or found exhausted) the budget."""
    if budget is None:
        return True
    try:
        budget.charge(kind, **detail)
        return True
    except RunBudgetExhaustedException:
        return False


def run_budget_remaining() -> Optional[float]:
    """Ambient wall budget left, or None (no budget / no deadline) —
    backoff sleeps cap themselves at this so a retry loop cannot sleep
    past the run deadline."""
    budget = current_run_budget()
    if budget is None:
        return None
    return budget.remaining_seconds()


# -- fault-state isolation ---------------------------------------------------


@contextmanager
def fault_state_scope(reset: bool = True):
    """Isolate the process-wide fault singletons for the block.

    Snapshots ``DEVICE_HEALTH`` / ``MESH_HEALTH`` (ops/device_policy.py)
    and ``RETRY_TELEMETRY`` (resilience/retry.py), plus the installed
    scan fault hook; with ``reset=True`` (default) the hook is removed
    and each singleton starts the block fresh (``reset=False`` keeps
    the current hook and counters live and merely guarantees the
    restore). On exit everything is restored bit-for-bit — the
    snapshot is a DEEP copy, so in-place mutation of e.g.
    ``MESH_HEALTH.consecutive_faults`` inside the block cannot leak
    out. A chaos run (or a test) can quarantine chips, trip breakers,
    and exhaust retries without leaking any of it into the next run;
    this replaces the ad hoc monkeypatching the fault suites
    previously needed."""
    from deequ_tpu.ops.device_policy import (
        DEVICE_HEALTH,
        MESH_HEALTH,
        current_scan_fault_hook,
        install_scan_fault_hook,
    )
    from deequ_tpu.resilience.retry import RETRY_TELEMETRY

    singletons = (DEVICE_HEALTH, MESH_HEALTH, RETRY_TELEMETRY)
    # plain-data state (ints/floats/strs/dicts): deepcopy is safe and
    # makes the snapshot immune to in-place mutation during the block
    saved = [(obj, copy.deepcopy(obj.__dict__)) for obj in singletons]
    prev_hook = current_scan_fault_hook()
    if reset:
        install_scan_fault_hook(None)
        for obj in singletons:
            obj.reset()
    try:
        yield
    finally:
        install_scan_fault_hook(prev_hook)
        for obj, state in saved:
            obj.__dict__.clear()
            obj.__dict__.update(state)
